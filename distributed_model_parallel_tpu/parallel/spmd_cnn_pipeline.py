"""Single-program SPMD pipeline for *heterogeneous* staged CNNs.

``parallel/spmd_pipeline.py`` pipelines homogeneous stacked Transformer
blocks over a ``stage`` mesh axis; this module gives the reference's
centerpiece workload — the staged MobileNetV2 pipeline
(``model_parallel.py:99-157``) — the same multi-host-capable path. The
single-controller ``PipelineRunner`` (parallel/pipeline.py) dispatches one
program per stage from one Python process, which cannot span hosts; here the
whole step is ONE ``shard_map`` program over the mesh, so it rides ICI/DCN
like any pjit program.

Heterogeneous stages break the two assumptions the Transformer pipeline
leans on, and this module replaces them:

* **Per-stage compute differs** (different units, different parameter
  shapes), so there is no stacked-blocks scan to shard. Instead every
  device holds the full (replicated) parameter tuple and applies only its
  own stage via ``lax.switch`` on ``axis_index(stage)`` — stage-indexed
  dispatch. Parameter memory is not sharded by stage; for the CNN zoo
  (3-25M params) that trade is negligible, and gradients still flow only
  through each device's own stage (the shard_map transpose psums the
  per-stage contributions back together).
* **Activation shapes differ per boundary** (CNN downsampling), and
  ``ppermute`` needs one static shape. Activations hop in a padded flat
  buffer ``[microbatch, max_boundary_elems]``; each stage unpacks its
  static input shape from the front and packs its output back.

Schedule: round-robin GPipe over ``M`` microbatches in ``M + S - 1`` ticks
(same recurrence as spmd_pipeline.py — at tick ``t`` stage ``s`` holds
microbatch ``t - s``; bubbles compute on finite zero-fill garbage that is
masked out of outputs and batch stats).

BatchNorm semantics: every microbatch observes the same pre-step running
stats; the M per-microbatch EMA states are pooled with the law-of-total-
variance correction (``merge_microbatch_bn_states``, the pooling the
single-controller pipeline trainer uses), so the updated stats match the
equivalent big-batch forward exactly. Under a ``data`` axis > 1 each shard
normalizes by its local moments (per-replica BN, the parallel/ddp.py
convention) and the running stats are pooled across shards with the same
correction — equal shard sizes make that pooling exact as well.
"""

from __future__ import annotations

import math
from collections.abc import Mapping
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from distributed_model_parallel_tpu.mesh import MeshSpec
from distributed_model_parallel_tpu.models.staged import StagedModel, stage_slices
from distributed_model_parallel_tpu.parallel.pipeline import (
    merge_microbatch_bn_states,
)


def boundary_shapes(model: StagedModel, params, state,
                    mbs: int, feat_shape: Sequence[int],
                    slices: Sequence[tuple[int, int]]) -> list[tuple[int, ...]]:
    """Static activation shape entering each stage (index s) plus the final
    output (index S) for one microbatch of ``mbs`` samples, via eval_shape
    (no FLOPs, no transfers)."""
    shapes = []
    aval: Any = jax.ShapeDtypeStruct((mbs, *feat_shape), jnp.float32)
    for lo, hi in slices:
        shapes.append(tuple(aval.shape))
        aval = jax.eval_shape(
            lambda x, lo=lo, hi=hi: model.apply_range(
                params, state, x, lo, hi, train=True)[0], aval)
    shapes.append(tuple(aval.shape))
    return shapes


def _pool_bn_over_axis(state, axis, momentum: float):
    """Pool per-data-shard EMA'd BN states across mesh axis ``axis`` into
    the stats the pooled batch would have produced (law of total variance
    across equal-sized shards; same derivation as
    ``merge_microbatch_bn_states`` with pmean replacing the stack-mean)."""
    one_minus = 1.0 - momentum

    def rec(node):
        if isinstance(node, Mapping):
            out = {}
            for k in node:
                if k == "var" and "mean" in node:
                    var_p = jax.lax.pmean(node["var"], axis)
                    if one_minus == 0.0:
                        out[k] = var_p
                        continue
                    m = node["mean"]
                    between = jax.lax.pmean(m * m, axis) - jax.lax.pmean(m, axis) ** 2
                    # EMA'd means differ by (1-mu)*shard_mean, so the pooled
                    # variance needs Var_shards(new_mean)/(1-mu).
                    out[k] = var_p + between / one_minus
                else:
                    out[k] = rec(node[k])
            return out if isinstance(node, dict) else type(node)(out)
        if isinstance(node, (tuple, list)):
            return type(node)(rec(x) for x in node)
        return jax.lax.pmean(node, axis)

    return rec(state)


def make_cnn_pipeline_apply(model: StagedModel, spec: MeshSpec, *,
                            sample_shape: Sequence[int],
                            num_microbatches: int = 1,
                            boundaries: Sequence[int] | None = None,
                            bn_momentum: float = 0.9,
                            init_params=None, init_state=None,
                            stage_dispatch: str = "switch",
                            dtype=jnp.float32) -> Callable:
    """Returns ``pipeline(params, state, x) -> (logits, new_state)`` — a
    shard_map'd GPipe forward over the ``stage`` axis for a heterogeneous
    ``StagedModel``.

    ``params``/``state`` are the full per-unit tuples, replicated over the
    mesh; ``x`` is the normalized global batch ``[B, H, W, C]`` sharded over
    ``data``. ``sample_shape`` fixes the boundary shapes (it must match the
    fed batch's trailing dims). ``init_params``/``init_state`` seed the
    eval_shape boundary probe; any correctly-structured tree works, so they
    default to a fresh ``model.init``.

    ``stage_dispatch`` picks how a device selects its stage's compute:

    * ``"switch"`` (default): ``lax.switch`` on ``axis_index(stage)`` —
      each device executes exactly its own stage's ops per tick. The
      right choice on TPU.
    * ``"masked"``: every device computes ALL stages' branches and
      ``select_n``s its own — S× the compute, but no conditionals. The
      XLA *CPU* backend runs conditional bodies without intra-op thread
      parallelism, which makes conv backward passes inside ``switch``
      ~35× slower (measured: a 6-deep depthwise-conv grad at 250 s vs
      7 s plain), so virtual-device CPU testing wants this mode.
      Numerics are identical (parity-tested).
    """
    S = spec.num_stages
    M = num_microbatches
    stage_axis = spec.stage_axis
    slices = stage_slices(model.num_units, S, boundaries)
    owner = [s for s, (lo, hi) in enumerate(slices) for _ in range(lo, hi)]
    if stage_dispatch not in ("switch", "masked"):
        raise ValueError(f"unknown stage_dispatch {stage_dispatch!r}; "
                         f"expected 'switch' or 'masked'")

    if init_params is None or init_state is None:
        init_params, init_state = model.init(
            jax.random.key(0), jnp.zeros((1, *sample_shape[1:]), dtype))

    def pipeline(params, state, x):
        b_local = x.shape[0] // spec.num_data
        if b_local % M:
            raise ValueError(f"per-shard batch {b_local} not divisible by "
                             f"num_microbatches={M}")
        mbs = b_local // M
        shapes = boundary_shapes(model, init_params, init_state, mbs,
                                 x.shape[1:], slices)
        feat_sizes = [math.prod(sh[1:]) for sh in shapes]
        max_feat = max(feat_sizes)
        out_shape = shapes[-1]

        def pack(y):
            flat = y.reshape(mbs, -1).astype(dtype)
            return jnp.zeros((mbs, max_feat), dtype).at[
                :, :flat.shape[1]].set(flat)

        def make_branch(si):
            lo, hi = slices[si]

            def branch(params, state, buf):
                xin = buf[:, :feat_sizes[si]].reshape(shapes[si])
                y, new_sub = model.apply_range(params, state, xin, lo, hi,
                                               train=True)
                full = tuple(new_sub[i - lo] if lo <= i < hi else state[i]
                             for i in range(model.num_units))
                return pack(y), full

            return branch

        branches = [make_branch(si) for si in range(S)]

        def stage_fn(params, state, x_local):
            s = jax.lax.axis_index(stage_axis)
            mb = x_local.reshape(M, mbs, *x_local.shape[1:])
            buf = jnp.zeros((mbs, max_feat), dtype)
            outputs = jnp.zeros((M, *out_shape), dtype)
            tick_states = []
            perm = [(i, (i + 1) % S) for i in range(S)]

            def dispatch(buf):
                if stage_dispatch == "switch":
                    return jax.lax.switch(s, branches, params, state, buf)
                outs = [br(params, state, buf) for br in branches]
                sel = lambda *leaves: jax.lax.select_n(s, *leaves)
                return (sel(*[o[0] for o in outs]),
                        jax.tree.map(sel, *[o[1] for o in outs]))

            for tick in range(M + S - 1):       # static unroll
                if tick < M:                    # stage 0 injects
                    buf = jnp.where(s == 0, pack(mb[tick]), buf)
                buf, tick_state = dispatch(buf)
                tick_states.append(tick_state)
                out_idx = tick - (S - 1)
                if 0 <= out_idx < M:            # last stage emits
                    y = buf[:, :feat_sizes[-1]].reshape(out_shape)
                    outputs = outputs.at[out_idx].set(
                        jnp.where(s == S - 1, y, outputs[out_idx]))
                if S > 1:
                    buf = jax.lax.ppermute(buf, stage_axis, perm)

            # Collect the logits on every stage so the (replicated) loss
            # sees them.
            outputs = jax.lax.psum(
                jnp.where(s == S - 1, outputs, jnp.zeros_like(outputs)),
                stage_axis)

            # Stage s's M real ticks are [s, s+M): gather those BN states,
            # pool them microbatch-wise, then keep each unit's pooled state
            # from its owning stage only.
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *tick_states)
            mine = jax.tree.map(
                lambda leaf: jnp.take(leaf, s + jnp.arange(M), axis=0),
                stacked)
            micro = [jax.tree.map(lambda leaf, m=m: leaf[m], mine)
                     for m in range(M)]
            merged = merge_microbatch_bn_states(micro, momentum=bn_momentum)
            new_state = tuple(
                jax.tree.map(
                    lambda new, old, si=i: jax.lax.psum(
                        jnp.where(s == owner[si], new,
                                  jnp.zeros_like(new)), stage_axis),
                    merged[i], state[i])
                for i in range(model.num_units))
            if spec.num_data > 1:
                new_state = _pool_bn_over_axis(new_state, spec.data_axis,
                                               bn_momentum)
            return outputs.reshape(b_local, *out_shape[1:]), new_state

        x_spec = P(spec.data_axis)
        return jax.shard_map(
            stage_fn, mesh=spec.mesh,
            in_specs=(P(), P(), x_spec),
            out_specs=(x_spec, P()),
            check_vma=False)(params, state, x)

    return pipeline


def make_cnn_1f1b_fwd_bwd(model: StagedModel, spec: MeshSpec, *,
                          sample_shape: Sequence[int],
                          num_microbatches: int = 1,
                          boundaries: Sequence[int] | None = None,
                          bn_momentum: float = 0.9,
                          init_params=None, init_state=None,
                          stage_dispatch: str = "switch",
                          virtual_stages: int = 1,
                          dtype=jnp.float32) -> Callable:
    """Hand-scheduled 1F1B for the heterogeneous CNN pipeline:
    ``fwd_bwd(params, state, x, labels) -> (loss, logits, new_state, grads)``
    as one shard_map program.

    Same schedule as the Transformer's ``make_1f1b_loss_and_grad``
    (parallel/spmd_pipeline.py — warmup / lax.scan steady state / drain,
    stash ring of padded boundary buffers, backward recomputed from
    the stash), transplanted onto this module's heterogeneous machinery:
    chunk-indexed ``lax.switch`` dispatch, padded flat activation hops,
    and per-tick BN state collection with the GPipe path's exact pooling.
    The memory story is the flat-in-M scan carry instead of GPipe's
    all-M-microbatches residual liveness (benchmarks/pipeline_memory.json).

    ``virtual_stages = V > 1`` is the Megatron interleaved placement: the
    model splits into ``D = V*S`` chunks, device ``s`` owning chunks
    ``s, S+s, …`` — the same mixed-radix fine-tick schedule as the
    Transformer engine (at forward tick ``ft`` device ``s`` decodes
    ``u = ft - s`` into (rank, chunk, group); the (S-1)->0 chunk
    wraparound rides the same +1 ppermute ring; requires ``M % S == 0``).
    Unlike the Transformer engine no parameter relayout is needed —
    params are replicated, so chunk c's units are just ``slices[c]``.

    Gradient bookkeeping is simpler than the Transformer's: params are
    replicated and the branches contain no collectives, so per-device
    grads are plain partials — masked by each tick's reality and summed
    over (data, stage) at the end. Meshes with model/seq/expert axes > 1
    are rejected (no CNN strategy uses them; their replicated compute
    would double-count under that sum).
    """
    S = spec.num_stages
    V = virtual_stages
    D = S * V
    M = num_microbatches
    stage_axis = spec.stage_axis
    mesh = spec.mesh
    for ax in (spec.model_axis, spec.seq_axis, spec.expert_axis):
        if mesh.shape[ax] > 1:
            raise ValueError(
                f"cnn 1f1b supports data x stage meshes only; axis "
                f"{ax!r} has size {mesh.shape[ax]}")
    if V < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {V}")
    if V > 1 and M % S:
        raise ValueError(
            f"interleaved schedule needs num_microbatches divisible by "
            f"the stage count: M={M}, S={S} (Megatron constraint)")
    slices = stage_slices(model.num_units, D, boundaries)
    # Unit -> owning chunk; the owning DEVICE is chunk % S.
    owner = [c for c, (lo, hi) in enumerate(slices) for _ in range(lo, hi)]
    if stage_dispatch not in ("switch", "masked"):
        raise ValueError(f"unknown stage_dispatch {stage_dispatch!r}; "
                         f"expected 'switch' or 'masked'")
    if init_params is None or init_state is None:
        init_params, init_state = model.init(
            jax.random.key(0), jnp.zeros((1, *sample_shape[1:]), dtype))
    K = min(2 * D - 1, M * V + D - 1)

    def _flat(entry):
        return list(entry) if isinstance(entry, (tuple, list)) else [entry]

    data_axes = [a for a in _flat(spec.data_axis) if mesh.shape[a] > 1]
    reduce_axes = tuple(data_axes + ([stage_axis] if S > 1 else []))
    n_data = spec.num_data          # covers the dcn x ici split

    def fwd_bwd(params, state, x, labels):
        b_local = x.shape[0] // spec.num_data
        if b_local % M:
            raise ValueError(f"per-shard batch {b_local} not divisible by "
                             f"num_microbatches={M}")
        mbs = b_local // M
        shapes = boundary_shapes(model, init_params, init_state, mbs,
                                 x.shape[1:], slices)
        feat_sizes = [math.prod(sh[1:]) for sh in shapes]
        max_feat = max(feat_sizes)
        out_shape = shapes[-1]
        b_global = b_local * n_data

        def pack(y):
            flat = y.reshape(mbs, -1).astype(dtype)
            return jnp.zeros((mbs, max_feat), dtype).at[
                :, :flat.shape[1]].set(flat)

        def make_branch(si):
            lo, hi = slices[si]

            def branch(params, buf):
                xin = buf[:, :feat_sizes[si]].reshape(shapes[si])
                y, new_sub = model.apply_range(params, state, xin, lo, hi,
                                               train=True)
                full = tuple(new_sub[i - lo] if lo <= i < hi else state[i]
                             for i in range(model.num_units))
                return pack(y), full

            return branch

        def stage_fn(params, state, x_local, lab_local):
            s = jax.lax.axis_index(stage_axis)
            branches = [make_branch(si) for si in range(D)]
            mb = x_local.reshape(M, mbs, *x_local.shape[1:])
            lab_mb = lab_local.reshape(M, mbs)
            perm_fwd = [(i, (i + 1) % S) for i in range(S)]
            perm_bwd = [(i, (i - 1) % S) for i in range(S)]

            def dispatch(params_, buf, c):
                """Run chunk ``c``'s units (c = v*S + s; V=1: c = s)."""
                if stage_dispatch == "switch":
                    return jax.lax.switch(c, branches, params_, buf)
                outs = [br(params_, buf) for br in branches]
                sel = lambda *leaves: jax.lax.select_n(c, *leaves)
                return (sel(*[o[0] for o in outs]),
                        jax.tree.map(sel, *[o[1] for o in outs]))

            def buf_only(params_, buf, c):
                return dispatch(params_, buf, c)[0]

            def fwd_slot(ft, state_f, stash):
                u = jnp.asarray(ft) - s
                v = jnp.mod(u // S, V)
                m = (u // D) * S + jnp.mod(u, S)
                real_f = jnp.logical_and(
                    u >= 0, jnp.logical_and(m >= 0, m < M))
                xmb = jax.lax.dynamic_index_in_dim(
                    mb, jnp.clip(m, 0, M - 1), 0, keepdims=False)
                inject = jnp.logical_and(
                    real_f, jnp.logical_and(s == 0, v == 0))
                state_f = jnp.where(inject, pack(xmb), state_f)
                stash = jax.lax.dynamic_update_index_in_dim(
                    stash, state_f, jnp.mod(jnp.asarray(ft), K), 0)
                state_f, tick_state = dispatch(params, state_f, v * S + s)
                return state_f, stash, tick_state

            def bwd_slot(bt, dy, state_b, stash, g_params):
                u_b = jnp.asarray(bt) - (S - 1 - s)
                q = jnp.mod(u_b // S, V)
                m_b = (u_b // D) * S + jnp.mod(u_b, S)
                real_b = jnp.logical_and(
                    u_b >= 0, jnp.logical_and(m_b >= 0, m_b < M))
                cot_in = state_b
                if dy is not None:
                    cot_in = jnp.where(
                        jnp.logical_and(s == S - 1, q == 0), dy, cot_in)
                c_hat = q * S + (S - 1 - s)
                slot = jnp.mod(jnp.asarray(bt) + (D - 1) - 2 * c_hat, K)
                x_in = jax.lax.dynamic_index_in_dim(stash, slot, axis=0,
                                                    keepdims=False)
                c_b = (V - 1 - q) * S + s
                _, stage_vjp = jax.vjp(
                    lambda p_, b_: buf_only(p_, b_, c_b), params, x_in)
                g_p, dbuf = stage_vjp(cot_in)
                g_params = jax.tree.map(
                    lambda g, d: g + jnp.where(real_b, d, 0),
                    g_params, g_p)
                state_b = dbuf
                if S > 1:
                    state_b = jax.lax.ppermute(state_b, stage_axis,
                                               perm_bwd)
                return state_b, g_params

            state_f = jnp.zeros((mbs, max_feat), dtype)
            state_b = jnp.zeros((mbs, max_feat), dtype)
            stash = jnp.zeros((K, mbs, max_feat), dtype)
            loss_acc = jnp.zeros((), jnp.float32)
            g_params = jax.tree.map(jnp.zeros_like, params)

            warm_states = []
            for ft in range(D - 1):
                state_f, stash, tick_state = fwd_slot(ft, state_f, stash)
                warm_states.append(tick_state)
                if S > 1:
                    state_f = jax.lax.ppermute(state_f, stage_axis,
                                               perm_fwd)

            def steady_tick(carry, i):
                state_f, state_b, stash, loss_acc, g_params = carry
                ft = i + (D - 1)
                state_f, stash, tick_state = fwd_slot(ft, state_f, stash)
                # Head: real when the last device just ran a LAST-chunk
                # (v == V-1) execution of a real microbatch.
                u_l = jnp.asarray(ft) - (S - 1)
                m_head = (u_l // D) * S + jnp.mod(u_l, S)
                head_real = jnp.logical_and(
                    s == S - 1,
                    jnp.logical_and(jnp.mod(u_l // S, V) == V - 1,
                                    jnp.logical_and(m_head >= 0,
                                                    m_head < M)))
                lab_i = jax.lax.dynamic_index_in_dim(
                    lab_mb, jnp.clip(m_head, 0, M - 1), 0, keepdims=False)

                def head(buf):
                    logits = buf[:, :feat_sizes[-1]].reshape(out_shape)
                    nll = optax.softmax_cross_entropy_with_integer_labels(
                        logits.astype(jnp.float32), lab_i).sum()
                    return nll, logits

                nll, head_vjp, logits_i = jax.vjp(head, state_f,
                                                  has_aux=True)
                loss_acc = loss_acc + jnp.where(head_real, nll, 0.0)
                dbuf, = head_vjp(jnp.ones((), jnp.float32))
                dy = jnp.where(head_real, dbuf, jnp.zeros_like(dbuf))
                state_b, g_params = bwd_slot(i, dy, state_b, stash,
                                             g_params)
                if S > 1:
                    state_f = jax.lax.ppermute(state_f, stage_axis,
                                               perm_fwd)
                return ((state_f, state_b, stash, loss_acc, g_params),
                        (tick_state, jnp.where(head_real, logits_i,
                                               jnp.zeros_like(logits_i))))

            carry = (state_f, state_b, stash, loss_acc, g_params)
            carry, (steady_states, logits_all) = jax.lax.scan(
                steady_tick, carry, jnp.arange(M * V))
            state_f, state_b, stash, loss_acc, g_params = carry

            for bt in range(M * V, M * V + D - 1):
                state_b, g_params = bwd_slot(bt, None, state_b, stash,
                                             g_params)

            # BN pooling — identical to the GPipe path: stack all tick
            # states in tick order; chunk v*S+s's real executions of
            # microbatch m land at fine tick s + (m%S) + (m//S)*S*V + v*S,
            # so take one M-window per owned chunk, pool microbatch-wise,
            # and keep each unit's pooled state from its owning chunk's
            # device. V=1 reduces to the old [s, s+M) window.
            if warm_states:
                warm_stack = jax.tree.map(lambda *xs: jnp.stack(xs),
                                          *warm_states)
                stacked = jax.tree.map(
                    lambda w, st: jnp.concatenate([w, st], axis=0),
                    warm_stack, steady_states)
            else:
                stacked = steady_states
            m_off = ((jnp.arange(M) // S) * (S * V)
                     + jnp.mod(jnp.arange(M), S))       # group stride
            merged_per_v = []
            for v in range(V):
                idx_v = s + v * S + m_off               # [M] tick indices
                mine = jax.tree.map(
                    lambda leaf, iv=idx_v: jnp.take(leaf, iv, axis=0),
                    stacked)
                micro = [jax.tree.map(lambda leaf, m=m: leaf[m], mine)
                         for m in range(M)]
                merged_per_v.append(
                    merge_microbatch_bn_states(micro, momentum=bn_momentum))
            new_state = tuple(
                jax.tree.map(
                    lambda new, old, si=i: jax.lax.psum(
                        jnp.where(s == owner[si] % S, new,
                                  jnp.zeros_like(new)), stage_axis),
                    merged_per_v[owner[i] // S][i], state[i])
                for i in range(model.num_units))
            if spec.num_data > 1:
                new_state = _pool_bn_over_axis(new_state, spec.data_axis,
                                               bn_momentum)

            # logits: steady tick (m//S)*S*V + m%S emitted microbatch m's
            # logits (zero-masked off the head ticks); select the M real
            # ticks in microbatch order, then fill across stages.
            logits_sel = jnp.take(logits_all, m_off, axis=0)
            logits_out = jax.lax.psum(
                jnp.where(s == S - 1, logits_sel,
                          jnp.zeros_like(logits_sel)), stage_axis)
            logits_out = logits_out.reshape(b_local, *out_shape[1:])

            loss = (jax.lax.psum(loss_acc, reduce_axes) if reduce_axes
                    else loss_acc) / b_global
            grads = jax.tree.map(
                lambda g: ((jax.lax.psum(g, reduce_axes) if reduce_axes
                            else g) / b_global).astype(g.dtype), g_params)
            return loss, logits_out, new_state, grads

        x_spec = P(spec.data_axis)
        return jax.shard_map(
            stage_fn, mesh=mesh,
            in_specs=(P(), P(), x_spec, x_spec),
            out_specs=(P(), x_spec, P(), P()),
            check_vma=False)(params, state, x, labels)

    return fwd_bwd


def make_spmd_cnn_train_step(model: StagedModel, spec: MeshSpec,
                             tx: optax.GradientTransformation, *,
                             sample_shape: Sequence[int], mean, std,
                             num_microbatches: int = 1,
                             boundaries: Sequence[int] | None = None,
                             bn_momentum: float = 0.9,
                             augment: bool = True,
                             resize_to: int | None = None,
                             stage_dispatch: str = "switch",
                             schedule: str = "gpipe",
                             virtual_stages: int = 1,
                             dtype=jnp.float32) -> Callable:
    """One SPMD training step for a staged CNN pipelined over ``stage``.

    ``step(state, rng, images_u8, labels) -> (state, metrics)`` with the
    same preprocessing, loss, and metric conventions as
    ``train.trainer.make_train_step`` (so the strategies stay
    loss-comparable), but the forward/backward runs through the shard_map
    GPipe pipeline. A single global optimizer steps the whole parameter
    tuple — equivalent to the reference's per-stage independent optimizers
    for any per-leaf transform like SGD (``model_parallel.py:105,131,146``),
    and parity-tested against ``PipelineRunner``.
    """
    # Late imports: trainer imports this module's sibling package; keep the
    # dependency one-way at import time.
    from distributed_model_parallel_tpu.data.loader import (
        augment_batch,
        normalize,
        resize_batch,
    )
    from distributed_model_parallel_tpu.train.metrics import topk_correct
    from distributed_model_parallel_tpu.train.trainer import (
        TrainState,
        cross_entropy,
    )

    if schedule == "1f1b":
        fwd_bwd = make_cnn_1f1b_fwd_bwd(
            model, spec, sample_shape=sample_shape,
            num_microbatches=num_microbatches, boundaries=boundaries,
            bn_momentum=bn_momentum, stage_dispatch=stage_dispatch,
            virtual_stages=virtual_stages, dtype=dtype)

        def loss_and_grad(params, model_state, images, labels):
            loss, logits, new_state, grads = fwd_bwd(params, model_state,
                                                     images, labels)
            return loss, logits, new_state, grads
    elif schedule == "gpipe":
        if virtual_stages != 1:
            raise ValueError(
                "interleaved virtual stages are a 1f1b schedule feature "
                "(gpipe's whole-program AD would gain nothing — no "
                "silent ignores)")
        pipeline = make_cnn_pipeline_apply(
            model, spec, sample_shape=sample_shape,
            num_microbatches=num_microbatches, boundaries=boundaries,
            bn_momentum=bn_momentum, stage_dispatch=stage_dispatch,
            dtype=dtype)

        def loss_fn(params, model_state, images, labels):
            logits, new_state = pipeline(params, model_state, images)
            return cross_entropy(logits, labels), (logits, new_state)

        def loss_and_grad(params, model_state, images, labels):
            (loss, (logits, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, model_state, images, labels)
            return loss, logits, new_state, grads
    else:
        raise ValueError(f"unknown spmd cnn pipeline schedule {schedule!r}; "
                         f"known: gpipe, 1f1b")

    def step(state: TrainState, rng: jax.Array, images_u8, labels):
        if resize_to is not None:
            images_u8 = resize_batch(images_u8, resize_to)
        images_u8 = augment_batch(rng, images_u8) if augment else images_u8
        images = normalize(images_u8, mean, std, dtype)
        loss, logits, new_model_state, grads = loss_and_grad(
            state.params, state.model_state, images, labels)
        updates, new_opt_state = tx.update(grads, state.opt_state,
                                           state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {"loss": loss,
                   "batch": jnp.asarray(labels.shape[0], jnp.float32),
                   **topk_correct(logits, labels)}
        return (TrainState(step=state.step + 1, params=new_params,
                           model_state=new_model_state,
                           opt_state=new_opt_state), metrics)

    return step
