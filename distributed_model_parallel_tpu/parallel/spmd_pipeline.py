"""Single-program SPMD pipeline: ``shard_map`` + ``ppermute`` over a ``stage``
mesh axis.

This is the multi-host-capable counterpart of ``parallel/pipeline.py``'s
single-controller runtime: the whole training step — embed, S pipeline
stages, LM head, loss, backward, optimizer — is ONE jitted SPMD program over
the mesh, so it scales over ICI/DCN exactly like any pjit program (the way
the reference's per-process NCCL ring never could without its hand-rolled
wire protocol, ``distributed_layers.py:7-62``).

Schedule: round-robin GPipe over ``M`` microbatches and ``S`` stages in
``M + S - 1`` ticks. Stage 0 injects microbatch ``t`` at tick ``t``; every
stage applies its local stacked blocks (a ``lax.scan``); activations hop one
stage per tick via ``ppermute``; the last stage emits microbatch ``t-S+1``.
Bubbles are real compute on garbage data — the price of SPMD pipelining —
shrinking relatively as M grows. Composes with ``data`` (batch sharding),
``model`` (Megatron TP inside the block via psum) and ``seq`` (ring
attention) axes in the same shard_map.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.mesh import MeshSpec
from distributed_model_parallel_tpu.models import transformer as tfm
from distributed_model_parallel_tpu.parallel.tensor_parallel import (
    block_specs,
    kv_heads_shardable,
    param_specs,
)


def make_pipeline_apply(cfg: tfm.TransformerConfig, spec: MeshSpec,
                        num_microbatches: int) -> Callable:
    """Returns pipeline_blocks(blocks, x) -> (y, aux), a shard_map'd function.

    blocks leaves are [L, ...] sharded over ``stage`` on dim 0; x is
    [B, T, d] sharded over ``data`` (and ``seq`` if sequence parallel).
    ``aux`` is the mean per-layer MoE load-balance loss over real
    microbatches (0 for dense models).
    """
    S = spec.num_stages
    M = num_microbatches
    stage_axis = spec.stage_axis
    axes = spec.mesh.axis_names

    def stage_fn(blocks_local, x_local):
        s = jax.lax.axis_index(stage_axis)
        b, t, d = x_local.shape
        if b % M:
            raise ValueError(f"local batch {b} not divisible by M={M}")
        mbs = b // M
        mb = x_local.reshape(M, mbs, t, d)
        state = jnp.zeros((mbs, t, d), x_local.dtype)
        outputs = jnp.zeros((M, mbs, t, d), x_local.dtype)
        aux_sum = jnp.zeros((tfm.AUX_STATS,), jnp.float32)
        perm = [(i, (i + 1) % S) for i in range(S)]

        for tick in range(M + S - 1):           # static unroll
            if tick < M:                        # stage 0 injects microbatch
                state = jnp.where(s == 0, mb[tick], state)
            state, aux = tfm.blocks_scan(blocks_local, state, cfg)
            # At tick t, stage s holds microbatch t-s; bubble ticks
            # (t-s outside [0, M)) run on garbage activations, so their
            # aux is masked out. Logits are unaffected (aux never feeds
            # the forward value).
            real = jnp.logical_and(tick - s >= 0, tick - s < M)
            aux_sum = aux_sum + jnp.where(real, aux, 0.0)
            out_idx = tick - (S - 1)
            if 0 <= out_idx < M:                # last stage emits
                outputs = outputs.at[out_idx].set(
                    jnp.where(s == S - 1, state, outputs[out_idx]))
            if S > 1:
                state = jax.lax.ppermute(state, stage_axis, perm)

        # Broadcast the collected outputs from the last stage to every stage
        # so the (replicated-over-stage) head/loss sees them.
        outputs = jax.lax.psum(
            jnp.where(s == S - 1, outputs, jnp.zeros_like(outputs)),
            stage_axis)
        # Mean over stages x microbatches; pmean over every mesh axis so the
        # result is replicated (aux differs per data/seq shard before this).
        aux_mean = jax.lax.pmean(aux_sum / M, tuple(axes))
        return outputs.reshape(b, t, d), aux_mean

    seq = spec.seq_axis if cfg.sp_axis else None
    x_spec = P(spec.data_axis, seq, None)
    return jax.shard_map(
        stage_fn, mesh=spec.mesh,
        in_specs=(block_specs(stage_axis, cfg.tp_axis,
                              moe=bool(cfg.moe_experts),
                              ep_axis=cfg.ep_axis, gqa=cfg.gqa,
                              shard_kv=kv_heads_shardable(cfg, spec)),
                  x_spec),
        out_specs=(x_spec, P()),
        check_vma=False)


def _flat_axis_names(*entries) -> list[str]:
    """Flatten axis-name entries (str | tuple | None) into a list."""
    out: list[str] = []
    for e in entries:
        if e is None:
            continue
        if isinstance(e, (tuple, list)):
            out.extend(e)
        else:
            out.append(e)
    return out


def _spec_axes(ps: P) -> set[str]:
    """Mesh axis names a PartitionSpec shards over."""
    out: set[str] = set()
    for entry in ps:
        out.update(_flat_axis_names(entry))
    return out


def _interleave_perm(n_layers: int, S: int, V: int):
    """Canonical→storage layer permutation for interleaved placement.

    Chunk ``c = v*S + s`` (layers ``[c*Lc, (c+1)*Lc)`` of the canonical
    stack) must live on device ``s``; stage sharding splits the stacked
    arrays into S contiguous row groups, so device s's group has to hold
    its V chunks back to back: storage row ``s*(V*Lc) + v*Lc + j`` =
    canonical layer ``(v*S + s)*Lc + j``."""
    import numpy as np

    lc = n_layers // (S * V)
    perm = np.empty(n_layers, np.int64)
    for s in range(S):
        for v in range(V):
            for j in range(lc):
                perm[s * V * lc + v * lc + j] = (v * S + s) * lc + j
    return perm


def interleave_block_rows(blocks, n_layers: int, S: int, V: int):
    """Reorder every stacked-blocks leaf's leading (layer) dim from
    canonical order into the interleaved storage order
    ``make_1f1b_loss_and_grad(virtual_stages=V)`` expects. V=1 is a no-op."""
    if V == 1:
        return blocks
    perm = _interleave_perm(n_layers, S, V)
    return jax.tree.map(lambda leaf: leaf[perm], blocks)


def deinterleave_block_rows(blocks, n_layers: int, S: int, V: int):
    """Inverse of :func:`interleave_block_rows` (e.g. for exporting grads
    or checkpoints back to canonical layer order)."""
    if V == 1:
        return blocks
    import numpy as np

    perm = _interleave_perm(n_layers, S, V)
    inv = np.argsort(perm)
    return jax.tree.map(lambda leaf: leaf[inv], blocks)


def make_1f1b_loss_and_grad(cfg: tfm.TransformerConfig, spec: MeshSpec,
                            num_microbatches: int,
                            virtual_stages: int = 1) -> Callable:
    """Hand-scheduled 1F1B: ``(params, tokens, targets) ->
    (loss, aux_stats, grads)`` as ONE shard_map program over the full mesh.

    Why not whole-program autodiff (the GPipe path): under
    ``jax.value_and_grad`` the backward runs only after every forward tick,
    so all M microbatches' residuals are live at the peak — the most
    memory-hungry schedule is the only one AD can produce. Here forward and
    backward ticks interleave explicitly (the 1F1B order: microbatch m's
    backward starts the moment its loss exists, S-1 ticks after injection),
    so at most ``2S-1`` stage inputs are stashed per device instead of M.
    Backward recomputes each stage forward from its stashed input
    (activation stashing + recompute, the standard 1F1B memory/FLOPs
    trade; with ``cfg.remat`` the GPipe path recomputes too, making the
    FLOPs identical and the memory strictly better for M > 2S-1).

    Schedule (lockstep SPMD): global tick ``T`` runs forward tick ``T``
    (stage s computes microbatch ``T - s``) and backward tick ``T - (S-1)``
    (stage s re-derives microbatch ``T-(S-1) - (S-1-s)``), so the head loss
    computed at the last stage on tick T seeds that same tick's backward.
    ``M + 2S - 2`` ticks total. The M steady-state ticks — one full
    forward slot, head loss, and backward slot each, nothing masked-idle —
    run as a ``lax.scan``, which bounds peak memory *by construction*:
    the loop carry (stash ring + chain states + grad accumulators) plus
    ONE tick's transients, regardless of M. (An earlier draft unrolled the
    ticks and relied on ``optimization_barrier`` to keep XLA from hoisting
    every forward ahead of the backwards; XLA:CPU strips the barriers
    after layout assignment and the GPipe memory profile silently
    returned — the scan makes the liveness structural instead.) The S-1
    warmup (forward-only) and S-1 drain (backward-only) ticks unroll
    outside the scan.

    Gradient correctness under ``check_vma=False`` (verified against the
    autodiff GPipe step by tests/test_spmd_1f1b.py): the transpose of an
    in-body ``psum`` re-psums the cotangent, so a *replicated* cotangent
    entering the chain is inflated by the axis size exactly once, while
    chained device-varying cotangents sum correctly. Scaling the head
    cotangent by ``1/(n_model * n_expert)`` turns it into per-device
    partials; every per-stage vjp then yields exact local grads for
    axis-sharded leaves and partial grads for replicated leaves, which one
    final psum over each leaf's missing axes completes. The head/final-LN
    leaves sit *above* the pipeline (replicated compute off the unscaled
    cotangent), so they alone skip the model/expert sum.

    Replaces the reference's placeholder-seed backward + blocking-P2P ring
    (``distributed_layers.py:17-26``, ``utils.py:59-63``) at the schedule
    level: same per-microbatch interleave PipeDream-flush runs per-process,
    expressed as one jitted SPMD program.

    **Interleaved virtual stages** (``virtual_stages = V > 1``, Megatron
    placement): the model splits into ``D = V*S`` chunks, device ``s``
    owning chunks ``s, S+s, …`` — ``params["blocks"]`` rows must arrive in
    the interleaved storage order (:func:`interleave_block_rows`). The
    whole schedule generalizes through one mixed-radix decomposition: at
    forward fine tick ``ft``, device ``s`` computes ``u = ft - s`` →
    ``(r, v, g) = (u mod S, (u//S) mod V, u // (S*V))``, i.e. chunk ``v``
    of microbatch ``g*S + r`` (requires ``M % S == 0``, the Megatron
    constraint). Both the within-chunk hop ``s→s+1`` and the wraparound
    ``(S-1)→0`` (chunk v→v+1) are the SAME +1 modular ppermute — the ring
    already wraps. The stash ring grows to ``2D-1`` slots (entry written
    at fine tick τ is re-read 2ĉ ticks later, ĉ = chunk depth from the
    end) and the steady state runs ``M*V`` fine ticks, each 1/V the work
    of a V=1 tick: warmup+drain stay ``D-1`` fine ticks each, so the
    bubble shrinks from ``(S-1)/(M+S-1)`` toward ``(S-1)/(V*M+D-1)`` of
    the step — the Megatron interleaving payoff, with V=1 reducing to
    exactly the schedule above.
    """
    S = spec.num_stages
    V = virtual_stages
    D = S * V
    M = num_microbatches
    if V < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {V}")
    if V > 1:
        if M % S:
            raise ValueError(
                f"interleaved schedule needs num_microbatches divisible "
                f"by the stage count: M={M}, S={S} (Megatron constraint "
                f"— the microbatch groups cycle chunks in blocks of S)")
        if cfg.n_layers % D:
            raise ValueError(
                f"n_layers={cfg.n_layers} must divide into D=V*S={D} "
                f"equal chunks for interleaved placement")
    mesh = spec.mesh
    stage_axis = spec.stage_axis
    all_axes = tuple(mesh.axis_names)
    data_axes = _flat_axis_names(spec.data_axis)
    seq_axes = [spec.seq_axis] if cfg.sp_axis else []
    n_model = mesh.shape[spec.model_axis]
    n_expert = mesh.shape[spec.expert_axis]
    d_all = 1
    for a in all_axes:
        d_all *= mesh.shape[a]
    # Batch-sharded mesh axes: gradient contributions differ per shard and
    # always sum. The stage axis sums too (masked: one stage holds the real
    # value). model/expert sum only where the leaf spec lacks them — and
    # never for the above-pipeline head group (see docstring).
    batch_axes = data_axes + seq_axes

    pspecs = param_specs(spec.stage_axis, cfg.tp_axis,
                         moe=bool(cfg.moe_experts), ep_axis=cfg.ep_axis,
                         learned_pos=cfg.pos_embedding == "learned",
                         gqa=cfg.gqa,
                         shard_kv=kv_heads_shardable(cfg, spec))

    def _reduce_axes(leaf_spec: P, above_pipeline: bool) -> tuple[str, ...]:
        present = _spec_axes(leaf_spec)
        axes = list(batch_axes)
        if stage_axis not in present:     # stage-sharded leaves (blocks)
            axes.append(stage_axis)       # own their shard — never summed
        if not above_pipeline:
            for a in (spec.model_axis, spec.expert_axis):
                if a not in present:
                    axes.append(a)
        return tuple(a for a in axes if mesh.shape[a] > 1)

    # Stash ring: the chunk input written at forward fine tick τ is re-read
    # 2ĉ ticks later (ĉ = chunk depth from the pipeline end, max D-1), so
    # 2D-1 slots guarantee no collision — one write per tick, each entry
    # live < 2D-1 ticks. Never more slots than forward ticks.
    K = min(2 * D - 1, M * V + D - 1)

    def _head_nll_sum(head_p: dict, x: jax.Array,
                      targets: jax.Array) -> jax.Array:
        """Sum (not mean) of next-token NLL over the local shard, chunked
        per cfg.loss_chunk (shares tfm.chunked_nll_sum with the GPipe
        path's chunked_token_loss so the two heads cannot drift)."""
        t = x.shape[1]
        if cfg.loss_chunk:
            if t % cfg.loss_chunk:
                # Same loud failure as the GPipe head — a silent dense
                # fallback would materialize the [mbs, t, V] logits the
                # chunk knob exists to avoid. Under sequence parallelism
                # t is the PER-SHARD length, so the chunk must divide it.
                raise ValueError(
                    f"local seq len {t} not divisible by "
                    f"loss_chunk={cfg.loss_chunk} (with sequence "
                    f"parallelism loss_chunk must divide seq_len / sp)")
            return tfm.chunked_nll_sum(head_p, x, targets, cfg.loss_chunk)
        logp = jax.nn.log_softmax(
            tfm.unembed(head_p, x).astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, targets[..., None],
                                    axis=-1)[..., 0].sum()

    def _embed_local(embed_p: dict, toks: jax.Array) -> jax.Array:
        x = embed_p["embed"][toks]
        if cfg.pos_embedding == "learned":
            t = toks.shape[1]
            if cfg.sp_axis:
                # Local slice of the position table at this shard's global
                # offset (the GPipe path slices outside the shard_map where
                # t is global; here it is local).
                off = jax.lax.axis_index(spec.seq_axis) * t
                pos = jax.lax.dynamic_slice_in_dim(embed_p["pos"], off, t)
            else:
                pos = embed_p["pos"][:t]
            x = x + pos[None]
        return x

    lc_local = cfg.n_layers // D        # layers per chunk (== local/V)

    def _chunk_fwd(blocks_local, v, x):
        """Chunk ``v``'s blocks (rows [v*lc, (v+1)*lc) of this device's
        interleaved-layout stack). V=1: the whole local stack (no slice —
        keeps the V=1 program byte-identical to previous rounds)."""
        if V == 1:
            return tfm.blocks_scan(blocks_local, x, cfg)
        chunk = jax.tree.map(
            lambda leaf: jax.lax.dynamic_slice_in_dim(
                leaf, v * lc_local, lc_local, 0), blocks_local)
        # aux is a per-layer mean over the chunk's lc layers; weight by
        # 1/V so the V chunk executions sum to this device's per-stage
        # mean, keeping the V=1 normalization (and cotangent) unchanged.
        y, aux = tfm.blocks_scan(chunk, x, cfg)
        return y, aux / V

    def fwd_bwd(params, tokens, targets):
        s = jax.lax.axis_index(stage_axis)
        blocks = params["blocks"]
        head_p = {"ln_f_scale": params["ln_f_scale"],
                  "ln_f_bias": params["ln_f_bias"],
                  "head": params["head"]}
        embed_keys = (["embed", "pos"] if cfg.pos_embedding == "learned"
                      else ["embed"])
        embed_p = {k: params[k] for k in embed_keys}

        b, t = tokens.shape
        if b % M:
            raise ValueError(f"local batch {b} not divisible by M={M}")
        mbs = b // M
        toks_mb = tokens.reshape(M, mbs, t)
        tgts_mb = targets.reshape(M, mbs, t)
        d = cfg.d_model
        cot_scale = 1.0 / (n_model * n_expert)
        n_total = mbs * M * t             # global token count (static)
        for a in batch_axes:
            n_total *= mesh.shape[a]

        state_f = jnp.zeros((mbs, t, d), cfg.dtype)
        state_b = jnp.zeros((mbs, t, d), cfg.dtype)
        stash = jnp.zeros((K, mbs, t, d), cfg.dtype)
        loss_acc = jnp.zeros((), jnp.float32)
        aux_sum = jnp.zeros((tfm.AUX_STATS,), jnp.float32)
        g_blocks = jax.tree.map(jnp.zeros_like, blocks)
        g_head = jax.tree.map(jnp.zeros_like, head_p)
        g_embed = jax.tree.map(jnp.zeros_like, embed_p)

        perm_fwd = [(i, (i + 1) % S) for i in range(S)]
        perm_bwd = [(i, (i - 1) % S) for i in range(S)]

        def mask_tree(tree, keep):
            return jax.tree.map(lambda g: jnp.where(keep, g, 0), tree)

        def fwd_slot(ft, state_f, stash, aux_sum):
            """Forward fine tick ``ft`` (static int or traced scalar):
            device s decodes ``u = ft - s`` into (r, v, g) — chunk v of
            microbatch g*S+r — injects at (s==0, v==0), stashes its chunk
            input, and advances chunk v's blocks. Returns the POST-chunk
            state (the fwd ppermute happens at the caller, after the head
            slot reads it). V=1 reduces to: inject iff ft<M at stage 0,
            run the whole local stack."""
            u = jnp.asarray(ft) - s
            v = jnp.mod(u // S, V)
            m = (u // D) * S + jnp.mod(u, S)
            real_f = jnp.logical_and(u >= 0, jnp.logical_and(m >= 0, m < M))
            toks_i = jax.lax.dynamic_index_in_dim(
                toks_mb, jnp.clip(m, 0, M - 1), 0, keepdims=False)
            inject = jnp.logical_and(real_f,
                                     jnp.logical_and(s == 0, v == 0))
            state_f = jnp.where(
                inject, _embed_local(embed_p, toks_i).astype(cfg.dtype),
                state_f)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, state_f, jnp.mod(jnp.asarray(ft), K), 0)
            state_f, aux = _chunk_fwd(blocks, v, state_f)
            aux_sum = aux_sum + jnp.where(real_f, aux, 0.0)
            return state_f, stash, aux_sum

        def bwd_slot(bt, dy, state_b, stash, g_blocks, g_embed):
            """Backward fine tick ``bt``: device s decodes
            ``û = bt - (S-1-s)`` into (r, q, g) — the q-th-from-last of
            its chunks (chunk ``v = V-1-q``) for microbatch g*S+r —
            re-derives that chunk's input from the stash and pulls the
            cotangent through it (and, at the pipeline head — s==0 with
            the chunk-0 execution — into the embedding). ``dy`` is the
            head cotangent seeding stage S-1's chunk-(V-1) executions
            (None on drain ticks, where the chain state carries
            everything)."""
            u_b = jnp.asarray(bt) - (S - 1 - s)
            q = jnp.mod(u_b // S, V)
            v_b = V - 1 - q
            m_b = (u_b // D) * S + jnp.mod(u_b, S)
            real_b = jnp.logical_and(u_b >= 0,
                                     jnp.logical_and(m_b >= 0, m_b < M))
            cot_in = state_b
            if dy is not None:
                cot_in = jnp.where(
                    jnp.logical_and(s == S - 1, q == 0), dy, cot_in)
            # Stash entry for this execution was written 2ĉ fine ticks
            # before its backward runs (ĉ = q*S + S-1-s, chunk depth from
            # the end); the write tick was bt + (D-1) - 2ĉ.
            c_hat = q * S + (S - 1 - s)
            slot = jnp.mod(jnp.asarray(bt) + (D - 1) - 2 * c_hat, K)
            x_in = jax.lax.dynamic_index_in_dim(stash, slot, axis=0,
                                                keepdims=False)
            _, stage_vjp = jax.vjp(
                lambda bl, x: _chunk_fwd(bl, v_b, x), blocks, x_in)
            # All grads are accumulated in SUM units and divided by
            # n_total once at the end, so the aux cotangent (whose true
            # per-stat scale is weight / (M * d_all)) pre-multiplies by
            # n_total. Drop rate is a metric: zero cotangent.
            aux_cot = (jnp.where(real_b, n_total / (M * d_all), 0.0)
                       * jnp.array([cfg.moe_aux_weight, cfg.moe_z_weight,
                                    0.0], jnp.float32))
            g_b, dx = stage_vjp((cot_in, aux_cot))
            g_blocks = jax.tree.map(
                jnp.add, g_blocks, mask_tree(g_b, real_b))

            # The pipeline head (device 0's chunk-0 execution, q == V-1)
            # finished a microbatch's block backward: fold its cotangent
            # into the embedding (recomputed vjp — a gather).
            toks_0 = jax.lax.dynamic_index_in_dim(
                toks_mb, jnp.clip(m_b, 0, M - 1), 0, keepdims=False)
            _, emb_vjp = jax.vjp(
                lambda ep: _embed_local(ep, toks_0).astype(cfg.dtype),
                embed_p)
            g_e, = emb_vjp(dx)
            emb_real = jnp.logical_and(
                real_b, jnp.logical_and(s == 0, q == V - 1))
            g_embed = jax.tree.map(
                jnp.add, g_embed, mask_tree(g_e, emb_real))

            state_b = dx.astype(cfg.dtype)
            if S > 1:
                state_b = jax.lax.ppermute(state_b, stage_axis, perm_bwd)
            return state_b, g_blocks, g_embed

        # ---- warmup: forward-only fine ticks 0 .. D-2 (unrolled).
        for ft in range(D - 1):
            state_f, stash, aux_sum = fwd_slot(ft, state_f, stash, aux_sum)
            if S > 1:
                state_f = jax.lax.ppermute(state_f, stage_axis, perm_fwd)

        # ---- steady state: M*V fine ticks, each a full forward slot +
        # head slot + backward slot. A lax.scan so one tick's transients
        # are the whole transient footprint (see docstring).
        def steady_tick(carry, i):
            (state_f, state_b, stash, loss_acc, aux_sum, g_blocks, g_head,
             g_embed) = carry
            ft = i + (D - 1)          # fwd fine tick; bwd fine tick = i
            state_f, stash, aux_sum = fwd_slot(ft, state_f, stash, aux_sum)

            # head slot: real when stage S-1 just ran a LAST-chunk
            # (v == V-1) execution of a real microbatch — that microbatch's
            # forward is complete and its loss seeds this tick's backward.
            u_l = jnp.asarray(ft) - (S - 1)
            m_head = (u_l // D) * S + jnp.mod(u_l, S)
            head_real = jnp.logical_and(
                s == S - 1,
                jnp.logical_and(jnp.mod(u_l // S, V) == V - 1,
                                jnp.logical_and(m_head >= 0, m_head < M)))
            tgt_i = jax.lax.dynamic_index_in_dim(
                tgts_mb, jnp.clip(m_head, 0, M - 1), 0, keepdims=False)
            nll, head_vjp = jax.vjp(
                lambda hp, x: _head_nll_sum(hp, x, tgt_i), head_p, state_f)
            loss_acc = loss_acc + jnp.where(head_real, nll, 0.0)
            g_h, dy = head_vjp(jnp.ones((), jnp.float32))
            g_head = jax.tree.map(jnp.add, g_head,
                                  mask_tree(g_h, head_real))
            dy = jnp.where(head_real, dy * cot_scale,
                           jnp.zeros_like(dy)).astype(cfg.dtype)

            state_b, g_blocks, g_embed = bwd_slot(
                i, dy, state_b, stash, g_blocks, g_embed)
            if S > 1:
                state_f = jax.lax.ppermute(state_f, stage_axis, perm_fwd)
            return (state_f, state_b, stash, loss_acc, aux_sum, g_blocks,
                    g_head, g_embed), None

        carry = (state_f, state_b, stash, loss_acc, aux_sum, g_blocks,
                 g_head, g_embed)
        carry, _ = jax.lax.scan(steady_tick, carry, jnp.arange(M * V))
        (state_f, state_b, stash, loss_acc, aux_sum, g_blocks, g_head,
         g_embed) = carry

        # ---- drain: backward-only fine ticks bt = M*V .. M*V+D-2.
        for bt in range(M * V, M * V + D - 1):
            state_b, g_blocks, g_embed = bwd_slot(
                bt, None, state_b, stash, g_blocks, g_embed)

        # ---- reductions: complete each leaf's partial grads over the mesh
        # axes its spec does not shard (docstring), and assemble the loss.
        def reduce_leaf(g, ps, above):
            axes = _reduce_axes(ps, above)
            return jax.lax.psum(g, axes) if axes else g

        grads = {"blocks": jax.tree.map(
            lambda g, ps: reduce_leaf(g, ps, False), g_blocks,
            pspecs["blocks"], is_leaf=lambda x: isinstance(x, P))}
        grads.update({k: reduce_leaf(v, pspecs[k], True)
                      for k, v in g_head.items()})
        grads.update({k: reduce_leaf(v, pspecs[k], False)
                      for k, v in g_embed.items()})
        scale = 1.0 / n_total             # sum units -> mean-loss units
        grads = jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)

        loss_axes = tuple(a for a in batch_axes + [stage_axis]
                          if mesh.shape[a] > 1)
        loss = (jax.lax.psum(loss_acc, loss_axes) if loss_axes
                else loss_acc) / n_total
        aux_all = (jax.lax.psum(aux_sum, tuple(
            a for a in all_axes if mesh.shape[a] > 1))
            if any(mesh.shape[a] > 1 for a in all_axes) else aux_sum)
        aux_mean = aux_all / (M * d_all)      # [AUX_STATS]
        loss = loss + tfm.aux_loss(aux_mean, cfg)
        return loss, aux_mean, grads

    seq = spec.seq_axis if cfg.sp_axis else None
    x_spec = P(spec.data_axis, seq)
    grad_specs = {k: v for k, v in pspecs.items()}
    return jax.shard_map(
        fwd_bwd, mesh=mesh,
        in_specs=(pspecs, x_spec, x_spec),
        out_specs=(P(), P(), grad_specs),
        check_vma=False)


def _make_loss_fn(cfg: tfm.TransformerConfig, spec: MeshSpec,
                  num_microbatches: int) -> Callable:
    """loss_fn(params, tokens, targets) -> (scalar, aux_stats[AUX_STATS]),
    through the shard_map pipeline and the dense or chunked head — the
    single definition the train step and the eval loss both jit."""
    pipeline_blocks = make_pipeline_apply(cfg, spec, num_microbatches)

    def loss_fn(params, tokens, targets):
        x = tfm.embed(params, tokens, cfg)
        x, aux = pipeline_blocks(params["blocks"], x)
        if cfg.loss_chunk:
            return tfm.chunked_token_loss(params, x, targets, aux, cfg,
                                          cfg.loss_chunk), aux
        logits = tfm.unembed(params, x)
        return tfm.token_loss(logits, targets, aux, cfg), aux

    return loss_fn


def make_spmd_train_step(cfg: tfm.TransformerConfig, spec: MeshSpec,
                         tx: optax.GradientTransformation,
                         num_microbatches: int = 1,
                         schedule: str = "gpipe",
                         virtual_stages: int = 1) -> Callable:
    """One fully-jitted SPMD training step over the whole mesh.

    Covers dp (batch sharding + XLA grad allreduce), pp (shard_map pipeline),
    tp (Megatron psums), sp (ring attention) in one program — the
    ``dryrun_multichip`` contract.

    ``schedule`` picks how the pipeline's backward is produced: ``"gpipe"``
    differentiates the forward tick loop whole-program (all M microbatches'
    residuals live at peak), ``"1f1b"`` hand-interleaves forward and
    backward ticks (``make_1f1b_loss_and_grad`` — at most 2S-1 stashed
    stage inputs per device). Loss and grads agree to float tolerance
    (tests/test_spmd_1f1b.py); memory and recompute differ.
    """
    def metrics_of(loss, aux):
        """Uniform per-step metrics: loss always; the MoE router stats
        whenever the model routes (zeros otherwise, dropped for dense
        models so logs stay clean)."""
        out = {"loss": loss}
        if cfg.moe_experts:
            out.update(moe_balance=aux[0], moe_z=aux[1], moe_drop=aux[2])
        return out

    if schedule == "1f1b":
        # virtual_stages > 1: params["blocks"] must be in interleaved
        # storage order (interleave_block_rows) for the step's lifetime —
        # optimizer state follows rows, so training in that layout is
        # self-consistent; deinterleave only for export.
        loss_and_grad = make_1f1b_loss_and_grad(
            cfg, spec, num_microbatches, virtual_stages=virtual_stages)

        def step(params, opt_state, tokens, targets):
            loss, aux, grads = loss_and_grad(params, tokens, targets)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics_of(loss, aux)
    elif schedule == "gpipe":
        if virtual_stages != 1:
            raise ValueError(
                "interleaved virtual stages are a 1f1b schedule feature "
                "(gpipe's whole-program AD would gain nothing — no "
                "silent ignores)")
        loss_fn = _make_loss_fn(cfg, spec, num_microbatches)

        def step(params, opt_state, tokens, targets):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, tokens, targets)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics_of(loss, aux)
    else:
        raise ValueError(f"unknown spmd pipeline schedule {schedule!r}; "
                         f"known: gpipe, 1f1b")

    pspecs = param_specs(spec.stage_axis, cfg.tp_axis,
                         moe=bool(cfg.moe_experts), ep_axis=cfg.ep_axis,
                         learned_pos=cfg.pos_embedding == "learned",
                         gqa=cfg.gqa,
                         shard_kv=kv_heads_shardable(cfg, spec))
    p_sh = jax.tree.map(lambda ps: NamedSharding(spec.mesh, ps), pspecs,
                        is_leaf=lambda x: isinstance(x, P))
    seq = spec.seq_axis if cfg.sp_axis else None
    tok_sh = NamedSharding(spec.mesh, P(spec.data_axis, seq))
    repl = NamedSharding(spec.mesh, P())

    return jax.jit(
        step,
        in_shardings=(p_sh, repl, tok_sh, tok_sh),
        out_shardings=(p_sh, repl, repl),
        donate_argnums=(0, 1))


def make_spmd_eval_loss(cfg: tfm.TransformerConfig, spec: MeshSpec,
                        num_microbatches: int = 1) -> Callable:
    """Forward-only loss over the same dp/pp/tp/sp program as the train
    step: ``eval_loss(params, tokens, targets) -> loss``. Shares the train
    step's loss_fn (``_make_loss_fn``) so the two can never diverge."""
    loss_fn = _make_loss_fn(cfg, spec, num_microbatches)

    pspecs = param_specs(spec.stage_axis, cfg.tp_axis,
                         moe=bool(cfg.moe_experts), ep_axis=cfg.ep_axis,
                         learned_pos=cfg.pos_embedding == "learned",
                         gqa=cfg.gqa,
                         shard_kv=kv_heads_shardable(cfg, spec))
    p_sh = jax.tree.map(lambda ps: NamedSharding(spec.mesh, ps), pspecs,
                        is_leaf=lambda x: isinstance(x, P))
    seq = spec.seq_axis if cfg.sp_axis else None
    tok_sh = NamedSharding(spec.mesh, P(spec.data_axis, seq))
    repl = NamedSharding(spec.mesh, P())

    def eval_loss(params, tokens, targets):
        return loss_fn(params, tokens, targets)[0]

    return jax.jit(eval_loss, in_shardings=(p_sh, tok_sh, tok_sh),
                   out_shardings=repl)


def shard_params(params: dict, cfg: tfm.TransformerConfig,
                 spec: MeshSpec) -> dict:
    """Place a host-initialized parameter tree onto the mesh per the TP/PP
    specs (the framework's replacement for per-rank shard construction,
    reference model_parallel.py:99-157)."""
    pspecs = param_specs(spec.stage_axis, cfg.tp_axis,
                         moe=bool(cfg.moe_experts), ep_axis=cfg.ep_axis,
                         learned_pos=cfg.pos_embedding == "learned",
                         gqa=cfg.gqa,
                         shard_kv=kv_heads_shardable(cfg, spec))
    return jax.tree.map(
        lambda x, ps: jax.device_put(x, NamedSharding(spec.mesh, ps)),
        params, pspecs,
        is_leaf=lambda x: isinstance(x, P))
