"""Single-program SPMD pipeline: ``shard_map`` + ``ppermute`` over a ``stage``
mesh axis.

This is the multi-host-capable counterpart of ``parallel/pipeline.py``'s
single-controller runtime: the whole training step — embed, S pipeline
stages, LM head, loss, backward, optimizer — is ONE jitted SPMD program over
the mesh, so it scales over ICI/DCN exactly like any pjit program (the way
the reference's per-process NCCL ring never could without its hand-rolled
wire protocol, ``distributed_layers.py:7-62``).

Schedule: round-robin GPipe over ``M`` microbatches and ``S`` stages in
``M + S - 1`` ticks. Stage 0 injects microbatch ``t`` at tick ``t``; every
stage applies its local stacked blocks (a ``lax.scan``); activations hop one
stage per tick via ``ppermute``; the last stage emits microbatch ``t-S+1``.
Bubbles are real compute on garbage data — the price of SPMD pipelining —
shrinking relatively as M grows. Composes with ``data`` (batch sharding),
``model`` (Megatron TP inside the block via psum) and ``seq`` (ring
attention) axes in the same shard_map.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.mesh import MeshSpec
from distributed_model_parallel_tpu.models import transformer as tfm
from distributed_model_parallel_tpu.parallel.tensor_parallel import (
    block_specs,
    kv_heads_shardable,
    param_specs,
)


def make_pipeline_apply(cfg: tfm.TransformerConfig, spec: MeshSpec,
                        num_microbatches: int) -> Callable:
    """Returns pipeline_blocks(blocks, x) -> (y, aux), a shard_map'd function.

    blocks leaves are [L, ...] sharded over ``stage`` on dim 0; x is
    [B, T, d] sharded over ``data`` (and ``seq`` if sequence parallel).
    ``aux`` is the mean per-layer MoE load-balance loss over real
    microbatches (0 for dense models).
    """
    S = spec.num_stages
    M = num_microbatches
    stage_axis = spec.stage_axis
    axes = spec.mesh.axis_names

    def stage_fn(blocks_local, x_local):
        s = jax.lax.axis_index(stage_axis)
        b, t, d = x_local.shape
        if b % M:
            raise ValueError(f"local batch {b} not divisible by M={M}")
        mbs = b // M
        mb = x_local.reshape(M, mbs, t, d)
        state = jnp.zeros((mbs, t, d), x_local.dtype)
        outputs = jnp.zeros((M, mbs, t, d), x_local.dtype)
        aux_sum = jnp.zeros((), jnp.float32)
        perm = [(i, (i + 1) % S) for i in range(S)]

        for tick in range(M + S - 1):           # static unroll
            if tick < M:                        # stage 0 injects microbatch
                state = jnp.where(s == 0, mb[tick], state)
            state, aux = tfm.blocks_scan(blocks_local, state, cfg)
            # At tick t, stage s holds microbatch t-s; bubble ticks
            # (t-s outside [0, M)) run on garbage activations, so their
            # aux is masked out. Logits are unaffected (aux never feeds
            # the forward value).
            real = jnp.logical_and(tick - s >= 0, tick - s < M)
            aux_sum = aux_sum + jnp.where(real, aux, 0.0)
            out_idx = tick - (S - 1)
            if 0 <= out_idx < M:                # last stage emits
                outputs = outputs.at[out_idx].set(
                    jnp.where(s == S - 1, state, outputs[out_idx]))
            if S > 1:
                state = jax.lax.ppermute(state, stage_axis, perm)

        # Broadcast the collected outputs from the last stage to every stage
        # so the (replicated-over-stage) head/loss sees them.
        outputs = jax.lax.psum(
            jnp.where(s == S - 1, outputs, jnp.zeros_like(outputs)),
            stage_axis)
        # Mean over stages x microbatches; pmean over every mesh axis so the
        # result is replicated (aux differs per data/seq shard before this).
        aux_mean = jax.lax.pmean(aux_sum / M, tuple(axes))
        return outputs.reshape(b, t, d), aux_mean

    seq = spec.seq_axis if cfg.sp_axis else None
    x_spec = P(spec.data_axis, seq, None)
    return jax.shard_map(
        stage_fn, mesh=spec.mesh,
        in_specs=(block_specs(stage_axis, cfg.tp_axis,
                              moe=bool(cfg.moe_experts),
                              ep_axis=cfg.ep_axis, gqa=cfg.gqa,
                              shard_kv=kv_heads_shardable(cfg, spec)),
                  x_spec),
        out_specs=(x_spec, P()),
        check_vma=False)


def _make_loss_fn(cfg: tfm.TransformerConfig, spec: MeshSpec,
                  num_microbatches: int) -> Callable:
    """loss_fn(params, tokens, targets) -> scalar, through the shard_map
    pipeline and the dense or chunked head — the single definition the
    train step and the eval loss both jit."""
    pipeline_blocks = make_pipeline_apply(cfg, spec, num_microbatches)

    def loss_fn(params, tokens, targets):
        x = tfm.embed(params, tokens, cfg)
        x, aux = pipeline_blocks(params["blocks"], x)
        if cfg.loss_chunk:
            return tfm.chunked_token_loss(params, x, targets, aux, cfg,
                                          cfg.loss_chunk)
        logits = tfm.unembed(params, x)
        return tfm.token_loss(logits, targets, aux, cfg)

    return loss_fn


def make_spmd_train_step(cfg: tfm.TransformerConfig, spec: MeshSpec,
                         tx: optax.GradientTransformation,
                         num_microbatches: int = 1) -> Callable:
    """One fully-jitted SPMD training step over the whole mesh.

    Covers dp (batch sharding + XLA grad allreduce), pp (shard_map pipeline),
    tp (Megatron psums), sp (ring attention) in one program — the
    ``dryrun_multichip`` contract.
    """
    loss_fn = _make_loss_fn(cfg, spec, num_microbatches)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    pspecs = param_specs(spec.stage_axis, cfg.tp_axis,
                         moe=bool(cfg.moe_experts), ep_axis=cfg.ep_axis,
                         learned_pos=cfg.pos_embedding == "learned",
                         gqa=cfg.gqa,
                         shard_kv=kv_heads_shardable(cfg, spec))
    p_sh = jax.tree.map(lambda ps: NamedSharding(spec.mesh, ps), pspecs,
                        is_leaf=lambda x: isinstance(x, P))
    seq = spec.seq_axis if cfg.sp_axis else None
    tok_sh = NamedSharding(spec.mesh, P(spec.data_axis, seq))
    repl = NamedSharding(spec.mesh, P())

    return jax.jit(
        step,
        in_shardings=(p_sh, repl, tok_sh, tok_sh),
        out_shardings=(p_sh, repl, repl),
        donate_argnums=(0, 1))


def make_spmd_eval_loss(cfg: tfm.TransformerConfig, spec: MeshSpec,
                        num_microbatches: int = 1) -> Callable:
    """Forward-only loss over the same dp/pp/tp/sp program as the train
    step: ``eval_loss(params, tokens, targets) -> loss``. Shares the train
    step's loss_fn (``_make_loss_fn``) so the two can never diverge."""
    loss_fn = _make_loss_fn(cfg, spec, num_microbatches)

    pspecs = param_specs(spec.stage_axis, cfg.tp_axis,
                         moe=bool(cfg.moe_experts), ep_axis=cfg.ep_axis,
                         learned_pos=cfg.pos_embedding == "learned",
                         gqa=cfg.gqa,
                         shard_kv=kv_heads_shardable(cfg, spec))
    p_sh = jax.tree.map(lambda ps: NamedSharding(spec.mesh, ps), pspecs,
                        is_leaf=lambda x: isinstance(x, P))
    seq = spec.seq_axis if cfg.sp_axis else None
    tok_sh = NamedSharding(spec.mesh, P(spec.data_axis, seq))
    repl = NamedSharding(spec.mesh, P())
    return jax.jit(loss_fn, in_shardings=(p_sh, tok_sh, tok_sh),
                   out_shardings=repl)


def shard_params(params: dict, cfg: tfm.TransformerConfig,
                 spec: MeshSpec) -> dict:
    """Place a host-initialized parameter tree onto the mesh per the TP/PP
    specs (the framework's replacement for per-rank shard construction,
    reference model_parallel.py:99-157)."""
    pspecs = param_specs(spec.stage_axis, cfg.tp_axis,
                         moe=bool(cfg.moe_experts), ep_axis=cfg.ep_axis,
                         learned_pos=cfg.pos_embedding == "learned",
                         gqa=cfg.gqa,
                         shard_kv=kv_heads_shardable(cfg, spec))
    return jax.tree.map(
        lambda x, ps: jax.device_put(x, NamedSharding(spec.mesh, ps)),
        params, pspecs,
        is_leaf=lambda x: isinstance(x, P))
