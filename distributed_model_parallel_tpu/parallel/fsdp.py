"""Fully-sharded data parallelism (FSDP / ZeRO-3) via GSPMD shardings.

Absent from the reference (SURVEY.md §2.3 lists ZeRO/FSDP as "Absent");
``parallel/zero.py`` already covers ZeRO stage 1+2 (sharded optimizer state +
reduce-scattered gradients) with explicit shard_map collectives. This module
is the stage-3 upgrade — *parameters themselves* live sharded across the data
axis — expressed the TPU-native way: no hand-written gather/scatter schedule
at all. Each parameter (and optimizer-state) leaf is annotated with a
``NamedSharding`` that splits its largest divisible dimension over ``data``;
the train step stays the plain global-batch program, and XLA's SPMD
partitioner inserts the just-in-time ``all-gather`` before each use site and
the ``reduce-scatter`` behind each gradient — overlapped with compute by the
XLA scheduler, which is exactly the hand-tuned prefetch pipeline frameworks
like torch FSDP implement manually around NCCL.

Memory: params + grads + optimizer state are all 1/N per chip at rest;
only the layer being computed is materialized full-size (transiently, by the
partitioner's gather).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.mesh import MeshSpec

# Leaves smaller than this stay replicated: sharding a 10-element bias over 8
# chips saves nothing and costs a collective. (torch FSDP has the same knob.)
DEFAULT_MIN_SHARD_SIZE = 1024


def leaf_spec(shape: tuple[int, ...], n: int, axis: str,
              min_size: int = DEFAULT_MIN_SHARD_SIZE) -> P:
    """PartitionSpec for one leaf: shard the largest n-divisible dim.

    Ties break toward the *last* dimension (output features) — on TPU the
    trailing dims are the lane dims, and sharding there keeps the gathered
    blocks contiguous in the layout XLA prefers.
    """
    if int(np.prod(shape, dtype=np.int64)) < max(min_size, n):
        return P()
    best = None
    for d in range(len(shape)):
        if shape[d] % n == 0 and (best is None or shape[d] >= shape[best]):
            best = d
    if best is None:
        return P()
    spec = [None] * len(shape)
    spec[best] = axis
    return P(*spec)


def tree_shardings(tree: Any, spec: MeshSpec,
                   min_size: int = DEFAULT_MIN_SHARD_SIZE) -> Any:
    """FSDP NamedSharding for every leaf of ``tree``.

    Works on concrete arrays or ``ShapeDtypeStruct``s (so optimizer-state
    shardings can be derived from ``jax.eval_shape(tx.init, params)`` without
    materializing a replicated copy first).
    """
    axis, n = spec.data_axis, spec.num_data

    def one(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        return NamedSharding(spec.mesh, leaf_spec(shape, n, axis, min_size))

    return jax.tree.map(one, tree)


def shard_pytree(tree: Any, spec: MeshSpec,
                 min_size: int = DEFAULT_MIN_SHARD_SIZE) -> Any:
    """Place a host/replicated pytree into its FSDP-sharded layout."""
    return jax.device_put(tree, tree_shardings(tree, spec, min_size))
