"""Parallelism strategies (SURVEY.md §2.3, all rows covered):

* ``data_parallel`` — single-host scatter/replicate/apply/gather (DP)
* ``ddp`` — explicit per-replica shard_map engine with psum allreduce (DDP)
* ``zero`` — sharded-optimizer data parallelism (ZeRO 1+2)
* ``pipeline`` — per-stage placement runtime: naive / GPipe / 1F1B (MP/PP)
* ``spmd_pipeline`` — single-jit shard_map+ppermute pipeline (multi-host PP)
* ``tensor_parallel`` — Megatron column/row PartitionSpecs (TP)
"""

from distributed_model_parallel_tpu.parallel.auto_partition import (  # noqa: F401
    # Public planner contract (docs/AUTOTUNE.md): the autotuner's compute
    # term and the pipeline balancer share these.
    auto_boundaries,
    compiled_flops_probe,
    cost_balanced_boundaries,
    microbatch_rows,
    unit_costs,
)
from distributed_model_parallel_tpu.parallel.data_parallel import (  # noqa: F401
    data_parallel_apply,
    gather,
    parallel_apply,
    replicate,
    scatter,
)
from distributed_model_parallel_tpu.parallel.pipeline import (  # noqa: F401
    PipelineRunner,
    StageState,
)
from distributed_model_parallel_tpu.parallel.tensor_parallel import (  # noqa: F401
    block_specs,
    param_specs,
)
from distributed_model_parallel_tpu.parallel.zero import (  # noqa: F401
    make_zero_train_step,
)
