"""Explicit DDP: shard_map per-replica programs + gradient allreduce.

The reference *analyzes* DistributedDataParallel — per-process replicas, a C++
``Reducer`` doing bucketed ring-allreduce from autograd hooks, optional
SyncBatchNorm, ``find_unused_parameters`` (``Readme.md:144-157``) — and
BASELINE.json promotes it to in-scope (configs 2-5). This module is the
TPU-native equivalent with *explicit* per-replica semantics, as opposed to the
GSPMD path in ``train/trainer.py`` where XLA infers the allreduce:

* each data shard runs its own forward/backward inside ``shard_map`` — a real
  per-replica program, like one DDP rank;
* BatchNorm statistics are **per-replica** (each replica carries its own
  running stats, sharded over the data axis — faithful to DDP-without-SyncBN)
  unless the model was built with ``bn_mode="sync"``, in which case the BN
  layers psum their batch stats over the axis (SyncBatchNorm);
* gradients are averaged with either a straight ``psum`` or the bucketed
  coalesced allreduce (``ops/collectives.bucketed_psum``), selectable like
  DDP's bucket_cap_mb;
* parameters stay replicated and the optimizer step runs identically on every
  replica (DDP's invariant).

That last invariant — bitwise-identical params/opt_state on every replica —
is exactly what silent data corruption breaks and what the consistency
sentinel (train/consistency.py) polices: params and optimizer state (specs
``P()``) are fingerprinted and compared across the data axis, while the
per-replica BatchNorm state (spec ``P(data)``) is *legitimately* divergent
and excluded by the sentinel's sharding filter.
:func:`assert_ddp_replicated` is the direct, fetch-everything spelling of
the same invariant for tests and post-mortems.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from distributed_model_parallel_tpu.data.loader import (
    augment_batch,
    normalize,
    resize_batch,
)
from distributed_model_parallel_tpu.mesh import MeshSpec
from distributed_model_parallel_tpu.models.staged import StagedModel
from distributed_model_parallel_tpu.ops.collectives import (
    bucketed_psum,
    hierarchical_psum_tree,
    psum_mean,
)
from distributed_model_parallel_tpu.ops.ring_reduce import ring_psum_tree
from distributed_model_parallel_tpu.train.metrics import topk_correct
from distributed_model_parallel_tpu.train.trainer import TrainState, cross_entropy


def replicate_model_state(state: Any, num_replicas: int) -> Any:
    """Give BN state a leading per-replica axis (to be sharded over 'data')."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (num_replicas,) + x.shape), state)


def assert_ddp_replicated(state: "TrainState") -> None:
    """Verify DDP's replication invariant directly: params and opt_state
    must be bitwise-identical on every device (model_state is per-replica
    by design and skipped). The exhaustive host-side spelling of the check
    the consistency sentinel does with on-device fingerprints — use in
    tests and post-mortems, not hot loops (it fetches every shard)."""
    from distributed_model_parallel_tpu.train.guards import assert_replicated

    assert_replicated(state.params, name="params")
    assert_replicated(state.opt_state, name="opt_state")


def make_ddp_train_step(model: StagedModel, tx: optax.GradientTransformation,
                        spec: MeshSpec, *, mean, std, augment: bool = True,
                        dtype=jnp.float32, bucket_bytes: int | None = None,
                        allreduce: str = "psum",
                        resize_to: int | None = None) -> Callable:
    """Returns jitted step(state, rng, images_u8, labels) -> (state, metrics).

    ``state.model_state`` must carry a leading per-replica axis
    (``replicate_model_state``). ``allreduce`` picks the gradient transport:
    "psum" (per-leaf, XLA chooses the algorithm), "bucketed" (flat coalesced
    buckets of ``bucket_bytes``), "ring" (explicit bandwidth-optimal
    neighbor-ppermute ring, ``ops/ring_reduce.py``), or "hierarchical"
    (two-level ICI/DCN staging for multi-host meshes, requires
    ``MeshConfig.dcn_data > 1``). ``bucket_bytes`` set with allreduce="psum"
    implies "bucketed" for backward compatibility.
    """
    axis = spec.data_axis
    if allreduce == "psum" and bucket_bytes is not None:
        allreduce = "bucketed"
    if allreduce not in ("psum", "bucketed", "ring", "hierarchical"):
        raise KeyError(f"unknown allreduce {allreduce!r}")
    if allreduce == "hierarchical" and spec.dcn_axis is None:
        raise ValueError(
            "allreduce='hierarchical' needs a two-level data axis; set "
            "MeshConfig.dcn_data > 1 (--dcn-data)")
    if allreduce == "ring" and spec.dcn_axis is not None:
        raise ValueError(
            "allreduce='ring' permutes over a flat data axis; with "
            "dcn_data > 1 use 'hierarchical' (or 'psum'/'bucketed')")

    def loss_fn(params, model_state, images, labels):
        logits, new_state = model.apply(params, model_state, images, train=True)
        loss = cross_entropy(logits, labels)
        return loss, (logits, new_state)

    def replica_step(state: TrainState, rng, images_u8, labels):
        # Per-replica program: local shard of the batch, own BN state.
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        local_state = jax.tree.map(lambda x: x[0], state.model_state)
        if resize_to is not None:
            images_u8 = resize_batch(images_u8, resize_to)
        images_u8 = augment_batch(rng, images_u8) if augment else images_u8
        images = normalize(images_u8, mean, std, dtype)
        (loss, (logits, new_local_state)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, local_state, images, labels)

        # The Reducer equivalent: average gradients across replicas.
        if allreduce == "hierarchical":
            # Multi-host staging: ICI reduce-scatter, small DCN exchange,
            # ICI all-gather (NCCL's hierarchical-ring analog).
            grads = hierarchical_psum_tree(
                grads, spec.ici_data_axis, spec.dcn_axis, mean=True)
        elif allreduce == "ring":
            grads = ring_psum_tree(
                grads, axis, **({} if bucket_bytes is None
                                else {"bucket_bytes": bucket_bytes}))
        elif allreduce == "bucketed":
            grads = bucketed_psum(
                grads, axis, **({} if bucket_bytes is None
                                else {"bucket_bytes": bucket_bytes}))
        else:
            grads = psum_mean(grads, axis)

        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        # Metrics reduce over replicas; loss is the global-batch mean.
        n = jax.lax.psum(1, axis)
        metrics = {
            "loss": jax.lax.psum(loss, axis) / n,
            "batch": jax.lax.psum(jnp.asarray(labels.shape[0], jnp.float32), axis),
            **{k: jax.lax.psum(v, axis)
               for k, v in topk_correct(logits, labels).items()},
        }
        new_state = TrainState(
            step=state.step + 1, params=new_params,
            model_state=jax.tree.map(lambda x: x[None], new_local_state),
            opt_state=new_opt_state)
        return new_state, metrics

    # Pytree-prefix specs: BN state is sharded per-replica on its leading
    # axis; everything else is replicated.
    state_specs = TrainState(step=P(), params=P(), model_state=P(axis),
                             opt_state=P())

    shard_fn = jax.shard_map(
        replica_step, mesh=spec.mesh,
        in_specs=(state_specs, P(), P(axis), P(axis)),
        out_specs=(state_specs, P()),
        check_vma=False)
    # Donate the state (in-place update) AND the batch buffers — each
    # sharded batch is consumed exactly once, and handing ownership to
    # the runtime frees its device memory at dispatch (see the GSPMD
    # step in train/trainer._build_steps for the full rationale).
    return jax.jit(shard_fn, donate_argnums=(0, 2, 3))


def make_ddp_eval_step(model: StagedModel, spec: MeshSpec, *, mean, std,
                       dtype=jnp.float32,
                       resize_to: int | None = None) -> Callable:
    axis = spec.data_axis

    def replica_eval(state: TrainState, images_u8, labels):
        local_state = jax.tree.map(lambda x: x[0], state.model_state)
        if resize_to is not None:
            images_u8 = resize_batch(images_u8, resize_to)
        images = normalize(images_u8, mean, std, dtype)
        logits, _ = model.apply(state.params, local_state, images, train=False)
        n = jax.lax.psum(1, axis)
        return {
            "loss": jax.lax.psum(cross_entropy(logits, labels), axis) / n,
            "batch": jax.lax.psum(jnp.asarray(labels.shape[0], jnp.float32), axis),
            **{k: jax.lax.psum(v, axis)
               for k, v in topk_correct(logits, labels).items()},
        }

    state_specs = TrainState(step=P(), params=P(), model_state=P(axis),
                             opt_state=P())
    shard_fn = jax.shard_map(
        replica_eval, mesh=spec.mesh,
        in_specs=(state_specs, P(axis), P(axis)), out_specs=P(),
        check_vma=False)
    return jax.jit(shard_fn)
