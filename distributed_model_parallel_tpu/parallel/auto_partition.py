"""Automatic cost-balanced pipeline stage partitioning.

The reference hard-codes its per-rank layer split in the launcher — rank 0
gets the stem + first blocks, middle ranks get ``layers[6r-3:6r+3]``, the last
rank gets the head (``model_parallel.py:99-157``) — so rebalancing means
editing code, and nothing guarantees the stages are actually balanced. Here
stage boundaries are already plain data over a ``StagedModel``
(``models/staged.py``); this module *computes* them: per-unit costs come from
XLA's own compiled cost model (``lowered.compile().cost_analysis()`` FLOPs,
with a parameter+activation-bytes fallback), and boundaries are chosen to
minimize the bottleneck stage cost — the pipeline's steady-state throughput is
set by its slowest stage, so minimax (not equal-count) is the right objective.

**Public contract:** ``unit_costs``, ``cost_balanced_boundaries``,
``auto_boundaries``, ``microbatch_rows`` and ``compiled_flops_probe`` are
stable API, not pipeline-internal helpers — the parallelism autotuner
(``autotune/``, docs/AUTOTUNE.md) builds its compute term on them, and
``parallel/__init__`` re-exports them. Pinned properties: ``unit_costs``
returns one strictly-positive float per unit, in unit order, at the given
sample shape; ``cost_balanced_boundaries`` is a deterministic exact
minimax DP whose ties keep the latest cut (front-loaded stages).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.models.staged import StagedModel

__all__ = [
    "auto_boundaries",
    "compiled_flops_probe",
    "cost_balanced_boundaries",
    "microbatch_rows",
    "unit_costs",
]


def compiled_flops_probe(fn, *args) -> float | None:
    """XLA's FLOP estimate for ``fn(*args)``, or None if unavailable
    (loop bodies counted once, custom calls zero — see
    ``utils/profiling.compiled_cost_analysis`` for the blind spots; valid
    for the loop-free per-unit programs this module costs)."""
    try:
        analysis = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):  # older jax returns [dict]
            analysis = analysis[0] if analysis else {}
        flops = analysis.get("flops", None)
        if flops is None or not np.isfinite(flops) or flops < 0:
            return None
        return float(flops)
    except Exception:
        return None


# Historical private name (pre-autotune callers).
_compiled_flops = compiled_flops_probe


def unit_costs(model: StagedModel, sample_shape: Sequence[int],
               *, train: bool = True) -> list[float]:
    """Per-unit relative cost of one forward pass at ``sample_shape``.

    Threads the activation shape through the unit chain with ``eval_shape``
    (so each unit is costed at its true input shape), compiling each unit
    once on whatever backend is active — the FLOP count is
    backend-independent. Falls back to parameter-count + activation-element
    proxies for units XLA cannot cost.

    Stability pin (consumed by ``autotune/search.cnn_workload`` and the
    pipeline balancer alike): returns ``model.num_units`` floats, each
    ``>= 1.0``, in unit order.
    """
    x = jnp.zeros(tuple(sample_shape), jnp.float32)
    params, state = model.init(jax.random.key(0), x)
    costs: list[float] = []
    for i in range(model.num_units):
        def fwd(p, s, a, _i=i):
            y, _ = model.apply_unit(_i, p, s, a, train=train)
            return y
        flops = compiled_flops_probe(fwd, params[i], state[i], x)
        out = jax.eval_shape(fwd, params[i], state[i], x)
        if flops is None:
            n_params = sum(l.size for l in jax.tree.leaves(params[i]))
            flops = 2.0 * n_params * np.prod(sample_shape[:1]) + out.size
        costs.append(max(flops, 1.0))
        x = jnp.zeros(out.shape, out.dtype)
    return costs


def cost_balanced_boundaries(costs: Sequence[float],
                             num_stages: int) -> list[int]:
    """Contiguous minimax partition of ``costs`` into ``num_stages`` stages.

    Returns boundaries like ``balanced_boundaries`` (length num_stages+1,
    b[0]=0, b[-1]=len(costs), strictly increasing). O(S·N²) exact DP —
    N is the unit count (19 for MobileNetV2), so this is microseconds.
    """
    n = len(costs)
    if not (1 <= num_stages <= n):
        raise ValueError(f"cannot split {n} units into {num_stages} stages")
    prefix = np.concatenate([[0.0], np.cumsum(costs)])

    def seg(i: int, j: int) -> float:      # cost of units [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # best[s][i] = minimal bottleneck cost splitting units [0, i) into s stages
    best = np.full((num_stages + 1, n + 1), INF)
    cut = np.zeros((num_stages + 1, n + 1), np.int64)
    best[0][0] = 0.0
    for s in range(1, num_stages + 1):
        for i in range(s, n + 1):
            for j in range(s - 1, i):
                v = max(best[s - 1][j], seg(j, i))
                # `<=` keeps the *latest* cut among minimax ties, pushing
                # extra units onto the earliest stages — the same
                # front-loading convention as balanced_boundaries (and the
                # reference's split, which gives rank 0 the stem plus the
                # first blocks, model_parallel.py:102-104).
                if v <= best[s][i]:
                    best[s][i] = v
                    cut[s][i] = j
    bounds = [n]
    for s in range(num_stages, 0, -1):
        bounds.append(int(cut[s][bounds[-1]]))
    return bounds[::-1]


def auto_boundaries(model: StagedModel, sample_shape: Sequence[int],
                    num_stages: int, *, train: bool = True) -> list[int]:
    """Measure unit costs and return the minimax stage boundaries."""
    return cost_balanced_boundaries(
        unit_costs(model, sample_shape, train=train), num_stages)


def microbatch_rows(batch_size: int, num_microbatches: int,
                    data_shards: int = 1) -> int:
    """Rows of ONE microbatch as a pipeline stage sees it — the batch shape
    ``auto_boundaries`` should profile at. The single home for this
    arithmetic: the single-controller runner feeds the whole global batch
    through one replica (``data_shards=1``); the SPMD pipeline splits it
    over the ``data`` axis first."""
    return max(1, batch_size // (max(1, data_shards)
                                 * max(1, num_microbatches)))
