"""distributed_model_parallel_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/pjit/pallas re-design of the capabilities of the reference
repo ``HaoKang-Timmy/distributed_model_parallel`` (see /root/repo/SURVEY.md):

* single-host data parallelism (the ``nn.DataParallel`` capability,
  reference ``data_parallel.py:76-78``) via batch-dimension ``NamedSharding``
  under ``jit``;
* multi-process DDP-equivalent gradient allreduce (reference ``Readme.md:144-157``)
  via ``shard_map`` + ``lax.psum`` over an ICI mesh, with SyncBatchNorm and a
  sparse-embedding gradient path;
* inter-layer model/pipeline parallelism (reference ``distributed_layers.py``,
  ``model_parallel.py``, ``utils.py``) via stage-partitioned models with both a
  naive 1-batch-in-flight schedule (parity) and micro-batched schedules;
* a training harness: SGD + cosine annealing + linear warmup, top-1/5 metrics,
  per-batch timing, checkpoint/resume, text+structured logging
  (reference ``data_parallel.py:89-171``, ``utils.py:34-229``);
* a model zoo (MobileNetV2 ± BatchNorm, ResNet-18/50, a Transformer LM for
  long-context and multi-axis mesh parallelism) and a dataset registry
  (reference ``model/mobilenetv2.py``, ``dataset/dataset_collection.py``).

Everything is SPMD-first: pick a ``Mesh``, annotate shardings, let XLA insert
collectives.
"""

__version__ = "0.1.0"

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # ``jax.shard_map`` is the stable name only in newer jax; the jax this
    # container ships exposes it as ``jax.experimental.shard_map`` with the
    # old ``check_rep`` kwarg where the codebase says ``check_vma``.
    # Polyfill the stable name (must run before any submodule — every
    # consumer imports through this package) so one codebase spans both.
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def _shard_map_compat(f, /, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_legacy(f, *args, **kwargs)

    _jax.shard_map = _shard_map_compat

from distributed_model_parallel_tpu.config import (  # noqa: F401
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    TrainConfig,
)
from distributed_model_parallel_tpu.mesh import (  # noqa: F401
    MeshSpec,
    best_effort_distributed_init,
    make_mesh,
)
