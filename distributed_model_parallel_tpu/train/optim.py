"""Optimizer factory: SGD/AdamW/LAMB/LARS, cosine annealing with linear
warmup.

``sgd`` mirrors the reference recipe — ``optim.SGD(lr, momentum=0.9,
weight_decay=1e-4)`` + ``CosineAnnealingLR(T_max=90)`` +
``pytorch_warmup.UntunedLinearWarmup`` (reference ``data_parallel.py:89-96``,
``model_parallel.py:105-108``) — as a single optax chain with a per-step
schedule; ordering matches torch SGD (weight decay added to the raw gradient
*before* the momentum buffer update). ``lars``/``lamb`` are the layerwise-
adaptive large-batch optimizers the reference's large-batch study
(``Readme.md:159-211``) motivates; ``adamw`` uses decoupled weight decay.
"""

from __future__ import annotations

import dataclasses

import optax

from distributed_model_parallel_tpu.config import OptimizerConfig


def make_schedule(config: OptimizerConfig, steps_per_epoch: int,
                  epochs: int) -> optax.Schedule:
    """Linear warmup then cosine annealing to 0.

    ``cosine_decay_steps`` defaults to the full run (the reference uses
    T_max=90 *epochs* with per-epoch stepping; here the schedule is per-step,
    the idiomatic JAX form — same curve, finer granularity).
    """
    decay_steps = config.cosine_decay_steps
    if decay_steps is None:
        decay_steps = max(1, steps_per_epoch * epochs)
    warmup = max(0, config.warmup_steps)
    if warmup == 0:
        return optax.cosine_decay_schedule(config.learning_rate, decay_steps)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=config.learning_rate,
        warmup_steps=warmup,
        decay_steps=warmup + decay_steps,
        end_value=0.0,
    )


def make_optimizer(config: OptimizerConfig, steps_per_epoch: int,
                   epochs: int) -> optax.GradientTransformation:
    # steps_per_epoch, warmup_steps and cosine_decay_steps all count gradient
    # computations (micro-steps); the inner schedule ticks once per applied
    # update, i.e. per accum_steps of them — convert every length to update
    # units so the lr curve matches the accum_steps=1 run. Totals are divided
    # across the whole run (MultiSteps carries partial accumulations over
    # epoch boundaries, so per-epoch flooring would undercount and leave the
    # tail of training at lr=0).
    accum = max(1, config.accum_steps)
    if accum > 1:
        config = dataclasses.replace(
            config,
            warmup_steps=config.warmup_steps // accum,
            cosine_decay_steps=(None if config.cosine_decay_steps is None
                                else max(1, config.cosine_decay_steps // accum)))
    total_updates = max(1, (steps_per_epoch * epochs) // accum)
    schedule = make_schedule(config, total_updates, 1)
    parts = []
    if config.grad_clip_norm is not None:
        parts.append(optax.clip_by_global_norm(config.grad_clip_norm))
    if config.fused and config.name != "sgd":
        raise ValueError(
            f"OptimizerConfig.fused implements the sgd recipe "
            f"(ops/pallas_optim.fused_sgd), got name={config.name!r} — "
            f"no silent ignores")
    if config.name == "sgd":
        if config.fused:
            # One Pallas kernel per flat parameter bucket instead of the
            # per-leaf elementwise chain below — same math, parity-tested
            # (ops/pallas_optim.py; pure-XLA fallback off-TPU). The
            # schedule stays a closure over the state's update count, so
            # lr_shrink rebuilds keep the opt_state structure.
            from distributed_model_parallel_tpu.ops.pallas_optim import (
                fused_sgd,
            )

            parts.append(fused_sgd(schedule, momentum=config.momentum,
                                   weight_decay=config.weight_decay,
                                   nesterov=config.nesterov))
        else:
            if config.weight_decay:
                parts.append(optax.add_decayed_weights(config.weight_decay))
            parts.append(optax.sgd(learning_rate=schedule,
                                   momentum=config.momentum or None,
                                   nesterov=config.nesterov))
    elif config.name == "adamw":
        parts.append(optax.adamw(learning_rate=schedule,
                                 weight_decay=config.weight_decay))
    elif config.name == "lamb":
        parts.append(optax.lamb(learning_rate=schedule,
                                weight_decay=config.weight_decay))
    elif config.name == "adafactor":
        # Sub-linear optimizer memory (factored second moments) — pairs
        # with FSDP/ZeRO for the largest-model regime.
        parts.append(optax.adafactor(learning_rate=schedule,
                                     weight_decay_rate=config.weight_decay
                                     or None))
    elif config.name == "adam":
        parts.append(optax.adam(learning_rate=schedule))
    elif config.name == "lars":
        parts.append(optax.lars(learning_rate=schedule,
                                weight_decay=config.weight_decay,
                                momentum=config.momentum,
                                nesterov=config.nesterov))
    else:
        raise KeyError(
            f"unknown optimizer {config.name!r}; known: sgd, adam, adamw, "
            f"adafactor, lamb, lars")
    tx = optax.chain(*parts)
    if config.accum_steps > 1:
        # Running-mean gradient accumulation: the inner transform (and so the
        # lr schedule) advances once per accum_steps calls; between
        # boundaries the update is all-zeros, so params hold still.
        tx = optax.MultiSteps(tx, every_k_schedule=config.accum_steps)
    return tx
