"""Training harness: jitted SPMD train/eval steps + the epoch driver.

Covers the reference's two driver scripts' harness behavior
(``data_parallel.py:99-172``, ``utils.py:34-210``): cross-entropy training
with SGD + cosine + warmup, top-1/5 accuracy, per-batch compute/data timing,
every-N-step prints, per-epoch text logging, best-acc checkpointing with
resume.

Data parallelism here is the GSPMD path: the batch is sharded over the mesh's
``data`` axis, parameters are replicated, and XLA inserts the gradient
allreduce — the TPU-native equivalent of both ``nn.DataParallel``'s
scatter/replicate/gather (reference ``Readme.md:17-143``) and DDP's bucketed
ring-allreduce (``Readme.md:144-157``). BatchNorm under this path sees the
global batch (SyncBN semantics); per-replica BN lives in the explicit
``shard_map`` DDP path (parallel/ddp.py).
"""

from __future__ import annotations

import dataclasses
import warnings
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from distributed_model_parallel_tpu.config import TrainConfig
from distributed_model_parallel_tpu.data.loader import (
    BatchLoader,
    augment_batch,
    maybe_device_prefetch,
    maybe_prefetch,
    normalize,
    resize_batch,
    resolve_input_size,
)
from distributed_model_parallel_tpu.data.registry import ArrayDataset, load_dataset
from distributed_model_parallel_tpu.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.models import get_model
from distributed_model_parallel_tpu.models.staged import StagedModel
from distributed_model_parallel_tpu.train.checkpoint import Checkpointer
from distributed_model_parallel_tpu.train.logging_util import RunLogger
from distributed_model_parallel_tpu.train.metrics import AverageMeter, StepTimer, topk_correct
from distributed_model_parallel_tpu.train.optim import make_optimizer
from distributed_model_parallel_tpu.utils import health, tracing
from distributed_model_parallel_tpu.utils.tracing import span


def _filter_expected_batch_donation_warnings() -> None:
    """Silence jax's "donated buffers were not usable" warning ONLY for
    the uint8/int32 batch buffers the train steps donate BY DESIGN (no
    same-shaped output to alias with — ownership transfer still frees
    them at dispatch, see ``_build_steps``). Left loud, the known-noise
    warning trains users to ignore donation warnings — including a
    future REAL one where the f32 state alias drops (the 2x-live-memory
    regression ``utils/profiling.assert_donation`` exists to catch).
    The filter is shape-anchored: a dropped float buffer breaks the
    pattern and stays loud. Audits are unaffected (``donation_report``
    captures under ``simplefilter("always")``, which overrides this
    filter in-context). Installed at import; re-invoke after anything
    that resets the process filters (pytest does per test)."""
    warnings.filterwarnings(
        "ignore",
        message=r"Some donated buffers were not usable: "
                r"(ShapedArray\((uint8|int32)[^)]*\)(, )?)+\.")


_filter_expected_batch_donation_warnings()


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    model_state: Any          # BN running stats (tuple over units)
    opt_state: Any
    # Exponential moving average of params + model_state (None unless
    # OptimizerConfig.ema_decay is set); evaluation/checkpoint-selection
    # read these when present — the standard large-batch trick the
    # reference lacks. BN running stats are averaged alongside the weights
    # so evaluation never pairs averaged weights with live statistics.
    ema_params: Any = None
    ema_model_state: Any = None


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def eval_now(epoch: int, total_epochs: int, eval_every: int) -> bool:
    """Eval-cadence rule shared by the DP and pipeline trainers: every Nth
    epoch, and always the final one (so final-loss artifacts exist)."""
    return ((epoch + 1) % max(1, eval_every) == 0
            or epoch == total_epochs - 1)


def make_train_step(model: StagedModel, tx: optax.GradientTransformation,
                    *, mean, std, augment: bool = True,
                    dtype=jnp.float32, ema_decay: float | None = None,
                    resize_to: int | None = None) -> Callable:
    """Returns step(state, rng, images_u8, labels) -> (state, metrics).

    Augmentation + normalization run on-device so XLA fuses them with the
    forward pass; metrics are computed on-device as sums (psum-friendly).
    With ``ema_decay``, ``state.ema_params`` tracks
    ``d*ema + (1-d)*params`` after each update. ``resize_to`` upsamples the
    uint8 batch on-device before augmentation (the 224px finetune input
    path; data/loader.resize_batch).
    """

    def loss_fn(params, model_state, images, labels):
        logits, new_state = model.apply(params, model_state, images, train=True)
        loss = cross_entropy(logits, labels)
        return loss, (logits, new_state)

    def step(state: TrainState, rng: jax.Array, images_u8, labels):
        if resize_to is not None:
            images_u8 = resize_batch(images_u8, resize_to)
        images_u8 = augment_batch(rng, images_u8) if augment else images_u8
        images = normalize(images_u8, mean, std, dtype)
        (loss, (logits, new_model_state)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params, state.model_state, images, labels)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_ema = state.ema_params
        new_ema_state = state.ema_model_state
        if ema_decay is not None:
            step_size = 1.0 - ema_decay
            if hasattr(new_opt_state, "mini_step"):
                # Gradient accumulation (optax.MultiSteps): only count real
                # optimizer updates — mini_step resets to 0 exactly when one
                # fires — so the EMA horizon matches the equivalent
                # big-batch run instead of shrinking by accum_steps.
                step_size = jnp.where(new_opt_state.mini_step == 0,
                                      step_size, 0.0)
            new_ema = optax.incremental_update(new_params, state.ema_params,
                                               step_size)
            # BN running stats averaged on the same horizon — evaluating
            # averaged weights against live statistics skews the metrics.
            new_ema_state = optax.incremental_update(
                new_model_state, state.ema_model_state, step_size)
        metrics = {"loss": loss, "batch": jnp.asarray(labels.shape[0], jnp.float32),
                   **topk_correct(logits, labels)}
        return (TrainState(step=state.step + 1, params=new_params,
                           model_state=new_model_state,
                           opt_state=new_opt_state,
                           ema_params=new_ema,
                           ema_model_state=new_ema_state), metrics)

    return step


def make_multi_step(model: StagedModel, tx: optax.GradientTransformation,
                    *, image_shape, mean, std, augment: bool = True,
                    dtype=jnp.float32, ema_decay: float | None = None,
                    resize_to: int | None = None) -> Callable:
    """K train steps per dispatched program (lax.scan) over a
    device-resident dataset.

    multi(state, rng, images_flat, labels_all, idx[K, B]) -> (state,
    stacked metrics). Each scan step gathers its batch from the on-device
    dataset by index — no host→device image traffic and no per-step
    dispatch, the two costs that dominate small-step training through a
    remote device transport. The per-step math is exactly
    ``make_train_step``'s.
    """
    step = make_train_step(model, tx, mean=mean, std=std, augment=augment,
                           dtype=dtype, ema_decay=ema_decay,
                           resize_to=resize_to)
    h, w, c = image_shape

    def multi(state: TrainState, rng: jax.Array, images_flat, labels_all, idx):
        rngs = jax.random.split(rng, idx.shape[0])

        def body(st, xs):
            r, ib = xs
            im = jnp.take(images_flat, ib, axis=0).reshape(
                ib.shape[0], h, w, c)
            lb = jnp.take(labels_all, ib, axis=0)
            return step(st, r, im, lb)

        return jax.lax.scan(body, state, (rngs, idx))

    return multi


def make_eval_step(model: StagedModel, *, mean, std, dtype=jnp.float32,
                   use_ema: bool = False,
                   resize_to: int | None = None) -> Callable:
    def step(state: TrainState, images_u8, labels):
        if resize_to is not None:
            images_u8 = resize_batch(images_u8, resize_to)
        images = normalize(images_u8, mean, std, dtype)
        params = state.ema_params if use_ema else state.params
        model_state = state.ema_model_state if use_ema else state.model_state
        logits, _ = model.apply(params, model_state, images,
                                train=False)
        return {"loss": cross_entropy(logits, labels),
                "batch": jnp.asarray(labels.shape[0], jnp.float32),
                **topk_correct(logits, labels)}

    return step


@dataclasses.dataclass
class EpochResult:
    loss: float
    acc1: float
    acc5: float
    step_time: float
    data_time: float


class Trainer:
    """Data-parallel epoch driver over a mesh (GSPMD path)."""

    def __init__(self, config: TrainConfig, spec: MeshSpec | None = None,
                 *, train_ds: ArrayDataset | None = None,
                 eval_ds: ArrayDataset | None = None):
        self.plan_decision = None
        if config.strategy == "auto" and spec is not None:
            raise ValueError(
                "strategy='auto' plans the mesh layout itself and cannot "
                "honor an explicit MeshSpec; resolve the plan first "
                "(autotune.plan_for_cnn) or pass a concrete strategy — "
                "no silent ignores")
        if config.strategy == "auto" and spec is None:
            # Cost-model-driven layout (autotune/, docs/AUTOTUNE.md):
            # probe the model, enumerate feasible (dp, pp) x strategy
            # layouts of the LIVE device count, rank with the alpha-beta
            # comm/compute model, and rewrite strategy + mesh from the
            # winner. On an elastic restart this REPLANS on the refitted
            # mesh instead of blindly shrinking dp.
            from distributed_model_parallel_tpu.autotune.planner import (
                plan_for_cnn,
            )
            from distributed_model_parallel_tpu.train.elastic import (
                live_device_count,
            )

            config, self.plan_decision = plan_for_cnn(config,
                                                      live_device_count())
        self.elastic_decision = None
        if config.elastic and spec is None and self.plan_decision is None:
            # Elastic restart: rebuild the mesh at the largest dp degree
            # the live device count supports (train/elastic.py) — the
            # degraded-slice restart path. An explicit `spec` means the
            # caller already chose a topology; strategy="auto" replans
            # above instead.
            from distributed_model_parallel_tpu.train.elastic import (
                fit_mesh_to_devices,
                live_device_count,
            )

            mesh_cfg, self.elastic_decision = fit_mesh_to_devices(
                config.mesh, live_device_count(),
                batch_size=config.data.batch_size)
            config = config.replace(mesh=mesh_cfg)
        self.config = config
        if config.optimizer.fused and config.strategy == "fsdp":
            raise ValueError(
                "OptimizerConfig.fused runs the update over flat "
                "coalesced parameter buckets, which would gather the "
                "ZeRO-sharded params/opt state back to full size on "
                "every step; use it with replicated-param strategies "
                "(gspmd/ddp) — no silent ignores")
        if config.grad_bucket_mb is not None and config.strategy != "ddp":
            raise ValueError(
                f"grad_bucket_mb routes the gradient allreduce through "
                f"ops/collectives.bucketed_psum, which needs the explicit "
                f"per-replica grad path (strategy='ddp'); "
                f"strategy={config.strategy!r} leaves the reduction to "
                f"XLA's partitioner — no silent ignores")
        if (config.grad_bucket_mb is not None
                and config.ddp_allreduce == "hierarchical"):
            raise ValueError(
                "grad_bucket_mb has no effect on the hierarchical "
                "transport (hierarchical_psum_tree flattens the whole "
                "tree into one two-level reduction, no size-capped "
                "buckets); use ddp_allreduce='psum'/'bucketed'/'ring' "
                "with it — no silent ignores")
        self.spec = spec if spec is not None else make_mesh(config.mesh)
        if train_ds is None or eval_ds is None:
            train_ds, eval_ds = load_dataset(config.data)
        self.train_ds, self.eval_ds = train_ds, eval_ds

        axis = self.spec.data_axis if config.model.batchnorm == "sync" else None
        self.model = get_model(config.model, axis_name=axis)

        # Multi-process (multi-host) runs: every process computes the same
        # global batch order; the loaders materialize only the local slice
        # and _shard_batch stitches the global array
        # (mesh.host_local_batch_to_global). Single-process runs are
        # untouched (shard_by_process degenerates to the whole batch).
        multiprocess = jax.process_count() > 1
        if multiprocess and config.device_resident_data:
            raise ValueError(
                "device_resident_data assumes a single-process runtime "
                "(the dataset upload and index gathers are per-process); "
                "use the streaming path on multi-host")
        self.train_loader = BatchLoader(
            train_ds, config.data.batch_size, shuffle=config.data.shuffle,
            seed=config.data.seed, use_native=config.data.use_native,
            num_workers=config.data.num_workers,
            shard_by_process=multiprocess)
        self.eval_loader = BatchLoader(
            eval_ds, min(config.data.eval_batch_size, len(eval_ds)),
            shuffle=False, use_native=config.data.use_native,
            num_workers=config.data.num_workers,
            shard_by_process=multiprocess)

        self.tx = make_optimizer(config.optimizer, len(self.train_loader),
                                 config.epochs)
        # On-device resize stage when the configured input size differs from
        # the dataset's native resolution (the 224px finetune input path):
        # the model initializes at the *target* size and every step upsamples
        # the uint8 batch before augmentation.
        resize_to, in_hw = resolve_input_size(train_ds.images.shape,
                                              config.data.image_size)
        sample = jnp.zeros((2, in_hw, in_hw, train_ds.images.shape[3]),
                           jnp.uint8)
        params, model_state = self.model.init(
            jax.random.key(config.seed),
            normalize(sample, train_ds.mean, train_ds.std))
        # Replicate state over the mesh; shard batches on the data axis.
        self._repl = self.spec.replicated()
        self._batch_sh = self.spec.batch_sharded()
        kw = dict(mean=train_ds.mean, std=train_ds.std, resize_to=resize_to)

        ema = config.optimizer.ema_decay
        if ema is not None and not (0.0 <= ema <= 1.0):
            raise ValueError(f"ema_decay must be in [0, 1], got {ema}")
        # Everything _build_steps needs to (re)construct the jitted step
        # functions — stored so a recovery-time LR shrink can rebuild them
        # without re-running state init (train/resilience.py).
        self._kw = kw
        self._in_hw = in_hw
        if config.strategy == "ddp":
            if config.device_resident_data:
                raise ValueError(
                    "device_resident_data is only supported with "
                    "strategy='gspmd' (the ddp path materializes per-replica "
                    "batches on host)")
            if ema is not None:
                raise ValueError(
                    "ema_decay is supported on the gspmd/fsdp strategies")
            # Explicit per-replica engine: BN state carries a leading
            # per-replica axis sharded over the data axis (parallel/ddp.py).
            from distributed_model_parallel_tpu.parallel.ddp import (
                replicate_model_state,
            )

            model_state = replicate_model_state(model_state, self.spec.num_data)
            state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                               model_state=model_state,
                               opt_state=self.tx.init(params))
            self._state_sh = TrainState(
                step=self._repl, params=self._repl,
                model_state=self.spec.batch_sharded(),
                opt_state=self._repl)
            self.state = jax.device_put(state, self._state_sh)
        elif config.strategy in ("gspmd", "fsdp"):
            if config.strategy == "fsdp":
                # ZeRO-3: params + optimizer state live sharded over `data`;
                # XLA's partitioner inserts the just-in-time all-gathers and
                # gradient reduce-scatters (parallel/fsdp.py). Shard params
                # *before* building optimizer state, and init that state
                # directly into its sharded layout (jit + out_shardings) so
                # the full-size tree never materializes on one device.
                from distributed_model_parallel_tpu.parallel.fsdp import (
                    tree_shardings,
                )

                params_sh = tree_shardings(params, self.spec)
                params = jax.device_put(params, params_sh)
                opt_sh = tree_shardings(jax.eval_shape(self.tx.init, params),
                                        self.spec)
                opt_state = jax.jit(self.tx.init, out_shardings=opt_sh)(params)
                self._state_sh = TrainState(
                    step=self._repl, params=params_sh,
                    model_state=self._repl, opt_state=opt_sh,
                    ema_params=params_sh if ema is not None else None,
                    ema_model_state=(self._repl if ema is not None else None))
            else:
                self._state_sh = self._repl
                opt_state = self.tx.init(params)
            # EMA starts at the initial weights/stats — as real copies:
            # params and ema_params live in one donated state, and donation
            # rejects the same buffer appearing twice.
            ema_params = (jax.tree.map(jnp.copy, params) if ema is not None
                          else None)
            ema_model_state = (jax.tree.map(jnp.copy, model_state)
                               if ema is not None else None)
            state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                               model_state=model_state, opt_state=opt_state,
                               ema_params=ema_params,
                               ema_model_state=ema_model_state)
            self.state = jax.device_put(state, self._state_sh)
            if config.device_resident_data:
                # Fast path: dataset lives on device; K steps per dispatch.
                if getattr(train_ds, "is_lazy", False):
                    raise ValueError(
                        "device_resident_data requires materialized pixels "
                        "but the dataset streams lazily from disk (auto "
                        "when decoded size exceeds the in-memory cap); set "
                        "DataConfig.lazy_decode=False to decode eagerly, "
                        "or drop device_resident_data")
                n = len(train_ds)
                self._dev_images = jax.device_put(
                    train_ds.images.reshape(n, -1), self._repl)
                self._dev_labels = jax.device_put(
                    np.asarray(train_ds.labels), self._repl)
        elif config.strategy == "spmd_pipeline":
            # Single-program GPipe over the `stage` mesh axis for staged
            # CNNs (parallel/spmd_cnn_pipeline.py) — the multi-host-capable
            # counterpart of PipelineTrainer's single-controller runtime,
            # driven by this harness because its step has the same
            # (state, rng, images, labels) -> (state, metrics) contract as
            # the GSPMD step. Params stay replicated (each device computes
            # only its own stage), so eval rides the ordinary batch-sharded
            # GSPMD forward.
            if config.device_resident_data:
                raise ValueError(
                    "device_resident_data is only supported with "
                    "strategy='gspmd'")
            if ema is not None:
                raise ValueError(
                    "ema_decay is supported on the gspmd/fsdp strategies")
            if self.spec.num_stages < 2:
                raise ValueError(
                    "strategy='spmd_pipeline' needs mesh.stage >= 2 "
                    "(use 'gspmd' for pure data parallelism)")
            if config.pipeline_schedule not in ("gpipe", "1f1b"):
                raise ValueError(
                    f"strategy='spmd_pipeline' implements the gpipe and "
                    f"1f1b schedules, got "
                    f"{config.pipeline_schedule!r} (interleaved is a "
                    f"single-controller PipelineRunner schedule — no "
                    f"silent ignores)")
            if config.virtual_stages != 1 and \
                    config.pipeline_schedule != "1f1b":
                raise ValueError(
                    "strategy='spmd_pipeline' supports interleaved "
                    "virtual stages only under pipeline_schedule='1f1b' "
                    "(spmd_cnn_pipeline.make_cnn_1f1b_fwd_bwd); gpipe's "
                    "whole-program AD would gain nothing — no silent "
                    "ignores")
            boundaries = config.stage_boundaries
            # Under interleaved virtual stages the model splits into
            # D = S*V CHUNKS, so boundaries (explicit or auto) are chunk
            # boundaries — D+1 cut points, not S+1.
            n_chunks = self.spec.num_stages * config.virtual_stages
            if (boundaries is not None
                    and len(boundaries) != n_chunks + 1):
                raise ValueError(
                    f"stage_boundaries has {len(boundaries)} cut points "
                    f"but the pipeline splits into {n_chunks} chunks "
                    f"({self.spec.num_stages} stages x "
                    f"{config.virtual_stages} virtual) — provide "
                    f"{n_chunks + 1}")
            if boundaries is None and config.auto_partition:
                from distributed_model_parallel_tpu.parallel.auto_partition import (
                    auto_boundaries,
                    microbatch_rows,
                )

                micro = microbatch_rows(config.data.batch_size,
                                        config.num_microbatches,
                                        self.spec.num_data)
                boundaries = auto_boundaries(
                    self.model,
                    (micro, in_hw, in_hw, train_ds.images.shape[3]),
                    n_chunks)
            self._boundaries = boundaries
            self._state_sh = self._repl
            state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                               model_state=model_state,
                               opt_state=self.tx.init(params))
            self.state = jax.device_put(state, self._state_sh)
            # masked dispatch on CPU: conv backward inside lax.switch loses
            # intra-op threading on the XLA CPU backend (~35x slower —
            # spmd_cnn_pipeline.py); TPU keeps the switch default.
            self._dispatch = ("masked" if jax.devices()[0].platform == "cpu"
                              else "switch")
        else:
            raise KeyError(f"unknown strategy {config.strategy!r}")
        self._build_steps()

        self._max_inflight = max(1, config.max_inflight_steps)
        from distributed_model_parallel_tpu.train.preemption import (
            PreemptionGuard,
        )

        self.preemption = PreemptionGuard()
        self.logger = RunLogger(
            config.log_dir, config.log_name,
            meta=dict(workload="cnn", model=config.model.name,
                      strategy=config.strategy,
                      batch_size=config.data.batch_size,
                      mesh=config.mesh.axis_sizes(),
                      steps_per_dispatch=config.steps_per_dispatch
                      if config.device_resident_data else 1))
        # Span sink for this thread (utils/tracing.py): every span opened
        # while this trainer runs — including the resume/restore below and
        # checkpoint I/O deep in train/checkpoint.py — lands on this run's
        # stream (and inherits its tenant tag under the orchestrator).
        tracing.install(self.logger.telemetry)
        # Live status exporter (utils/statusz.py): start the process's
        # exporter when a port is configured (else join the running one —
        # orchestrated tenants land on the fleet's) and publish this
        # run's live state under /statusz. No-op when neither
        # statusz_port nor DMP_STATUSZ_PORT is set.
        from distributed_model_parallel_tpu.utils import statusz

        statusz.maybe_serve(config.statusz_port)
        statusz.register_trainer(self, "cnn")
        from distributed_model_parallel_tpu.train.resilience import (
            RecoverySupervisor,
        )
        from distributed_model_parallel_tpu.utils.faults import (
            FaultInjector,
            validate_corruption_plan,
        )

        # Slice identity for the device-health sentinel feeds
        # (utils/health.py; no-ops unless an orchestrator installed a
        # monitor): step windows, guarded syncs, checkpoint I/O and stall
        # escalations are all attributed to these devices.
        self._device_ids = tuple(sorted(
            d.id for d in np.asarray(self.spec.mesh.devices).flat))
        self.faults = FaultInjector(config.recovery.faults)
        if config.consistency_every and config.strategy == "fsdp":
            raise ValueError(
                "consistency_every needs state replicated over the data "
                "axis to compare; strategy='fsdp' shards params + "
                "optimizer state over it — no redundancy, no cross-replica "
                "check. No silent ignores")
        # Topology validation first: on a topology that CANNOT arm the
        # sentinel, the supervisor's "set consistency_every >= 1" advice
        # would send the user into the rejection above.
        validate_corruption_plan(
            self.faults.plan,
            # FSDP shards state over the data axis — zero replicated copies.
            0 if config.strategy == "fsdp" else self.spec.num_data,
            context=f"strategy={config.strategy!r}")
        self.ckpt = Checkpointer(config.checkpoint_dir,
                                 keep=config.recovery.keep_checkpoints,
                                 injector=self.faults,
                                 meta_fn=self._ckpt_meta)
        self.resilience = RecoverySupervisor(
            config.recovery, logger=self.logger, ckpt=self.ckpt,
            preemption=self.preemption, slot="good", injector=self.faults,
            check_finite_every=config.check_finite_every,
            consistency_every=config.consistency_every,
            device_ids=self._device_ids)
        from distributed_model_parallel_tpu.train.guards import GuardRunner

        self.guards = GuardRunner(
            check_finite_every=config.check_finite_every,
            stall_budget_s=config.stall_budget_s, logger=self.logger,
            watchdog_interval_s=config.recovery.watchdog_interval_s,
            on_stall=self.resilience.on_stall, injector=self.faults,
            device_ids=self._device_ids)
        from distributed_model_parallel_tpu.train.consistency import (
            ConsistencySentinel,
        )

        self.sentinel = ConsistencySentinel(
            config.consistency_every, self.spec, logger=self.logger,
            guards=self.guards,
            barrier_timeout_s=config.recovery.barrier_timeout_s)
        from distributed_model_parallel_tpu.train.elastic import (
            EmergencyCheckpointer,
        )

        self.emergency = EmergencyCheckpointer(
            self.ckpt, "emergency", config.emergency_every,
            logger=self.logger, wait=not config.async_checkpoint)
        self.best_acc = 0.0
        self.start_epoch = 0
        # Cooperative-scheduling hook (orchestrator/): when set, called with
        # this trainer at EVERY train-step boundary, before the preemption
        # poll — so an external scheduler can pause the run mid-epoch
        # (block in the hook), and a preemption it requests while the run
        # is paused is honored before the next step dispatches.
        self.step_hook: Callable[["Trainer"], None] | None = None
        # Per-step augmentation rng is derived from (base key, global step)
        # — stateless, so a resumed run replays the exact stream an
        # uninterrupted run would have used (train/elastic.py). The host
        # mirrors the on-device TrainState.step counter.
        self._rng_base = jax.random.key(config.seed + 1)
        self._global_step = 0
        # Trainer-authoritative loader position (epoch, consumed batches);
        # see _resume_tree for why the loader's own state is not trusted.
        self._loader_pos = (0, 0)
        if self.elastic_decision is not None and self.elastic_decision.changed:
            self.logger.log_line(self.elastic_decision.describe())
            self.logger.telemetry.event(self.elastic_decision.describe())
        if config.resume and any(self.ckpt.exists(n)
                                 for n in ("ckpt", "preempt", "emergency",
                                           "good")):
            self._resume()
        if self.plan_decision is not None:
            # After _resume so an elastic re-plan is stamped with the
            # exact global step the run continues from.
            from distributed_model_parallel_tpu.autotune.planner import (
                emit_plan_record,
            )

            emit_plan_record(self.logger.telemetry, self.plan_decision,
                             global_step=self._global_step)
            self.logger.log_line(self.plan_decision.describe())

    def _build_steps(self) -> None:
        """(Re)build the jitted step functions from the current config and
        ``self.tx``. Called once at init and again by ``_apply_lr_shrink``
        after a recovery rebuilds the optimizer: state, shardings and the
        on-device dataset are untouched, so a restored ``opt_state`` stays
        structurally compatible (the LR lives in the schedule closure, not
        in the state)."""
        config = self.config
        kw = self._kw
        ema = config.optimizer.ema_decay
        self._multi_step = None
        if config.strategy == "ddp":
            from distributed_model_parallel_tpu.parallel.ddp import (
                make_ddp_eval_step,
                make_ddp_train_step,
            )

            bucket_bytes = config.ddp_bucket_bytes
            allreduce = config.ddp_allreduce
            if config.grad_bucket_mb is not None:
                # The Reducer's bucket_cap_mb knob: size-capped flat
                # buckets in reverse leaf order, fired as the backward
                # produces them (ops/collectives.bucketed_psum).
                bucket_bytes = int(config.grad_bucket_mb * 1024 * 1024)
                if allreduce == "psum":
                    allreduce = "bucketed"
            self._train_step = make_ddp_train_step(
                self.model, self.tx, self.spec,
                augment=config.data.augment,
                bucket_bytes=bucket_bytes,
                allreduce=allreduce, **kw)
            self._eval_step = make_ddp_eval_step(self.model, self.spec, **kw)
        elif config.strategy in ("gspmd", "fsdp"):
            # Full-step donation: the state (in-place param/opt update)
            # AND the input batch. The uint8/int32 batch buffers have no
            # same-shaped output to alias with, but donating them hands
            # ownership to the runtime so their device memory frees at
            # dispatch instead of at the next GC — with the device
            # prefetcher keeping depth extra batches resident, that is
            # the difference between depth+1 and 2*depth live batches.
            # utils/profiling.assert_donation is the trace-time proof the
            # state aliasing actually held (perf smoke + bench.py).
            self._train_step = jax.jit(
                make_train_step(self.model, self.tx, ema_decay=ema,
                                augment=config.data.augment, **kw),
                in_shardings=(self._state_sh, self._repl, self._batch_sh,
                              self._batch_sh),
                out_shardings=(self._state_sh, self._repl),
                donate_argnums=(0, 2, 3))
            self._eval_step = jax.jit(
                make_eval_step(self.model, use_ema=ema is not None, **kw),
                in_shardings=(self._state_sh, self._batch_sh, self._batch_sh),
                out_shardings=self._repl)
            if config.device_resident_data:
                from jax.sharding import NamedSharding, PartitionSpec as P

                idx_sh = NamedSharding(self.spec.mesh,
                                       P(None, self.spec.data_axis))
                self._multi_step = jax.jit(
                    make_multi_step(self.model, self.tx, ema_decay=ema,
                                    image_shape=self.train_ds.images.shape[1:],
                                    augment=config.data.augment, **kw),
                    in_shardings=(self._state_sh, self._repl, self._repl,
                                  self._repl, idx_sh),
                    out_shardings=(self._state_sh, self._repl),
                    donate_argnums=(0,))
        elif config.strategy == "spmd_pipeline":
            from distributed_model_parallel_tpu.parallel.spmd_cnn_pipeline import (
                make_spmd_cnn_train_step,
            )

            in_hw = self._in_hw
            self._train_step = jax.jit(
                make_spmd_cnn_train_step(
                    self.model, self.spec, self.tx,
                    sample_shape=(2, in_hw, in_hw,
                                  self.train_ds.images.shape[3]),
                    num_microbatches=config.num_microbatches,
                    boundaries=self._boundaries,
                    bn_momentum=config.model.bn_momentum,
                    augment=config.data.augment,
                    stage_dispatch=self._dispatch,
                    schedule=config.pipeline_schedule,
                    virtual_stages=config.virtual_stages, **kw),
                in_shardings=(self._state_sh, self._repl, self._batch_sh,
                              self._batch_sh),
                out_shardings=(self._state_sh, self._repl),
                donate_argnums=(0, 2, 3))
            self._eval_step = jax.jit(
                make_eval_step(self.model, use_ema=False, **kw),
                in_shardings=(self._state_sh, self._batch_sh,
                              self._batch_sh),
                out_shardings=self._repl)

    def _apply_lr_shrink(self, factor: float) -> None:
        """Recovery-time LR shrink: scale the configured LR, rebuild the
        optimizer (same opt_state structure — the schedule is a closure)
        and re-jit the step functions (train/resilience.py)."""
        opt = self.config.optimizer
        self.config = self.config.replace(
            optimizer=dataclasses.replace(
                opt, learning_rate=opt.learning_rate * factor))
        self.tx = make_optimizer(self.config.optimizer,
                                 len(self.train_loader), self.config.epochs)
        self._build_steps()

    # -- checkpointing (reference data_parallel.py:80-87,143-155) ------------
    def _ckpt_tree(self):
        return {"state": self.state,
                "best_acc": jnp.asarray(self.best_acc, jnp.float32),
                "epoch": jnp.asarray(self.start_epoch, jnp.int32),
                "resume": self._resume_tree()}

    def _ckpt_meta(self):
        """Manifest stamp written with every committed version: the saving
        topology + exact position, readable without restoring anything
        (train/checkpoint.py, train/elastic.py)."""
        return {"workload": "cnn",
                "mesh": {**self.config.mesh.axis_sizes(),
                         "dcn_data": self.config.mesh.dcn_data},
                "n_devices": int(np.asarray(self.spec.mesh.devices).size),
                "global_step": self._global_step}

    def _resume_tree(self):
        """The exact-continuation state riding along in every checkpoint:
        loader position, global step, and the supervisor's live budgets —
        what turns an epoch-granular restore into a mid-epoch one
        (train/elastic.py).

        The position comes from the TRAINER's own (epoch, consumed)
        bookkeeping, not the loader's: a prefetch worker that exhausts the
        underlying iterator before the consumer has dispatched anything
        auto-advances the loader's epoch on its own thread (data/loader.py)
        — only the trainer knows what was actually consumed. The loader is
        re-synced here so its state matches every checkpoint written."""
        from distributed_model_parallel_tpu.train import elastic

        ep, cur = self._loader_pos
        tree = elastic.build_resume_tree(ep, cur, len(self.train_loader),
                                         self._global_step,
                                         self.resilience.budgets())
        self.train_loader.position(int(tree["loader_epoch"]),
                                   int(tree["batch_cursor"]))
        return tree

    def _apply_resume_tree(self, restored: dict, *, budgets: bool) -> None:
        """Adopt a restored checkpoint's exact-continuation state. Legacy
        checkpoints (no "resume" subtree) degrade to the historical
        epoch-granular resume. ``budgets=False`` for in-run recovery
        restores: the LIVE retry budget/LR scale must not be refilled from
        a checkpoint written before the failure."""
        from distributed_model_parallel_tpu.train import elastic

        ri = restored.get("resume")
        if ri is None:
            self._global_step = int(jax.device_get(restored["state"].step))
            return
        ep, cur, gs, retries, lr_scale = elastic.unpack_resume_tree(ri)
        self.train_loader.load_state_dict({"epoch": ep, "batch_cursor": cur})
        self._loader_pos = (self.train_loader.epoch,
                            self.train_loader.cursor)
        self._global_step = gs
        if budgets:
            self.resilience.restore_budgets(retries, lr_scale)
            if lr_scale != 1.0:
                # Re-apply the cumulative recovery LR shrink the saving run
                # had in effect (the optimizer was rebuilt at base LR).
                self._apply_lr_shrink(lr_scale)

    def _resume(self):
        from distributed_model_parallel_tpu.train import elastic

        tmpl = self._ckpt_tree()
        # The checkpoint's TrainState may differ from the current config in
        # the optional EMA subtrees: runs resumed with ema_decay toggled,
        # and checkpoints from before ema_model_state existed (params-only
        # EMA layout). Try the current template first, then each alternate
        # layout; pre-elastic checkpoints additionally lack the "resume"
        # subtree, so every layout also gets a legacy template without it.
        st = tmpl["state"]
        layouts, seen = [], set()
        for layout in (
                st,
                st.replace(ema_params=None, ema_model_state=None),
                st.replace(ema_params=st.params,
                           ema_model_state=st.model_state),
                st.replace(ema_params=st.params, ema_model_state=None)):
            key = jax.tree.structure(layout)
            if key not in seen:          # the candidates overlap with tmpl
                seen.add(key)
                layouts.append(layout)
        templates = [{**tmpl, "state": lo} for lo in layouts]
        legacy = {k: v for k, v in tmpl.items() if k != "resume"}
        templates += [{**legacy, "state": lo} for lo in layouts]
        # Newest-valid slot wins — best-accuracy, preemption, step-cadence
        # emergency, or the recovery supervisor's per-epoch good slot —
        # restored through restore_resharded so a checkpoint from a
        # different mesh degree lands in THIS mesh's shardings; torn
        # versions/slots fall back (train/elastic.py). The good slot is
        # the last resort that makes a torn preemption save survivable
        # (the multi-tenant soak flushed this out: an injected tear_save
        # landing on a first-preemption checkpoint used to kill the
        # resume outright — scripts/dmp_soak.py).
        name, restored = elastic.elastic_restore(
            self.ckpt, templates, ("ckpt", "preempt", "emergency", "good"),
            on_fallback=self.resilience.note_fallback)
        rs = restored["state"]
        want_ema = self.config.optimizer.ema_decay is not None
        if want_ema:
            if rs.ema_params is None:
                # EMA newly enabled: seed the average at the restored state.
                rs = rs.replace(ema_params=jax.tree.map(jnp.copy, rs.params))
            if rs.ema_model_state is None:
                # Also covers the legacy params-only EMA layout.
                rs = rs.replace(
                    ema_model_state=jax.tree.map(jnp.copy, rs.model_state))
        elif rs.ema_params is not None or rs.ema_model_state is not None:
            rs = rs.replace(ema_params=None, ema_model_state=None)
        self.state = jax.device_put(rs, self._state_sh)
        self.best_acc = float(restored["best_acc"])
        self.start_epoch = int(restored["epoch"])
        self._apply_resume_tree(restored, budgets=True)
        # The best-acc slot's "epoch" leaf lags when later epochs brought
        # no accuracy improvement; the loader position is authoritative
        # for where training actually stood.
        self.start_epoch = max(self.start_epoch, self.train_loader.epoch)
        # Provenance from the version actually read (a torn-newest
        # fallback may have restored an older one).
        from distributed_model_parallel_tpu.train.checkpoint import (
            read_manifest_meta,
        )

        saved_mesh = (read_manifest_meta(self.ckpt.last_restored_path)
                      if self.ckpt.last_restored_path else {}).get("mesh")
        current_mesh = self._ckpt_meta()["mesh"]
        self.logger.telemetry.resume(
            slot=name, epoch=self.start_epoch,
            loader_epoch=self.train_loader.epoch,
            batch_cursor=self.train_loader.cursor,
            global_step=self._global_step,
            mesh=current_mesh,
            **({"saved_mesh": saved_mesh}
               if saved_mesh and saved_mesh != current_mesh else {}))
        self.logger.log_line(
            f"resume: slot {name!r} -> epoch {self.start_epoch} "
            f"batch {self.train_loader.cursor} "
            f"(global step {self._global_step})"
            + (f", resharded from mesh {saved_mesh}"
               if saved_mesh and saved_mesh != current_mesh else ""))

    def _save(self, epoch: int):
        self.start_epoch = epoch + 1
        self.ckpt.save(self._ckpt_tree(),
                       wait=not self.config.async_checkpoint)

    def _restore_good(self):
        """Recovery restore: pull the supervisor's "last good" slot (same
        tree layout as this run wrote it) back onto the devices, with
        torn-version fallback (train/resilience.py). The loader position
        and global step ride along so the retry replays exactly the
        batches the restored state had seen — budgets stay LIVE (a
        checkpoint written before the failure must not refill them)."""
        restored = self.ckpt.restore(
            self._ckpt_tree(), self.resilience.slot, allow_fallback=True,
            on_fallback=self.resilience.note_fallback)
        self.state = jax.device_put(restored["state"], self._state_sh)
        self.best_acc = float(restored["best_acc"])
        self._apply_resume_tree(restored, budgets=False)

    # -- epoch loops ---------------------------------------------------------
    def _shard_batch(self, images, labels):
        if jax.process_count() > 1:
            # Each process holds only its slice (BatchLoader shards by
            # process); stitch the global batch-sharded jax.Array.
            from distributed_model_parallel_tpu.mesh import (
                host_local_batch_to_global,
            )

            return host_local_batch_to_global((images, labels), self.spec,
                                              sharding=self._batch_sh)
        return (jax.device_put(images, self._batch_sh),
                jax.device_put(labels, self._batch_sh))

    def _prefetched(self, loader):
        return maybe_prefetch(loader, self.config.data.prefetch)

    def _input_stream(self, loader):
        """The full input pipeline: host-thread batch assembly
        (PrefetchLoader) feeding the device-resident prefetcher, which
        issues the next ``device_prefetch`` batches' sharded device_put
        (the old per-step transfer at the top of the epoch loop) while the
        current step runs. Yields device-resident (images, labels)."""
        return maybe_device_prefetch(self._prefetched(loader),
                                     self._shard_batch,
                                     self.config.data.device_prefetch)

    def _drain(self, pending: list, meters: dict, *,
               sentinel: bool = False) -> None:
        """Fetch queued device metrics and fold them into the meters.

        Metrics are held as device arrays between sync points so the host
        never blocks on a step it doesn't need yet — step k+1 dispatches
        while step k still runs (async dispatch). The reference instead
        syncs every batch via ``.item()`` on loss/accuracy (``utils.py:64-68``).
        Entries may be stacked over a leading K axis (multi-step dispatch).

        This is the trainer's sync point, so the guards (when configured)
        run here: the blocking fetch sits under the stall watchdog, and the
        fetched values (plus, at the coarser cadence, the params) get
        finiteness-checked (train/guards.py:GuardRunner). With
        ``sentinel=True`` (training drains only — eval never mutates
        state) the cross-replica consistency sentinel also advances and,
        at its cadence, fingerprints + repairs the live state
        (train/consistency.py).
        """
        with span("drain", n=len(pending)), self.guards.watch():
            host = jax.device_get(pending)
        if host and (self.guards.enabled
                     or (sentinel and self.sentinel.enabled)):
            # Entries may stack K steps (multi-step dispatch): count real
            # steps so the every-N cadence is dispatch-shape independent.
            n_steps = sum(np.atleast_1d(m["loss"]).shape[0] for m in host)
            if self.guards.enabled:
                self.guards.after_sync(
                    host, n_steps,
                    params=getattr(self.state, "params", None))
            if sentinel and self.sentinel.enabled and n_steps:
                self._run_sentinel(n_steps)
        # Vectorized meter fold: one weighted update per meter for the
        # whole drained window instead of a per-element Python float()
        # loop — at steps_per_dispatch x max_inflight entries per drain,
        # host bookkeeping must not shadow the async fetch.
        if host:
            loss = np.concatenate([np.atleast_1d(m["loss"]) for m in host])
            batch = np.concatenate([np.atleast_1d(m["batch"])
                                    for m in host]).astype(np.float64)
            c1 = np.concatenate([np.atleast_1d(m["correct@1"])
                                 for m in host])
            c5 = np.concatenate([np.atleast_1d(m["correct@5"])
                                 for m in host])
            b_tot = float(batch.sum())
            if b_tot > 0:
                # update(v, n) folds v*n into the running sum: the
                # batch-weighted mean at weight b_tot reproduces the
                # per-step update sequence's totals.
                meters["loss"].update(float((loss * batch).sum()) / b_tot,
                                      int(b_tot))
                meters["acc1"].update(float(c1.sum()) / b_tot * 100,
                                      int(b_tot))
                meters["acc5"].update(float(c5.sum()) / b_tot * 100,
                                      int(b_tot))
        pending.clear()

    def _sentinel_tree(self) -> dict:
        """The replicated-state subtree the consistency sentinel
        fingerprints: params + optimizer state (+ EMA and BN stats where
        present — per-replica DDP BN state is auto-excluded by the
        sentinel's data-axis sharding filter). Keys are TrainState field
        names so a repaired tree splices back via ``state.replace``."""
        t = {"params": self.state.params,
             "model_state": self.state.model_state,
             "opt_state": self.state.opt_state}
        if self.state.ema_params is not None:
            t["ema_params"] = self.state.ema_params
        if self.state.ema_model_state is not None:
            t["ema_model_state"] = self.state.ema_model_state
        return t

    def _run_sentinel(self, n_steps: int, *, flush: bool = False) -> None:
        """Advance the consistency sentinel (or, with ``flush=True``,
        check any steps the cadence hasn't covered — end of epoch, before
        the good slot is stamped); splice a repaired state back in place.
        No-quorum divergence / non-finite consensus raise out of here
        into fit()'s recovery handlers."""
        fixed = (self.sentinel.flush(self._sentinel_tree) if flush
                 else self.sentinel.after_sync(n_steps, self._sentinel_tree))
        if fixed is not None:
            self.state = self.state.replace(**fixed)

    def _poll_step_faults(self, pending: list) -> None:
        """Serve planned step-site faults (utils/faults.py): poison the
        just-computed metrics or the live params, silently corrupt one
        replica's params (bitflip/desync/grad_skew), or request a
        simulated preemption — the chaos hooks the recovery tests drive.
        No-op (one counter bump) when no fault plan is configured."""
        from distributed_model_parallel_tpu.utils.faults import (
            CORRUPTION_KINDS,
            corrupt_one_replica,
            poison,
        )

        for spec in self.faults.poll("step"):
            if spec.kind == "preempt":
                self.preemption.request()
            elif spec.kind == "nan_loss" and pending:
                pending[-1] = poison(pending[-1])
            elif spec.kind == "nan_params":
                self.state = self.state.replace(
                    params=poison(self.state.params))
            elif spec.kind in CORRUPTION_KINDS:
                self.state = self.state.replace(
                    params=corrupt_one_replica(
                        self.state.params, self.spec, spec.kind,
                        spec.param))

    def _health_window(self, n_steps: int, timer: StepTimer) -> None:
        """Report a drained step window's per-step wall time to the
        device-health sentinel (utils/health.py; no-op unless a monitor
        is installed — i.e. outside orchestrated runs). The first-window
        compile skip lives in the shared helper."""
        health.observe_step_warmed(self, self._device_ids,
                                   timer.step.last, n_steps)

    def train_epoch(self, epoch: int) -> EpochResult:
        if getattr(self, "_multi_step", None) is not None:
            return self._train_epoch_device_resident(epoch)
        meters = {k: AverageMeter(k) for k in ("loss", "acc1", "acc5")}
        timer = StepTimer()
        pending: list = []
        # Loader position: start of `epoch`, or the mid-epoch cursor a
        # resumed run loaded (train/elastic.py). `base + i` is the global
        # batch index within the epoch; _loader_pos after each dispatched
        # step keeps the resume position in lockstep with the train state
        # (the prefetch worker runs ahead and cannot be trusted).
        self.train_loader.set_epoch(epoch)
        base = self.train_loader.cursor
        self._loader_pos = (epoch, base)
        for i, (images, labels) in enumerate(self._input_stream(self.train_loader)):
            if self.step_hook is not None:
                self.step_hook(self)
            if self.preemption.requested():
                break
            gi = base + i
            timer.data_ready()
            sub = jax.random.fold_in(self._rng_base, self._global_step)
            self.state, metrics = self._train_step(self.state, sub, images, labels)
            self._global_step += 1
            self._loader_pos = (epoch, gi + 1)
            pending.append(metrics)
            if self.faults.enabled:
                self._poll_step_faults(pending)
            log_now = gi % self.config.log_every_n_steps == 0
            if log_now or len(pending) >= self._max_inflight:
                n = len(pending)
                self._drain(pending, meters, sentinel=True)  # sync point
                timer.window_done(n)
                self._health_window(n, timer)
            if log_now:
                # Per-WINDOW samples (meter .last, set by window_done), not
                # the epoch running mean: the report's step-time percentiles
                # must see real per-step variation or a straggler window
                # collapses into the average and disappears.
                self.logger.log_step(
                    epoch, gi, loss=meters["loss"].avg,
                    acc1=meters["acc1"].avg,
                    step_time_s=timer.step.last,
                    data_time_s=timer.data.last,
                    samples_per_s=self.config.data.batch_size
                    / max(timer.step.last, 1e-9))
            self.emergency.after_step(1, self._ckpt_tree)
        n = len(pending)
        self._drain(pending, meters, sentinel=True)
        timer.window_done(n)
        self._health_window(n, timer)
        if self.sentinel.enabled:
            self._run_sentinel(0, flush=True)
        return EpochResult(meters["loss"].avg, meters["acc1"].avg,
                           meters["acc5"].avg, timer.step.avg, timer.data.avg)

    def _train_epoch_device_resident(self, epoch: int) -> EpochResult:
        """Epoch over the on-device dataset: K steps per dispatched program.

        Batch composition is identical to the materializing path — both use
        ``BatchLoader.epoch_indices()`` — so switching the fast path on
        changes performance, not math.
        """
        meters = {k: AverageMeter(k) for k in ("loss", "acc1", "acc5")}
        timer = StepTimer()
        pending: list = []
        bs = self.train_loader.batch_size
        K = max(1, self.config.steps_per_dispatch)
        self.train_loader.set_epoch(epoch)
        # Resume cursor is always dispatch-aligned: saves only happen at
        # dispatch boundaries, so a resumed run re-chunks the remaining
        # steps exactly like the uninterrupted run would have.
        base = self.train_loader.cursor
        self._loader_pos = (epoch, base)
        idx = self.train_loader.epoch_indices(epoch)
        steps = len(idx) // bs
        idx = idx[:steps * bs].reshape(steps, bs)
        inflight = 0
        for i in range(base, steps, K):
            if self.step_hook is not None:
                self.step_hook(self)
            if self.preemption.requested():
                break
            chunk = np.ascontiguousarray(idx[i:i + K])
            timer.data_ready()
            sub = jax.random.fold_in(self._rng_base, self._global_step)
            self.state, metrics = self._multi_step(
                self.state, sub, self._dev_images, self._dev_labels,
                jnp.asarray(chunk))
            self._global_step += chunk.shape[0]
            self._loader_pos = (epoch, i + chunk.shape[0])
            pending.append(metrics)
            if self.faults.enabled:
                # One step-site poll per DISPATCH (K fused steps) — faults
                # cannot target an individual step inside the scan.
                self._poll_step_faults(pending)
            inflight += chunk.shape[0]
            # Log when a multiple of log_every_n_steps falls inside this
            # dispatch's [i, i+K) step window — same cadence as the
            # per-batch path.
            log_now = (-i) % self.config.log_every_n_steps < chunk.shape[0]
            if log_now or len(pending) >= self._max_inflight:
                self._drain(pending, meters, sentinel=True)
                timer.window_done(inflight)
                self._health_window(inflight, timer)
                inflight = 0
            if log_now:
                # Per-window samples, same rationale as the per-batch path.
                self.logger.log_step(
                    epoch, i, loss=meters["loss"].avg,
                    acc1=meters["acc1"].avg,
                    step_time_s=timer.step.last,
                    data_time_s=timer.data.last,
                    samples_per_s=self.config.data.batch_size
                    / max(timer.step.last, 1e-9))
            self.emergency.after_step(chunk.shape[0], self._ckpt_tree)
        self._drain(pending, meters, sentinel=True)
        timer.window_done(inflight)
        self._health_window(inflight, timer)
        if self.sentinel.enabled:
            self._run_sentinel(0, flush=True)
        return EpochResult(meters["loss"].avg, meters["acc1"].avg,
                           meters["acc5"].avg, timer.step.avg, timer.data.avg)

    def evaluate(self) -> EpochResult:
        meters = {k: AverageMeter(k) for k in ("loss", "acc1", "acc5")}
        timer = StepTimer()
        pending: list = []
        for images, labels in self._input_stream(self.eval_loader):
            timer.data_ready()
            pending.append(self._eval_step(self.state, images, labels))
            if len(pending) >= self._max_inflight:
                # Bound host run-ahead so in-flight eval batches can't pile
                # up in device memory on large eval sets.
                n = len(pending)
                self._drain(pending, meters)
                timer.window_done(n)
        n = len(pending)
        self._drain(pending, meters)
        timer.window_done(n)
        return EpochResult(meters["loss"].avg, meters["acc1"].avg,
                           meters["acc5"].avg, timer.step.avg, timer.data.avg)

    def fit(self, epochs: int | None = None) -> list[dict]:
        """Train with per-epoch eval + best-acc checkpointing
        (reference epoch loop data_parallel.py:160-172).

        SIGTERM/SIGINT (TPU preemption, Ctrl-C) request a graceful stop:
        the epoch loop breaks at the next step boundary, a checkpoint is
        written pointing resume at the interrupted epoch, and fit returns
        the completed history (train/preemption.py).

        With recovery enabled (``TrainConfig.recovery.max_retries > 0``) a
        NonFiniteError raised by the guards restores the supervisor's
        per-epoch "last good" checkpoint, optionally shrinks the LR, and
        retries the epoch — bounded by the retry budget
        (train/resilience.py). A no-quorum replica divergence from the
        consistency sentinel (train/consistency.py) takes the same
        restore-and-retry path, without the LR shrink.
        """
        from distributed_model_parallel_tpu.train.guards import (
            NonFiniteError,
            ReplicaDivergenceError,
        )

        epochs = epochs if epochs is not None else self.config.epochs
        history = []
        with self.preemption.installed():
            self.resilience.begin(self._ckpt_tree)
            epoch = self.start_epoch
            while epoch < epochs:
                try:
                    with span("train_epoch", epoch=epoch):
                        tr = self.train_epoch(epoch)
                except NonFiniteError as e:
                    if self.resilience.recover_nonfinite(
                            e, epoch=epoch, restore=self._restore_good,
                            shrink_lr=self._apply_lr_shrink):
                        continue        # state restored — redo the epoch
                    raise
                except ReplicaDivergenceError as e:
                    if self.resilience.recover_divergence(
                            e, epoch=epoch, restore=self._restore_good):
                        continue        # state restored — redo the epoch
                    raise
                if self.preemption.requested():
                    # Partial epoch: resume *at* this epoch (the standard
                    # redo-the-epoch convention); the dedicated slot never
                    # evicts the best-accuracy checkpoint.
                    from distributed_model_parallel_tpu.train.preemption import (
                        checkpoint_on_preempt,
                    )

                    self.start_epoch = epoch
                    checkpoint_on_preempt(self.preemption, self.ckpt,
                                          self._ckpt_tree(), "preempt",
                                          self.logger, epoch,
                                          global_step=self._global_step)
                    break
                if eval_now(epoch, epochs, self.config.eval_every):
                    with span("evaluate", epoch=epoch):
                        ev = self.evaluate()
                else:
                    ev = None
                record = dict(epoch=epoch, loss_train=tr.loss,
                              acc1_train=tr.acc1,
                              loss_val=ev.loss if ev else None,
                              acc1_val=ev.acc1 if ev else None,
                              time_per_batch=tr.step_time,
                              time_load_per_batch=tr.data_time)
                self.logger.log_epoch(**record)
                # Device memory watermark per epoch (no-op where the backend
                # reports none, e.g. CPU).
                self.logger.telemetry.memory()
                history.append(record)
                if ev is not None and ev.acc1 > self.best_acc:
                    self.best_acc = ev.acc1
                    self._save(epoch)
                # Epoch completed with finite metrics/params — persist it
                # as the recovery restore point (no-op unless enabled).
                self.resilience.note_good(self._ckpt_tree)
                epoch += 1
        self.ckpt.wait_until_finished()
        self.logger.finish(epochs_run=len(history))
        return history
