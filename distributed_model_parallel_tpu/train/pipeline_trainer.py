"""Epoch driver for pipeline-parallel training.

The counterpart of the reference's ``model_parallel.py`` main loop + the
per-role loops in ``utils.py:34-210`` — but one driver instead of three
role-specialized ones, because the single-controller runtime sees all stages.
Metrics/logging/timing match the reference's rank-0 behavior
(``model_parallel.py:110-125``): loss and accuracy are computed where the
data lives (stage 0), per-batch compute and data-load times are averaged per
epoch. Adds checkpoint/resume, which the reference's pipeline path lacks
entirely (SURVEY.md §5 "Checkpoint/resume").
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.config import TrainConfig
from distributed_model_parallel_tpu.data.loader import (
    BatchLoader,
    maybe_prefetch,
    resolve_input_size,
)
from distributed_model_parallel_tpu.data.registry import load_dataset
from distributed_model_parallel_tpu.models import get_model
from distributed_model_parallel_tpu.parallel.pipeline import PipelineRunner
from distributed_model_parallel_tpu.train.checkpoint import Checkpointer
from distributed_model_parallel_tpu.train.logging_util import RunLogger
from distributed_model_parallel_tpu.utils import tracing
from distributed_model_parallel_tpu.utils.tracing import span
from distributed_model_parallel_tpu.train.metrics import AverageMeter, StepTimer
from distributed_model_parallel_tpu.train.optim import make_optimizer
from distributed_model_parallel_tpu.train.trainer import EpochResult, eval_now


class PipelineTrainer:
    def __init__(self, config: TrainConfig, devices=None):
        self.plan_decision = None
        if config.strategy == "auto":
            # Autotune the single-controller pipeline (autotune/,
            # docs/AUTOTUNE.md): the stage count is fixed by the device
            # list, so the planner picks the microbatch count (GPipe
            # bubble vs boundary-latency alpha cost) and turns the
            # cost-balanced stage cut on; the decision lands as a typed
            # `plan` telemetry record below.
            from distributed_model_parallel_tpu.autotune.planner import (
                plan_for_stage_pipeline,
            )

            n_stages = (config.mesh.stage if config.mesh.stage > 1
                        else len(devices if devices is not None
                                 else jax.devices()))
            config, self.plan_decision = plan_for_stage_pipeline(config,
                                                                 n_stages)
        self.config = config
        if devices is None:
            devices = jax.devices()[:max(config.mesh.stage, 1)]
        if len(devices) < config.mesh.stage:
            # Fail loudly rather than silently training a shallower pipeline
            # than the config (and logs) claim.
            raise ValueError(
                f"pipeline depth {config.mesh.stage} needs that many devices, "
                f"but only {len(devices)} are available; on CPU pass the "
                f"stage count via the CLI flag (scripts/_cpu_devices.py needs "
                f"it in argv before jax initializes) or set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{config.mesh.stage}")
        self.devices = devices

        train_ds, eval_ds = load_dataset(config.data)
        self.train_ds, self.eval_ds = train_ds, eval_ds
        self.train_loader = BatchLoader(train_ds, config.data.batch_size,
                                        shuffle=config.data.shuffle,
                                        seed=config.data.seed,
                                        use_native=config.data.use_native,
                                        num_workers=config.data.num_workers)
        self.eval_loader = BatchLoader(
            eval_ds, min(config.data.eval_batch_size, len(eval_ds)),
            shuffle=False, use_native=config.data.use_native,
            num_workers=config.data.num_workers)

        # On-device resize when the configured input size differs from the
        # dataset's native resolution (same rule as the DP Trainer).
        resize_to, in_hw = resolve_input_size(train_ds.images.shape,
                                              config.data.image_size)
        in_shape = (in_hw, in_hw, train_ds.images.shape[3])

        model = get_model(config.model)
        if config.optimizer.ema_decay is not None:
            raise ValueError(
                "ema_decay is implemented by the data-parallel Trainer "
                "(gspmd/fsdp), not the pipeline trainer — no silent ignores")
        tx = make_optimizer(config.optimizer, len(self.train_loader),
                            config.epochs)
        boundaries = config.stage_boundaries
        if boundaries is None and config.auto_partition:
            # Cost-balanced split: minimax over XLA per-unit FLOPs, replacing
            # both the reference's hard-coded ranges (model_parallel.py:99-157)
            # and the equal-unit-count default.
            from distributed_model_parallel_tpu.parallel.auto_partition import (
                auto_boundaries,
                microbatch_rows,
            )

            n_chunks = len(devices) * max(1, config.virtual_stages)
            micro = microbatch_rows(config.data.batch_size,
                                    config.num_microbatches)
            boundaries = auto_boundaries(
                model, (micro,) + in_shape, n_chunks)
        self.runner = PipelineRunner(
            model, devices, tx=tx, rng=jax.random.key(config.seed),
            sample_shape=(2,) + train_ds.images.shape[1:],
            resize_to=resize_to,
            mean=train_ds.mean, std=train_ds.std,
            boundaries=boundaries,
            num_microbatches=config.num_microbatches,
            augment=config.data.augment,
            schedule=config.pipeline_schedule,
            virtual_stages=config.virtual_stages,
            bn_momentum=config.model.bn_momentum)

        from distributed_model_parallel_tpu.train.preemption import (
            PreemptionGuard,
        )

        self.preemption = PreemptionGuard()
        self.logger = RunLogger(
            config.log_dir, config.log_name,
            meta=dict(workload="cnn-pipeline", model=config.model.name,
                      batch_size=config.data.batch_size,
                      n_stages=len(self.devices),
                      num_microbatches=config.num_microbatches,
                      pipeline_schedule=config.pipeline_schedule))
        # Span sink for this thread (utils/tracing.py) — resume/checkpoint
        # spans below land on this run's stream.
        tracing.install(self.logger.telemetry)
        # Live status exporter (utils/statusz.py) — see Trainer: start or
        # join the process's exporter, publish this run under /statusz.
        from distributed_model_parallel_tpu.utils import statusz

        statusz.maybe_serve(config.statusz_port)
        statusz.register_trainer(self, "pipeline")
        from distributed_model_parallel_tpu.train.resilience import (
            RecoverySupervisor,
        )
        from distributed_model_parallel_tpu.utils.faults import FaultInjector

        self.faults = FaultInjector(config.recovery.faults)
        from distributed_model_parallel_tpu.utils.faults import (
            validate_corruption_plan,
        )

        validate_corruption_plan(
            self.faults.plan, 1,
            context="the single-controller pipeline (one copy per stage)")
        self.ckpt = Checkpointer(config.checkpoint_dir,
                                 keep=config.recovery.keep_checkpoints,
                                 injector=self.faults,
                                 meta_fn=self._ckpt_meta)
        # Slice identity for the device-health sentinel feeds
        # (utils/health.py; no-ops outside orchestrated runs).
        self._device_ids = tuple(sorted(d.id for d in self.devices))
        self.resilience = RecoverySupervisor(
            config.recovery, logger=self.logger, ckpt=self.ckpt,
            preemption=self.preemption, slot="pipeline-good",
            injector=self.faults,
            check_finite_every=config.check_finite_every,
            consistency_every=config.consistency_every,
            device_ids=self._device_ids)
        from distributed_model_parallel_tpu.train.guards import GuardRunner

        self.guards = GuardRunner(
            check_finite_every=config.check_finite_every,
            stall_budget_s=config.stall_budget_s, logger=self.logger,
            watchdog_interval_s=config.recovery.watchdog_interval_s,
            on_stall=self.resilience.on_stall, injector=self.faults,
            device_ids=self._device_ids)
        from distributed_model_parallel_tpu.train.consistency import (
            ConsistencySentinel,
        )

        # Meshless single-controller engine: one copy of every stage, so
        # the sentinel honestly degrades to its on-device finiteness
        # fingerprint (cross-replica detection requires redundancy —
        # train/consistency.py topology notes).
        self.sentinel = ConsistencySentinel(
            config.consistency_every, None, logger=self.logger,
            guards=self.guards,
            barrier_timeout_s=config.recovery.barrier_timeout_s)
        from distributed_model_parallel_tpu.train.elastic import (
            EmergencyCheckpointer,
        )

        self.emergency = EmergencyCheckpointer(
            self.ckpt, "pipeline-emergency", config.emergency_every,
            logger=self.logger)
        self.best_acc = 0.0
        self.start_epoch = 0
        # Cooperative-scheduling hook (orchestrator/): called with this
        # trainer at every train-step boundary, before the preemption poll
        # — see Trainer.step_hook.
        self.step_hook = None
        # Stateless per-step augmentation rng (base key x global step) +
        # host-side step counter — the exact-continuation pair
        # (train/elastic.py).
        self._rng_base = jax.random.key(config.seed + 1)
        self._global_step = 0
        # Trainer-authoritative loader position (epoch, consumed batches);
        # see Trainer._resume_tree for why the loader's own state is not
        # trusted (prefetch-worker auto-advance race).
        self._loader_pos = (0, 0)
        if config.resume and any(self.ckpt.exists(n)
                                 for n in ("pipeline", "pipeline-preempt",
                                           "pipeline-emergency",
                                           "pipeline-good")):
            self._resume()
        if self.plan_decision is not None:
            # After _resume so a re-plan is stamped with the exact global
            # step the run continues from.
            from distributed_model_parallel_tpu.autotune.planner import (
                emit_plan_record,
            )

            emit_plan_record(self.logger.telemetry, self.plan_decision,
                             global_step=self._global_step)
            self.logger.log_line(self.plan_decision.describe())

    def _ckpt_meta(self):
        """Manifest stamp: saving topology + exact position
        (train/checkpoint.py, train/elastic.py)."""
        return {"workload": "cnn-pipeline",
                "mesh": {**self.config.mesh.axis_sizes(),
                         "dcn_data": self.config.mesh.dcn_data},
                "n_devices": len(self.devices),
                "global_step": self._global_step}

    def _resume_tree(self):
        # Trainer-side position, loader re-synced — see
        # Trainer._resume_tree for the prefetch-worker race this avoids.
        from distributed_model_parallel_tpu.train import elastic

        ep, cur = self._loader_pos
        tree = elastic.build_resume_tree(ep, cur, len(self.train_loader),
                                         self._global_step,
                                         self.resilience.budgets())
        self.train_loader.position(int(tree["loader_epoch"]),
                                   int(tree["batch_cursor"]))
        return tree

    def _ckpt_tree(self):
        # opt_state is stored per chunk (optax wraps each chunk's
        # unit-tuple in its own state structure, so a flat merge like
        # params' is not possible); exact continuation needs it — momentum
        # buffers lost on resume silently change the trajectory.
        return {"params": self.runner.merged_params(),
                "model_state": self.runner.merged_model_state(),
                "opt_state": tuple(jax.device_get(st.opt_state)
                                   for st in self.runner.stages),
                "best_acc": jnp.asarray(self.best_acc, jnp.float32),
                "epoch": jnp.asarray(self.start_epoch, jnp.int32),
                "resume": self._resume_tree()}

    def _apply_resume_tree(self, restored: dict, *, budgets: bool) -> None:
        """Adopt the exact-continuation position; ``budgets=False`` on
        in-run recovery restores (see Trainer._restore_good)."""
        from distributed_model_parallel_tpu.train import elastic

        ri = restored.get("resume")
        if ri is None:
            return
        ep, cur, gs, retries, lr_scale = elastic.unpack_resume_tree(ri)
        self.train_loader.load_state_dict({"epoch": ep, "batch_cursor": cur})
        self._loader_pos = (self.train_loader.epoch,
                            self.train_loader.cursor)
        self._global_step = gs
        if budgets:
            self.resilience.restore_budgets(retries, lr_scale)
            if lr_scale != 1.0:
                self._apply_lr_shrink(lr_scale)

    def _push_restored(self, restored) -> None:
        """Scatter a restored checkpoint tree back onto the per-stage
        devices (chunk c lives on device c % S — matches PipelineRunner's
        round-robin virtual-stage placement)."""
        params, state = restored["params"], restored["model_state"]
        opt = restored.get("opt_state")   # absent in legacy checkpoints
        for s, (lo, hi) in enumerate(self.runner.slices):
            dev = self.runner.devices[s % self.runner.num_stages]
            self.runner.stages[s].params = jax.device_put(
                tuple(params[lo:hi]), dev)
            self.runner.stages[s].model_state = jax.device_put(
                tuple(state[lo:hi]), dev)
            if opt is not None:
                self.runner.stages[s].opt_state = jax.device_put(
                    opt[s], dev)
        self.best_acc = float(restored["best_acc"])

    def _resume(self):
        from distributed_model_parallel_tpu.train import elastic

        # Newest-valid slot wins (best-acc / preemption / emergency), with
        # torn-version and torn-slot fallback; pre-elastic checkpoints
        # (no "resume" subtree) restore through the legacy template.
        tmpl = self._ckpt_tree()
        legacy = {k: v for k, v in tmpl.items()
                  if k not in ("resume", "opt_state")}
        name, restored = elastic.elastic_restore(
            self.ckpt, (tmpl, legacy),
            # The supervisor's good slot is the last resort: it makes a
            # torn preemption/emergency save survivable (dmp_soak.py).
            ("pipeline", "pipeline-preempt", "pipeline-emergency",
             "pipeline-good"),
            on_fallback=self.resilience.note_fallback)
        self._push_restored(restored)
        self.start_epoch = int(restored["epoch"])
        self._apply_resume_tree(restored, budgets=True)
        self.start_epoch = max(self.start_epoch, self.train_loader.epoch)
        self.logger.telemetry.resume(
            slot=name, epoch=self.start_epoch,
            loader_epoch=self.train_loader.epoch,
            batch_cursor=self.train_loader.cursor,
            global_step=self._global_step,
            mesh=self._ckpt_meta()["mesh"])
        self.logger.log_line(
            f"resume: slot {name!r} -> epoch {self.start_epoch} "
            f"batch {self.train_loader.cursor} "
            f"(global step {self._global_step})")

    def _restore_good(self):
        """Recovery restore from the supervisor's "last good" slot
        (train/resilience.py), with torn-version fallback. Position rides
        along; budgets stay live (see Trainer._restore_good)."""
        restored = self.ckpt.restore(
            self._ckpt_tree(), self.resilience.slot, allow_fallback=True,
            on_fallback=self.resilience.note_fallback)
        self._push_restored(restored)
        self._apply_resume_tree(restored, budgets=False)

    def _apply_lr_shrink(self, factor: float) -> None:
        """Recovery-time LR shrink (mirrors Trainer._apply_lr_shrink):
        scale the configured LR, rebuild the optimizer and have the runner
        re-jit its per-stage programs (PipelineRunner.rebuild_optimizer).
        Stage opt_state structure is unchanged — the schedule is a
        closure — so the restored state carries over."""
        import dataclasses

        opt = dataclasses.replace(
            self.config.optimizer,
            learning_rate=self.config.optimizer.learning_rate * factor)
        self.config = self.config.replace(optimizer=opt)
        self.runner.rebuild_optimizer(
            make_optimizer(opt, len(self.train_loader), self.config.epochs))

    def _poll_step_faults(self, pending: list) -> None:
        """Serve planned step-site faults (utils/faults.py): poison the
        just-queued step metrics or the per-stage params, or request a
        simulated preemption."""
        from distributed_model_parallel_tpu.utils.faults import poison

        for spec in self.faults.poll("step"):
            if spec.kind == "preempt":
                self.preemption.request()
            elif spec.kind == "nan_loss" and pending:
                mm, b = pending[-1]
                pending[-1] = (poison(mm), b)
            elif spec.kind == "nan_params":
                for stage in self.runner.stages:
                    stage.params = poison(stage.params)

    def _sentinel_tree(self) -> dict:
        """The per-stage state the sentinel's finiteness fingerprint
        covers (one data replica — no cross-replica redundancy here)."""
        return {"params": tuple(s.params for s in self.runner.stages),
                "model_state": tuple(s.model_state
                                     for s in self.runner.stages),
                "opt_state": tuple(s.opt_state
                                   for s in self.runner.stages)}

    def _run_epoch(self, epoch: int, train: bool) -> EpochResult:
        meters = {k: AverageMeter(k) for k in ("loss", "acc1", "acc5")}
        timer = StepTimer()
        base = 0
        if train:
            # Start of `epoch`, or the mid-epoch cursor a resumed run
            # loaded; position() after each dispatched step keeps the
            # persistent cursor in lockstep with the stage state
            # (train/elastic.py).
            self.train_loader.set_epoch(epoch)
            base = self.train_loader.cursor
            self._loader_pos = (epoch, base)
        loader = self.train_loader if train else self.eval_loader
        loader = maybe_prefetch(loader, self.config.data.prefetch)
        # Metrics stay on device between sync points (train path): a
        # per-step host fetch through a remote device transport serializes
        # upload/compute across steps (the v5e tunnel charges a blocking
        # round trip per fetch). Step time is reported as the wall-clock
        # residual after loader-fetch time — per-phase meters would
        # misattribute the async dispatch cost of non-drain steps.
        pending: list = []

        def update(m, b):
            meters["loss"].update(m["loss"], int(b))
            meters["acc1"].update(m["correct@1"] / b * 100, int(b))
            meters["acc5"].update(m["correct@5"] / b * 100, int(b))

        def drain():
            # The blocking fetch is the sync point — guard it (stall watch
            # + metric finiteness; train/guards.py:GuardRunner).
            with span("drain", n=len(pending)), self.guards.watch():
                finalized = [(self.runner.finalize_metrics(mm, b), b)
                             for mm, b in pending]
            if self.guards.enabled and finalized:
                self.guards.after_sync(
                    [m for m, _ in finalized], len(finalized),
                    params=tuple(s.params for s in self.runner.stages))
            if train and self.sentinel.enabled and finalized:
                # Finiteness fingerprint of the per-stage state (one cheap
                # on-device reduction per stage; raises NonFiniteError into
                # fit()'s recovery path — train/consistency.py). The
                # meshless sentinel (one replica) can only pass or raise —
                # if this path ever gains replicated state, a repaired
                # tree MUST be spliced back like Trainer._run_sentinel
                # does, not dropped while telemetry claims "repaired".
                fixed = self.sentinel.after_sync(len(finalized),
                                                 self._sentinel_tree)
                if fixed is not None:
                    raise RuntimeError(
                        "meshless sentinel returned a repair — splice it "
                        "back into the stages before training on")
            for m, b in finalized:
                update(m, b)
            pending.clear()

        max_inflight = max(1, self.config.max_inflight_steps)
        t_epoch = time.perf_counter()
        n_steps = 0
        # Per-window residual tracking for the telemetry step records: the
        # report's percentiles need per-window samples, not the epoch
        # running mean (which hides stragglers).
        win_wall, win_data, win_steps = t_epoch, 0.0, 0
        timer.mark()
        for i, (images, labels) in enumerate(loader):
            if train and self.step_hook is not None:
                self.step_hook(self)
            if train and self.preemption.requested():
                break
            timer.data_ready()          # pure loader-fetch time
            n_steps += 1
            if train:
                gi = base + i
                sub = jax.random.fold_in(self._rng_base, self._global_step)
                pending.append(
                    (self.runner.train_step_device(sub, images, labels),
                     float(labels.shape[0])))
                self._global_step += 1
                self._loader_pos = (epoch, gi + 1)
                if self.faults.enabled:
                    self._poll_step_faults(pending)
                log_now = gi % self.config.log_every_n_steps == 0
                if log_now or len(pending) >= max_inflight:
                    drain()
                self.emergency.after_step(1, self._ckpt_tree)
                if log_now:
                    now = time.perf_counter()
                    d_data = timer.data.sum - win_data
                    d_steps = max(1, n_steps - win_steps)
                    run_step = max(0.0, now - win_wall - d_data) / d_steps
                    win_wall, win_data, win_steps = (now, timer.data.sum,
                                                     n_steps)
                    # Per-window health signal (utils/health.py; no-op
                    # outside orchestrated runs, first compile window
                    # skipped).
                    from distributed_model_parallel_tpu.utils import health

                    health.observe_step_warmed(self, self._device_ids,
                                               run_step, d_steps)
                    self.logger.log_step(
                        epoch, gi, loss=meters["loss"].avg,
                        acc1=meters["acc1"].avg,
                        step_time_s=run_step,
                        data_time_s=timer.data.last,
                        samples_per_s=self.config.data.batch_size
                        / max(run_step, 1e-9))
            else:
                m = self.runner.eval_step(images, labels)
                update(m, m["batch"])
            timer.mark()                # dispatch time -> residual, not data
        drain()
        if train and self.sentinel.enabled:
            # Cover any tail steps the cadence missed before the epoch is
            # declared clean — an epoch shorter than the cadence would
            # otherwise never be checked (train/consistency.py flush).
            # Same pass-or-raise contract as the drain-site check above.
            fixed = self.sentinel.flush(self._sentinel_tree)
            if fixed is not None:
                raise RuntimeError(
                    "meshless sentinel returned a repair — splice it "
                    "back into the stages before training on")
        wall = time.perf_counter() - t_epoch
        step_avg = max(0.0, wall - timer.data.sum) / max(1, n_steps)
        return EpochResult(meters["loss"].avg, meters["acc1"].avg,
                           meters["acc5"].avg, step_avg, timer.data.avg)

    def fit(self, epochs: int | None = None) -> list[dict]:
        """Epoch loop with eval, best-acc checkpointing, preemption-safe
        stop, and (when ``recovery.max_retries > 0``) automatic restore-
        and-retry on non-finite detections (train/resilience.py)."""
        from distributed_model_parallel_tpu.train.guards import (
            NonFiniteError,
            ReplicaDivergenceError,
        )

        epochs = epochs if epochs is not None else self.config.epochs
        history = []
        with self.preemption.installed():
            self.resilience.begin(self._ckpt_tree)
            epoch = self.start_epoch
            while epoch < epochs:
                try:
                    with span("train_epoch", epoch=epoch):
                        tr = self._run_epoch(epoch, train=True)
                except NonFiniteError as e:
                    if self.resilience.recover_nonfinite(
                            e, epoch=epoch, restore=self._restore_good,
                            shrink_lr=self._apply_lr_shrink):
                        continue        # state restored — redo the epoch
                    raise
                except ReplicaDivergenceError as e:
                    if self.resilience.recover_divergence(
                            e, epoch=epoch, restore=self._restore_good):
                        continue        # state restored — redo the epoch
                    raise
                if self.preemption.requested():
                    # Partial epoch: resume at this epoch (the pipeline
                    # path had NO checkpointing at all in the reference,
                    # SURVEY.md §5).
                    from distributed_model_parallel_tpu.train.preemption import (
                        checkpoint_on_preempt,
                    )

                    self.start_epoch = epoch
                    checkpoint_on_preempt(self.preemption, self.ckpt,
                                          self._ckpt_tree(),
                                          "pipeline-preempt", self.logger,
                                          epoch,
                                          global_step=self._global_step)
                    break
                if eval_now(epoch, epochs, self.config.eval_every):
                    with span("evaluate", epoch=epoch):
                        ev = self._run_epoch(epoch, train=False)
                else:
                    ev = None
                record = dict(epoch=epoch, loss_train=tr.loss,
                              acc1_train=tr.acc1,
                              loss_val=ev.loss if ev else None,
                              acc1_val=ev.acc1 if ev else None,
                              time_per_batch=tr.step_time,
                              time_load_per_batch=tr.data_time)
                self.logger.log_epoch(**record)
                self.logger.telemetry.memory()
                history.append(record)
                if ev is not None and ev.acc1 > self.best_acc:
                    self.best_acc = ev.acc1
                    self.start_epoch = epoch + 1
                    self.ckpt.save(self._ckpt_tree(), "pipeline")
                # Finite-checked epoch state = the recovery restore point.
                self.resilience.note_good(self._ckpt_tree)
                epoch += 1
        self.logger.finish(epochs_run=len(history))
        return history
