"""Preemption-safe training: signal-triggered checkpoint + clean stop.

The reference has no failure story at all — a dead rank hangs the ring and a
killed job loses everything since the last best-accuracy save (SURVEY.md §5
"Failure detection"). TPU pods make this a first-class concern: maintenance
events and spot reclaims deliver SIGTERM with a grace window. This module
turns that signal into a cooperative stop flag; the epoch drivers poll it at
step boundaries, checkpoint immediately, and exit cleanly so ``--resume``
continues from the preempted epoch.
"""

from __future__ import annotations

import contextlib
import logging
import signal
import threading

logger = logging.getLogger(__name__)

DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


def checkpoint_on_preempt(guard: "PreemptionGuard", ckpt, tree, name: str,
                          logger, epoch: int, *,
                          global_step: int | None = None) -> None:
    """The shared honor-a-preemption sequence used by every epoch driver:
    durable save under the dedicated slot, event line, consume the request
    (so a later fit() trains normally). Callers set their resume epoch
    before building ``tree`` and ``break`` after.

    ``tree`` is the trainer's full checkpoint tree, which carries the
    exact-continuation "resume" subtree (loader position, global step,
    recovery budgets — train/elastic.py): the preemption save IS an
    emergency checkpoint, so a restart continues at the interrupted step
    instead of replaying the epoch.

    Emits the typed ``failure`` / ``recovery`` telemetry pair (a preemption
    — real SIGTERM, injected fault, or watchdog stall escalation — is a
    failure whose recovery action is this graceful checkpoint-and-exit), so
    ``scripts/dmp_report.py`` shows it on the resilience timeline."""
    telemetry = getattr(logger, "telemetry", None)
    extra = {} if global_step is None else {"global_step": int(global_step)}
    if telemetry is not None:
        telemetry.failure("preempted", stage=name, epoch=epoch, **extra)
    ckpt.save(tree, name, wait=True)
    logger.log_line(f"preempted: checkpoint saved at epoch {epoch}"
                    + (f", global step {global_step}"
                       if global_step is not None else ""))
    if telemetry is not None:
        telemetry.recovery(action="checkpoint-and-exit", slot=name,
                           epoch=epoch, **extra)
    guard.reset()


class PreemptionGuard:
    """Converts SIGTERM/SIGINT into a thread-safe "stop requested" flag.

    Handlers chain to the previously-installed handler for SIGINT *only on
    the second delivery* — first Ctrl-C requests a graceful checkpointed
    stop, a second one falls through to the default KeyboardInterrupt.
    Installation is a no-op off the main thread (CPython restriction);
    ``request()`` still works for cooperative/manual triggering.
    """

    def __init__(self, signals=DEFAULT_SIGNALS):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev: dict[int, object] = {}

    # -- flag ---------------------------------------------------------------
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self) -> None:
        """Manually request a graceful stop (tests, cluster-API callbacks)."""
        self._event.set()

    def reset(self) -> None:
        self._event.clear()

    # -- signal plumbing ----------------------------------------------------
    def _handler(self, signum, frame):
        if self._event.is_set() and signum == signal.SIGINT:
            prev = self._prev.get(signum)
            if callable(prev):
                prev(signum, frame)
                return
            raise KeyboardInterrupt
        logger.warning("signal %s: requesting graceful checkpointed stop "
                       "(repeat SIGINT to abort hard)", signum)
        self._event.set()

    @contextlib.contextmanager
    def installed(self):
        """Install handlers for the scope of a fit() call, restoring the
        previous handlers on exit."""
        installed = []
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._handler)
                installed.append(s)
            except ValueError:      # not the main thread
                logger.debug("cannot install handler for %s off main thread", s)
        try:
            yield self
        finally:
            for s in installed:
                signal.signal(s, self._prev.pop(s))
