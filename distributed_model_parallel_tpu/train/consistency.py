"""Cross-replica consistency sentinel: detect and repair silent corruption.

The recovery supervisor (train/resilience.py) catches faults that announce
themselves — non-finite values, stalls, torn checkpoints. It is blind to
the failure mode that dominates at fleet scale: *silent* data corruption
and replica drift (Hochschild et al., "Cores that don't count"; Dixit et
al., "Silent Data Corruptions at Scale"), where one data-parallel
replica's params/optimizer state quietly diverge and poison every
subsequent gradient allreduce. The replicate→allreduce topology this
framework implements is exactly the one where a single lying replica
corrupts all of them.

The sentinel closes that gap on a configurable step cadence
(``TrainConfig.consistency_every`` / ``LMTrainConfig.consistency_every``):

1. **fingerprint** — one cheap on-device reduction per leaf of
   params + optimizer state: non-finite count, L2 (sum of squares), a
   float checksum (signed sum) and an **exact** wrap-around sum of the
   element bit patterns (uint32 — catches a mantissa-LSB flip the float
   sums would absorb below their precision), computed *per data-parallel
   replica* inside a ``shard_map`` (partial blocks psum-reduced over the
   non-data mesh axes) and ``all_gather``\\ ed over the data axis — a
   ``[n_replicas, n_leaves, 4]`` array, a few KB regardless of model
   size. Only the gathered fingerprint crosses to host; the parameters
   never do.
2. **compare** — host-side, replicas are grouped by bitwise fingerprint
   equality. One group and finite → consistent, done. The blocking fetch
   runs under the PR 2 Watchdog (``GuardRunner.watch``) so a divergence
   check on a wedged mesh escalates instead of hanging the very
   mechanism meant to catch hangs; on multi-process runs a
   ``mesh.barrier_with_timeout`` rendezvous precedes the collectives so
   a missing host surfaces as a typed ``straggler`` failure record
   (StragglerTimeoutError), not an eternal hang.
3. **repair** — with a quorum (a strict-majority group, or the unique
   all-finite group), the outlier minority is repaired **in place**: a
   second ``shard_map`` re-broadcasts every leaf from a majority-good
   replica (a masked integer psum of the bit patterns — bit-exact and
   O(1) extra memory), then the fingerprint is recomputed to verify
   bitwise equality was restored.
   No quorum (e.g. 1-vs-1 finite disagreement) raises
   :class:`~distributed_model_parallel_tpu.train.guards.ReplicaDivergenceError`,
   which the trainers route to the supervisor's good-slot restore
   (``RecoverySupervisor.recover_divergence``) — bounded retry, same
   budget as non-finite recovery.

Every event emits typed telemetry: a ``consistency`` record
(``divergence`` / ``repaired`` / ``no-quorum`` / ``non-finite``) plus the
``failure``/``recovery`` pair ``scripts/dmp_report.py`` renders on the
resilience timeline. Registry counters ``consistency_checks`` /
``consistency_divergences`` / ``consistency_repairs`` and the
``consistency_check_s`` histogram quantify cadence overhead.

Topology notes: leaves *sharded over* the data axis (DDP per-replica BN
state, FSDP params/optimizer) are legitimately different across replicas
and are excluded from the fingerprint; a state with **no** replicated
leaves (FSDP) cannot be cross-checked and is rejected loudly. With a
single data replica (pipeline trainer, dp=1 LM runs) there is nothing to
compare against, and the sentinel honestly degrades to its finiteness
fingerprint only — cross-replica detection *requires* redundancy.

Deterministic corruption faults for chaos-testing all of this
(``bitflip``/``desync``/``grad_skew``) live in utils/faults.py.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Sequence

import numpy as np

from distributed_model_parallel_tpu.train.guards import (
    NonFiniteError,
    ReplicaDivergenceError,
)
from distributed_model_parallel_tpu.utils.faults import _spec_axes

__all__ = [
    "ConsistencySentinel",
    "FingerprintVerdict",
    "analyze_fingerprints",
]

# Per-leaf fingerprint statistics, in row order. "bitsum" is the exact
# detector: a wrap-around (mod 2^32) sum of every element's BIT PATTERN,
# computed in integer arithmetic — any single flipped bit changes it with
# certainty, where the float l2/sum stats absorb deltas below their own
# precision (a mantissa-LSB flip in a large leaf is invisible to an f32
# running sum). The float stats stay for diagnosis: they say *how far*
# a replica drifted, not just that it did.
FINGERPRINT_STATS = ("nonfinite", "l2", "sum", "bitsum")


@dataclasses.dataclass(frozen=True)
class FingerprintVerdict:
    """Host-side analysis of one ``[n_replicas, n_leaves,
    len(FINGERPRINT_STATS)]`` fingerprint: who agrees, who lies, whether
    a repair quorum exists."""

    consistent: bool           # all replicas bitwise-identical fingerprints
    finite: bool               # the consensus/good fingerprint is finite
    good_replica: int | None   # representative replica to re-broadcast from
    outliers: tuple[int, ...]  # replicas outside the good group
    n_groups: int              # distinct fingerprint values observed

    @property
    def has_quorum(self) -> bool:
        return self.good_replica is not None


def analyze_fingerprints(fp: np.ndarray) -> FingerprintVerdict:
    """Group replicas by bitwise fingerprint equality and pick the quorum.

    Policy (docs/RESILIENCE.md "Silent corruption & replica divergence"):

    * one group → consistent (finite iff its non-finite counts are 0);
    * a group holding a **strict majority** of replicas and finite → the
      quorum; everyone else is an outlier to repair;
    * no strict majority, but exactly **one** group is all-finite → that
      group wins (a non-finite replica is definitely bad — the tie-break
      that saves the 1-vs-1 case when one side is NaN);
    * otherwise → no quorum (``good_replica=None``): the caller falls
      back to the supervisor's good-slot restore.
    """
    fp = np.asarray(fp)
    n = fp.shape[0]
    groups: dict[bytes, list[int]] = {}
    for i in range(n):
        groups.setdefault(fp[i].tobytes(), []).append(i)
    finite_of = {key: bool(fp[members[0], :, 0].sum() == 0)
                 for key, members in groups.items()}
    if len(groups) == 1:
        key = next(iter(groups))
        return FingerprintVerdict(consistent=True, finite=finite_of[key],
                                  good_replica=None, outliers=(),
                                  n_groups=1)
    majority = max(groups.values(), key=len)
    good: list[int] | None = None
    if len(majority) * 2 > n and finite_of[fp[majority[0]].tobytes()]:
        good = majority
    else:
        finite_groups = [m for k, m in groups.items() if finite_of[k]]
        if len(finite_groups) == 1:
            good = finite_groups[0]
    if good is None:
        return FingerprintVerdict(consistent=False, finite=False,
                                  good_replica=None,
                                  outliers=tuple(range(n)),
                                  n_groups=len(groups))
    outliers = tuple(sorted(set(range(n)) - set(good)))
    return FingerprintVerdict(consistent=False,
                              finite=finite_of[fp[good[0]].tobytes()],
                              good_replica=good[0], outliers=outliers,
                              n_groups=len(groups))


class ConsistencySentinel:
    """Cadence-driven cross-replica state verification + in-place repair.

    ``spec`` is the run's :class:`~distributed_model_parallel_tpu.mesh.
    MeshSpec`, or None for meshless single-controller engines (the
    pipeline runner) — with one data replica the sentinel runs its
    finiteness fingerprint only. ``guards`` (a ``GuardRunner``) arms the
    stall watchdog around the blocking fingerprint fetch;
    ``barrier_timeout_s`` bounds the multi-process pre-check rendezvous.
    """

    def __init__(self, every: int, spec=None, *, logger,
                 guards=None, barrier_timeout_s: float | None = None,
                 name: str = "state"):
        if every < 0:
            raise ValueError(f"consistency_every must be >= 0, got {every}")
        self.every = every
        self.spec = spec
        self.logger = logger
        self.guards = guards
        self.barrier_timeout_s = barrier_timeout_s
        self.name = name
        self.checks = 0
        self.repairs = 0
        self._seen = 0
        self._next = every
        self._checked_at = 0
        self._fp_cache: dict = {}
        self._repair_cache: dict = {}
        self._included_cache: tuple | None = None
        self._skip_noted = False
        if spec is not None:
            self._data_axes = spec.data_axes
            self.n_replicas = spec.num_data
            self._other_axes = tuple(n for n in spec.mesh.axis_names
                                     if n not in self._data_axes)
        else:
            self._data_axes = ()
            self._other_axes = ()
            self.n_replicas = 1

    # ------------------------------------------------------------- cadence
    @property
    def enabled(self) -> bool:
        return self.every > 0

    def after_sync(self, n_steps: int, tree_fn: Callable[[], Any]
                   ) -> Any | None:
        """Advance the step counter by ``n_steps``; when the cadence is
        due, fingerprint+compare ``tree_fn()`` and return the repaired
        tree (same structure) when an in-place repair happened, else
        None. Raises ``ReplicaDivergenceError`` on no-quorum divergence
        and ``NonFiniteError`` on a (consensus) non-finite state — both
        routed to the recovery supervisor by the trainers."""
        if not self.enabled:
            return None
        self._seen += n_steps
        if self._seen < self._next:
            return None
        self._next = self._seen + self.every
        self._checked_at = self._seen
        return self.check(tree_fn())

    def flush(self, tree_fn: Callable[[], Any]) -> Any | None:
        """Check any steps the cadence hasn't covered yet — the trainers
        call this at the end of every epoch, right before the supervisor
        stamps the "good" restore slot. It closes two holes the pure
        cadence leaves open: an epoch (or whole run) shorter than
        ``every`` would otherwise never be checked at all, so an injected
        corruption fault could go silently undetected — the exact
        misconfiguration the supervisor's plan validation exists to
        reject — and without it the "good" slot could be saved from state
        the sentinel has never validated. No-op when disabled or when the
        last check already covered every step seen; same return/raise
        contract as :meth:`after_sync`."""
        if not self.enabled or self._seen == self._checked_at:
            return None
        self._next = self._seen + self.every
        self._checked_at = self._seen
        return self.check(tree_fn())

    # ------------------------------------------------------------ plumbing
    @property
    def _telemetry(self):
        return self.logger.telemetry

    def _log(self, msg: str) -> None:
        self.logger.log_line(msg)

    def _included(self, tree, all_leaves=None,
                  treedef=None) -> tuple[list, list, list]:
        """Leaves expected bitwise-identical across data replicas: numeric,
        and not sharded over the data axis (DDP BN state / FSDP shards are
        legitimately per-replica). Returns (leaves, labels, flat
        positions) — positions index the full tree_flatten order, so a
        repaired subset can be spliced back.

        The filter (labels + positions) is cached by ``treedef``: tree
        structure and shardings are invariant across a run (the same
        jitted step produces them), so the O(n_leaves) per-leaf
        path-string construction and sharding-spec walk run once, not on
        every cadence hit of the hot drain path."""
        import jax
        from jax.sharding import NamedSharding

        if all_leaves is None:
            all_leaves, treedef = jax.tree.flatten(tree)
        if (self._included_cache is not None
                and self._included_cache[0] == treedef):
            _, labels, positions = self._included_cache
            return [all_leaves[p] for p in positions], labels, positions
        flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        leaves, labels, positions, skipped = [], [], [], []
        for pos, (path, leaf) in enumerate(flat):
            label = jax.tree_util.keystr(path)
            if self.spec is not None and self.n_replicas > 1:
                sh = getattr(leaf, "sharding", None)
                if not isinstance(sh, NamedSharding):
                    raise ValueError(
                        f"consistency sentinel needs NamedSharding-"
                        f"committed state; {self.name}{label} has {sh!r}")
                if _spec_axes(sh.spec) & set(self._data_axes):
                    skipped.append(label)
                    continue
            leaves.append(leaf)
            labels.append(label)
            positions.append(pos)
        if skipped and not self._skip_noted:
            self._skip_noted = True
            self._log(f"consistency: {len(skipped)} data-sharded "
                      f"(per-replica) leaves excluded from the replicated "
                      f"fingerprint, e.g. {skipped[0]}")
        if not leaves:
            raise ValueError(
                "consistency sentinel: no replicated leaves to compare — "
                "every leaf is sharded over the data axis (FSDP/ZeRO "
                "shards state instead of replicating it; cross-replica "
                "consistency checking requires redundancy)")
        self._included_cache = (treedef, labels, positions)
        return leaves, labels, positions

    # -------------------------------------------------------- fingerprints
    @staticmethod
    def _leaf_row(x):
        import jax.numpy as jnp

        xf = x.astype(jnp.float32)
        if jnp.issubdtype(x.dtype, jnp.floating):
            bad = jnp.sum(~jnp.isfinite(x), dtype=jnp.float32)
        else:
            bad = jnp.zeros((), jnp.float32)
        return jnp.stack([bad, jnp.sum(xf * xf), jnp.sum(xf)])

    @staticmethod
    def _leaf_bitsum(x):
        """Exact mod-2^32 sum of the leaf's element bit patterns (uint32):
        integer wrap-around addition is associative and exact, so ANY
        single flipped bit — including a mantissa LSB far below the float
        stats' precision — changes the result with certainty. 64-bit
        elements fold both 32-bit halves into the sum (a plain uint32
        cast would truncate away flips in bits 32-63)."""
        import jax
        import jax.numpy as jnp

        nbits = x.dtype.itemsize * 8
        if nbits >= 16:
            u = jax.lax.bitcast_convert_type(x, jnp.dtype(f"uint{nbits}"))
        else:
            u = x                       # 8-bit: the value IS the pattern
        if nbits == 64:
            lo = jnp.sum(u.astype(jnp.uint32), dtype=jnp.uint32)
            hi = jnp.sum((u >> jnp.uint64(32)).astype(jnp.uint32),
                         dtype=jnp.uint32)
            return lo + hi
        return jnp.sum(u.astype(jnp.uint32), dtype=jnp.uint32)

    def _copy_rotated_bitsum(self, x, pspec):
        """Per-device bitsum contribution for the mesh fingerprint: the
        local block's bitsum rotated left by the device's copy index over
        the non-data axes the leaf is NOT sharded on (mod 32). Without
        the rotation, identical copies of a leaf replicated over e.g. a
        tp=2 model axis contribute the same value twice to the integer
        psum, so a bit flip that hits every copy the same way (exactly
        what ``corrupt_one_replica`` produces for replicated leaves) adds
        ``2 * 2^31 ≡ 0 (mod 2^32)`` for the sign bit — and a ``0.0 →
        -0.0`` flip is then invisible to all four stats. Distinct
        rotations per copy make any correlated flip land on distinct
        bits, so it cannot cancel (up to 32 copies; a flip in a single
        copy stays visible too). Rotation amounts are a pure function of
        mesh position — identical across data replicas — so cross-replica
        comparison is unaffected; shards along axes the leaf IS sharded
        on share one rotation and still psum to that copy's full
        bitsum."""
        import jax.numpy as jnp

        from distributed_model_parallel_tpu.utils.faults import (
            _combined_replica_index,
        )

        b = self._leaf_bitsum(x)
        replicated = tuple(a for a in self._other_axes
                           if a not in _spec_axes(pspec))
        if not replicated:
            return b
        r = (_combined_replica_index(replicated) % 32).astype(jnp.uint32)
        return (b << r) | (b >> ((jnp.uint32(32) - r) % jnp.uint32(32)))

    @classmethod
    def _leaf_stats(cls, x):
        """[4] fingerprint row: the three f32 stats + the uint32 bitsum
        carried bit-exactly in the f32 slot via bitcast (rows are compared
        as raw bytes, never arithmetically — only column 0 is read as a
        number)."""
        import jax
        import jax.numpy as jnp

        bits_f = jax.lax.bitcast_convert_type(cls._leaf_bitsum(x),
                                              jnp.float32)
        return jnp.concatenate([cls._leaf_row(x), bits_f[None]])

    def _cache_key(self, leaves) -> tuple:
        return tuple((l.shape, str(l.dtype),
                      getattr(l, "sharding", None) and str(l.sharding))
                     for l in leaves)

    def _fingerprint_fn(self, leaves, cache_token=None):
        """[n_replicas, n_leaves, 4] fingerprint program over the mesh
        (columns = FINGERPRINT_STATS). ``cache_token`` (check() passes the
        treedef) keys the compiled-program cache without rebuilding the
        O(n_leaves) stringified-sharding key on every cadence hit — the
        same structure-is-run-invariant assumption ``_included``'s filter
        cache already rests on; leave it None when calling with bare
        leaves (tests)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from distributed_model_parallel_tpu.utils.telemetry import (
            record_collective,
        )

        key = cache_token if cache_token is not None \
            else self._cache_key(leaves)
        fn = self._fp_cache.get(key)
        if fn is not None:
            return fn
        specs = tuple(l.sharding.spec for l in leaves)
        data_axes, other_axes = self._data_axes, self._other_axes
        row_bytes = len(FINGERPRINT_STATS) * 4 * len(leaves)
        record_collective("all_gather", data_axes,
                          row_bytes * self.n_replicas, self.n_replicas)

        def body(*ls):
            stats = jnp.stack([self._leaf_row(x) for x in ls])    # [L, 3]
            bits = jnp.stack([self._copy_rotated_bitsum(x, s)     # [L] u32
                              for x, s in zip(ls, specs)])
            if other_axes:
                # Partial blocks of leaves sharded over non-data axes
                # (tp/pp/sp/ep) reduce to the replica's full-tree stats;
                # the bitsum reduces in integer arithmetic (still exact —
                # wrap-around addition commutes), never as a float, with
                # each replicated copy's contribution rotated by its copy
                # index so correlated flips cannot cancel mod 2^32 (see
                # _copy_rotated_bitsum).
                stats = jax.lax.psum(stats, other_axes)
                bits = jax.lax.psum(bits, other_axes)
            fp = jnp.concatenate(
                [stats,
                 jax.lax.bitcast_convert_type(bits, jnp.float32)[:, None]],
                axis=1)                                           # [L, 4]
            return jax.lax.all_gather(fp, data_axes, axis=0, tiled=False)

        fn = jax.jit(jax.shard_map(body, mesh=self.spec.mesh,
                                   in_specs=specs, out_specs=P(),
                                   check_vma=False))
        self._fp_cache[key] = fn
        return fn

    def _local_fingerprint(self, leaves) -> np.ndarray:
        """Single-replica fingerprint: one jitted reduction per device
        group (the meshless pipeline engine places each stage's tree on
        its own device; arrays on one mesh form a single group)."""
        import jax
        import jax.numpy as jnp

        if not hasattr(self, "_fp_plain"):
            self._fp_plain = jax.jit(
                lambda *ls: jnp.stack([self._leaf_stats(x) for x in ls]))
        by_dev: dict = {}
        for i, leaf in enumerate(leaves):
            try:
                dev = frozenset(leaf.devices())
            except Exception:
                dev = None
            by_dev.setdefault(dev, []).append(i)
        rows: list = [None] * len(leaves)
        for idxs in by_dev.values():
            out = np.asarray(self._fp_plain(*[leaves[i] for i in idxs]))
            for j, i in enumerate(idxs):
                rows[i] = out[j]
        return np.stack(rows)[None]            # [1, n_leaves, 4]

    def _repair_fn(self, leaves, cache_token=None):
        """Re-broadcast every leaf from replica ``good_idx`` (traced arg):
        a masked psum of each leaf's BIT PATTERN over the data axis — the
        good replica contributes its bits, everyone else zeros, and
        integer wrap-around addition returns the good copy bit-exactly on
        all replicas. O(1) extra memory per leaf (an all_gather-and-index
        spelling would transiently materialize n_replicas x the state —
        an OOM exactly when a corrupted replica needs fixing — and a
        FLOAT psum would not even be exact: ``-0.0 + 0.0`` rounds to
        ``+0.0``, silently breaking bitwise parity)."""
        import jax
        import jax.numpy as jnp

        from distributed_model_parallel_tpu.utils.faults import (
            _combined_replica_index,
        )
        from distributed_model_parallel_tpu.utils.telemetry import (
            record_collective,
        )
        from jax.sharding import PartitionSpec as P

        key = cache_token if cache_token is not None \
            else self._cache_key(leaves)
        fn = self._repair_cache.get(key)
        if fn is not None:
            return fn
        specs = tuple(l.sharding.spec for l in leaves)
        data_axes = self._data_axes
        payload = sum(l.size * np.dtype(l.dtype).itemsize for l in leaves)
        record_collective("psum", data_axes, payload, self.n_replicas)

        def body(good_idx, *ls):
            sel = _combined_replica_index(data_axes) == good_idx
            out = []
            for x in ls:
                nbits = x.dtype.itemsize * 8
                uint = jnp.dtype(f"uint{nbits}")
                bits = jax.lax.bitcast_convert_type(x, uint)
                # Sub-32-bit payloads ride a u32 psum (exact: one nonzero
                # contribution per element group, the rest zeros).
                wire = bits.astype(jnp.uint32) if nbits < 32 else bits
                summed = jax.lax.psum(
                    jnp.where(sel, wire, jnp.zeros_like(wire)), data_axes)
                out.append(jax.lax.bitcast_convert_type(
                    summed.astype(uint), x.dtype))
            return tuple(out)

        fn = jax.jit(jax.shard_map(body, mesh=self.spec.mesh,
                                   in_specs=(P(),) + specs, out_specs=specs,
                                   check_vma=False))
        self._repair_cache[key] = fn
        return fn

    # ----------------------------------------------------------- the check
    def _budget(self) -> float | None:
        """Effective straggler bound for the next blocking wait: the
        configured ``barrier_timeout_s``, with a 10x grace on the FIRST
        check — that one uniquely bills one-time costs (XLA compile of
        the barrier/fingerprint programs, and on multi-process runs the
        wait for PEER hosts still compiling theirs) that can exceed a
        steady-state few-KB fetch by orders of magnitude. Sizing guidance
        in config.py assumes steady state; without the grace a bound that
        is generous for every later check would kill a healthy run at
        check #1 with a spurious fatal StragglerTimeoutError."""
        if self.barrier_timeout_s is None:
            return None
        return self.barrier_timeout_s * (10.0 if self.checks == 0 else 1.0)

    def _on_straggler(self, what: str, budget: float) -> None:
        """Shared timeout hook for every bounded rendezvous/fetch: emit
        the typed straggler record (the failure half of the pair) and a
        log line; the caller then raises StragglerTimeoutError."""
        self._telemetry.failure(
            "straggler", detail=f"{what} incomplete after {budget:.1f}s "
            f"— a participant is wedged or missing")
        self._log(f"consistency: {what} timed out after {budget:.1f}s "
                  f"— straggler")

    def _guarded_fetch(self, fetch: Callable[[], np.ndarray]) -> np.ndarray:
        """Blocking fingerprint fetch — never allowed to hang the very
        mechanism meant to catch hangs. Wraps BOTH fingerprint paths (the
        mesh all_gather fetch via :meth:`_fetch` and the single-replica
        device fetch in :meth:`check`'s meshless branch) — a wedged
        device hangs a dp=1/pipeline check exactly as hard as a wedged
        mesh hangs a replicated one. The two protections COMPOSE: with
        the stall watchdog armed (``stall_budget_s``) the *caller's wait*
        runs under it, so a wedged mesh gets live "still blocked" logging
        and the stall escalation policy; with ``barrier_timeout_s`` set
        the fetch is additionally hard-bounded (a host can die between
        the pre-check barrier and the all_gather, and the watchdog alone
        only logs — its preemption escalation is checked by the very
        loop blocked inside this fetch) and a timeout raises
        StragglerTimeoutError after emitting the straggler record. The
        watch wraps the bounded wait on THIS thread, not the worker
        doing the device_get: on a straggler timeout the raise exits the
        watched region, so the watchdog stops logging and cannot keep
        escalating an incident the straggler record already reported
        (the abandoned daemon worker stays wedged but unwatched)."""

        def bounded() -> np.ndarray:
            budget = self._budget()
            if budget is None:
                return fetch()
            from distributed_model_parallel_tpu.mesh import (
                barrier_with_timeout,
            )

            return barrier_with_timeout(
                fetch, budget,
                what="consistency-fingerprint",
                on_timeout=self._on_straggler)

        if self.guards is not None and getattr(self.guards, "stall",
                                               None) is not None:
            with self.guards.watch(what="consistency-fingerprint"):
                return bounded()
        return bounded()

    def _fetch(self, device_fp) -> np.ndarray:
        """Guarded host fetch of the mesh fingerprint (see
        :meth:`_guarded_fetch` for the watchdog/timeout contract)."""
        import jax

        return self._guarded_fetch(
            lambda: np.asarray(jax.device_get(device_fp)))

    def _pre_barrier(self) -> None:
        """Multi-process rendezvous with a timeout before the fingerprint
        collectives: a wedged/missing host becomes a typed ``straggler``
        failure record + StragglerTimeoutError, not an eternal hang."""
        import jax

        if self.barrier_timeout_s is None or jax.process_count() <= 1:
            return
        from distributed_model_parallel_tpu.mesh import barrier_with_timeout
        from distributed_model_parallel_tpu.ops.collectives import (
            mesh_barrier,
        )

        barrier_with_timeout(lambda: mesh_barrier(self.spec),
                             self._budget(),
                             what="consistency-barrier",
                             on_timeout=self._on_straggler)

    def check(self, tree) -> Any | None:
        """Fingerprint ``tree`` now (ignoring the cadence). Returns the
        repaired tree after an in-place re-broadcast, else None. See
        :meth:`after_sync` for the raise contract."""
        import jax

        from distributed_model_parallel_tpu.utils.telemetry import registry

        t0 = time.perf_counter()
        all_leaves, treedef = jax.tree.flatten(tree)
        leaves, labels, positions = self._included(tree, all_leaves,
                                                   treedef)
        mesh_mode = self.spec is not None and self.n_replicas > 1
        self._pre_barrier()
        if mesh_mode:
            fp = self._fetch(
                self._fingerprint_fn(leaves, cache_token=treedef)(*leaves))
        else:
            fp = self._guarded_fetch(
                lambda: self._local_fingerprint(leaves))
        self.checks += 1
        reg = registry()
        reg.counter("consistency_checks").inc()
        reg.histogram("consistency_check_s").observe(
            time.perf_counter() - t0)

        verdict = analyze_fingerprints(fp)
        if verdict.consistent:
            if not verdict.finite:
                # All replicas agree — on a non-finite state (e.g. a NaN
                # that poisoned every replica through the allreduce).
                # Cheaper detection than the full-params host fetch the
                # finiteness guards pay; same recovery path.
                bad = [labels[i] for i in range(len(labels))
                       if fp[0, i, 0] > 0]
                self._telemetry.consistency(
                    "non-finite", replicas=self.n_replicas,
                    leaves=len(bad), check=self.checks)
                raise NonFiniteError(
                    f"consistency fingerprint: non-finite values in "
                    f"{len(bad)} leaves (first: {self.name}{bad[0]})")
            return None

        # --- replicas disagree: silent corruption / drift detected -------
        reg.counter("consistency_divergences").inc()
        good_row = (fp[verdict.good_replica] if verdict.has_quorum
                    else fp[0])
        diverged = [labels[i] for i in range(len(labels))
                    if any(fp[r, i].tobytes() != good_row[i].tobytes()
                           for r in verdict.outliers)]
        detail = (f"{len(verdict.outliers)}/{self.n_replicas} replica(s) "
                  f"diverged on {len(diverged)} leaves "
                  f"(first: {self.name}{diverged[0] if diverged else '?'})")
        self._telemetry.consistency(
            "divergence", replicas=self.n_replicas,
            outliers=list(verdict.outliers), leaves=len(diverged),
            check=self.checks)
        self._telemetry.failure("replica-divergence", detail=detail)
        self._log(f"consistency: {detail}")

        if not verdict.has_quorum:
            self._telemetry.consistency(
                "no-quorum", replicas=self.n_replicas,
                groups=verdict.n_groups, check=self.checks)
            self._log("consistency: no majority-good quorum "
                      f"({verdict.n_groups} distinct states over "
                      f"{self.n_replicas} replicas) — falling back to the "
                      "good-slot restore")
            raise ReplicaDivergenceError(
                f"no repair quorum: {verdict.n_groups} distinct replica "
                f"states over {self.n_replicas} replicas ({detail})")

        # --- quorum: repair in place by re-broadcast ---------------------
        import jax.numpy as jnp

        fixed_leaves = self._repair_fn(leaves, cache_token=treedef)(
            jnp.asarray(verdict.good_replica, jnp.int32), *leaves)
        # Repair out_specs pin the repaired leaves to the input shapes/
        # shardings, so the treedef-keyed fingerprint program is reused.
        verify = self._fetch(self._fingerprint_fn(
            list(fixed_leaves), cache_token=treedef)(*fixed_leaves))
        after = analyze_fingerprints(verify)
        if not after.consistent:
            # The re-broadcast itself came back divergent — the corruption
            # is live (a bad core still flipping bits), not a one-off.
            self._telemetry.failure(
                "replica-divergence",
                detail="re-broadcast repair did not restore consistency")
            raise ReplicaDivergenceError(
                "re-broadcast repair did not restore bitwise consistency "
                "— corruption is live, not transient")
        self.repairs += 1
        reg.counter("consistency_repairs").inc()
        self._telemetry.consistency(
            "repaired", replicas=self.n_replicas,
            outliers=list(verdict.outliers), leaves=len(diverged),
            check=self.checks)
        self._telemetry.recovery(
            action="replica-rebroadcast",
            detail=f"from replica {verdict.good_replica}: {detail}")
        self._log(f"consistency: repaired in place — re-broadcast from "
                  f"replica {verdict.good_replica} "
                  f"(outliers {list(verdict.outliers)})")
        if not after.finite:
            raise NonFiniteError(
                "consistency fingerprint: replicas agree after repair but "
                "the consensus state is non-finite")
        out = list(all_leaves)
        for pos, new in zip(positions, fixed_leaves):
            out[pos] = new
        return jax.tree.unflatten(treedef, out)
