"""Recovery supervisor: wires failure detection to recovery policy.

The guards (train/guards.py) and preemption flag (train/preemption.py) can
*notice* NaN, stalls and SIGTERM — but until this module every detection
ended the run: nothing rolled back, retried, or fell back to an older
checkpoint (ISSUE 2; the reference loses everything since the last
best-accuracy save on any kill, SURVEY.md §5). The supervisor closes the
loop:

* **non-finite loss/params** → restore the per-epoch "last good" checkpoint
  slot, optionally shrink the learning rate, and retry the epoch — bounded
  by ``RecoveryConfig.max_retries``;
* **torn/corrupt newest checkpoint** on any supervised restore → the
  integrity manifest (train/checkpoint.py) rejects it and the restore falls
  back to the previous committed version;
* **failed save** of the good slot → logged, retried once, and otherwise
  skipped (the previous committed version stays restorable) instead of
  killing training;
* **stalled sync** → the :class:`Watchdog` logs "still blocked after Ns"
  lines *while* the sync is blocked (the old ``StallDetector`` could only
  flag after the fact) and, with ``RecoveryConfig.stall_exit``, escalates to
  a graceful checkpoint-and-exit via the preemption flag;
* **no-quorum replica divergence** → the consistency sentinel
  (train/consistency.py) detects silent corruption/drift across
  data-parallel replicas and repairs in place when a majority-good quorum
  exists; when none does, :meth:`RecoverySupervisor.recover_divergence`
  restores the good slot and retries on the same bounded budget.

Every detection emits a typed telemetry ``failure`` record and every action
a ``recovery`` record (utils/telemetry.py), so ``scripts/dmp_report.py``
renders a recovery timeline. Fault injection for testing all of this on
demand lives in utils/faults.py.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Sequence

from distributed_model_parallel_tpu.config import RecoveryConfig
from distributed_model_parallel_tpu.utils import flightrec, health, tracing
from distributed_model_parallel_tpu.utils.faults import FaultInjector, FaultSpec


class Watchdog:
    """Live stall watchdog around blocking sync points.

    ``watch()`` arms a background monitor thread for the scope of one
    blocking call: while the call is still running, the monitor logs a
    "still blocked after Ns" line every ``interval_s`` — so a wedged
    collective is visible *before* the step returns — and flips
    ``stalled`` / fires ``on_escalate`` once the stall budget is exceeded.
    On exit the overrun is also checked post-hoc (tiny overruns can
    complete between monitor ticks), which preserves the old
    ``StallDetector`` semantics (``stalled`` / ``worst_s`` /
    one loud "exceeded the stall budget" log line).
    """

    def __init__(self, budget_s: float, *, interval_s: float | None = None,
                 logger=None,
                 on_escalate: Callable[[str, float], None] | None = None):
        self.budget_s = float(budget_s)
        self.interval_s = (float(interval_s) if interval_s
                           else min(30.0, max(0.05, self.budget_s / 2)))
        self.logger = logger
        self.on_escalate = on_escalate
        self.stalled = False
        self.worst_s = 0.0
        self._overrun_logged = False
        self._escalated = False
        # ONE long-lived monitor thread, armed/disarmed per watch(): the
        # LM trainer syncs every step, so per-watch thread spawn/join would
        # tax the hot path of every guarded run, stall or not. Arm/disarm
        # is two lock acquisitions.
        self._cv = threading.Condition()
        self._armed_at: float | None = None
        self._what = "sync"
        self._gen = 0
        self._thread: threading.Thread | None = None

    def _log(self, msg: str) -> None:
        if self.logger is not None:
            self.logger.log_line(msg)

    def _escalate(self, what: str, dt: float) -> None:
        if self._escalated or self.on_escalate is None:
            return
        self._escalated = True
        self.on_escalate(what, dt)

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._monitor,
                                            daemon=True, name="dmp-watchdog")
            self._thread.start()

    def _monitor(self) -> None:
        while True:
            with self._cv:
                while self._armed_at is None:
                    self._cv.wait()          # idle: costs nothing
                gen, t0, what = self._gen, self._armed_at, self._what
                self._cv.wait(self.interval_s)
                still = self._armed_at is not None and self._gen == gen
                dt = (time.perf_counter() - t0) if still else 0.0
            if still:
                # Log outside the lock so slow sink I/O never blocks the
                # main thread's disarm on watch() exit.
                self._log(f"watchdog: {what} still blocked after {dt:.1f}s "
                          f"(budget {self.budget_s:.1f}s)")
                if dt > self.budget_s:
                    self.stalled = True
                    self._escalate(what, dt)

    @contextlib.contextmanager
    def watch(self, what: str = "sync"):
        self._ensure_thread()
        t0 = time.perf_counter()
        with self._cv:
            self._gen += 1
            self._armed_at = t0
            self._what = what
            self._cv.notify()
        try:
            yield self
        finally:
            with self._cv:
                self._gen += 1
                self._armed_at = None
            dt = time.perf_counter() - t0
            self.worst_s = max(self.worst_s, dt)
            if dt > self.budget_s:
                self.stalled = True
                if not self._overrun_logged:
                    self._overrun_logged = True
                    self._log(f"guard: sync exceeded the stall budget "
                              f"({dt:.1f}s > {self.budget_s:.1f}s)")
                self._escalate(what, dt)


def _short(e: BaseException, n: int = 300) -> str:
    return f"{type(e).__name__}: {e}"[:n]


class RecoverySupervisor:
    """Per-trainer recovery orchestration (see module docstring).

    The trainer owns the mechanics (how to build its checkpoint tree, how
    to push restored state back onto devices, how to rebuild its optimizer
    at a smaller LR); the supervisor owns the policy (when to restore, the
    retry budget, what to record). ``slot`` is the trainer's "last good"
    checkpoint name — saved by :meth:`note_good` after every clean epoch
    and at :meth:`begin`, restored by the trainer's callback on recovery.
    """

    def __init__(self, config: RecoveryConfig, *, logger, ckpt, preemption,
                 slot: str = "good", injector: FaultInjector | None = None,
                 check_finite_every: int | None = None,
                 consistency_every: int | None = None,
                 device_ids: Sequence[int] = ()):
        if config.max_retries < 0:
            raise ValueError(
                f"recovery.max_retries must be >= 0, got {config.max_retries}")
        if not (0.0 < config.lr_shrink <= 1.0):
            raise ValueError(
                f"recovery.lr_shrink must be in (0, 1], got "
                f"{config.lr_shrink}")
        if config.keep_checkpoints < 1:
            raise ValueError(
                f"recovery.keep_checkpoints must be >= 1, got "
                f"{config.keep_checkpoints}")
        self.config = config
        self.logger = logger
        self.ckpt = ckpt
        self.preemption = preemption
        self.slot = slot
        # The run's device ids, for the device-health sentinel feeds
        # (utils/health.py): checkpoint-I/O latency and stall escalations
        # are attributed to the slice this trainer runs on.
        self.device_ids = tuple(device_ids)
        self.injector = (injector if injector is not None
                         else FaultInjector(config.faults))
        self.injector.on_fire = self._on_fault_fired
        self.retries_left = config.max_retries
        self.lr_scale = 1.0
        self._stall_reported = False
        self._fallback_reported: set[str] = set()
        sentinel_on = (consistency_every or 0) > 0
        if check_finite_every is not None and check_finite_every <= 0:
            # An injected NaN nothing detects doesn't test recovery — it
            # crashes the metrics drain on int(NaN). No silent
            # misconfigurations. The consistency sentinel's finiteness
            # fingerprint counts as a detector for nan_params ONLY: it
            # fingerprints params/opt state, never the step metrics, so
            # nan_loss still needs the metrics guards. A cadence longer
            # than the run does not reopen the hole: the trainers flush
            # the sentinel at every epoch end (ConsistencySentinel.flush),
            # so armed means at-least-once-per-epoch.
            undetectable = sorted({
                s.kind for s in self.injector.plan
                if s.kind == "nan_loss"
                or (s.kind == "nan_params" and not sentinel_on)})
            if undetectable:
                raise ValueError(
                    f"the fault plan injects NaN ({', '.join(undetectable)})"
                    f" but check_finite_every is 0, so the guards would "
                    f"never detect it; set check_finite_every >= 1"
                    + ("" if sentinel_on else
                       " (or, for nan_params only, consistency_every >= 1)"))
            if self.enabled and not sentinel_on:
                self.logger.log_line(
                    "resilience: warning — recovery.max_retries is set but "
                    "check_finite_every is 0, so non-finite steps are never "
                    "detected (stall/preempt/save recovery still active)")
        if not sentinel_on:
            from distributed_model_parallel_tpu.utils.faults import (
                CORRUPTION_KINDS,
            )

            corrupting = sorted({s.kind for s in self.injector.plan
                                 if s.kind in CORRUPTION_KINDS})
            if corrupting:
                # Silent corruption is, by definition, invisible to the
                # finiteness guards — a plan injecting it without the
                # sentinel armed is an untestable no-op.
                raise ValueError(
                    f"the fault plan injects silent corruption "
                    f"({', '.join(corrupting)}) but consistency_every is "
                    f"0, so the cross-replica sentinel would never detect "
                    f"it; set consistency_every >= 1")

    @property
    def enabled(self) -> bool:
        return self.config.max_retries > 0

    @property
    def _telemetry(self):
        return self.logger.telemetry

    # -- chaos bookkeeping --------------------------------------------------
    def _on_fault_fired(self, spec: FaultSpec, site: str, index: int) -> None:
        # Typed record, not just the log line: the fleet report's fault
        # ledger pairs every injected fault with the detection/recovery
        # records that follow it (scripts/dmp_report.py pair_faults).
        self._telemetry.record("fault", fault=spec.kind, site=site,
                               index=index)
        self.logger.log_line(
            f"chaos: injected fault {spec.kind} at {site}[{index}]")

    # -- budget persistence (elastic resume, train/elastic.py) --------------
    def budgets(self) -> dict:
        """The budgets a checkpoint carries so a restarted run cannot
        launder its retry allowance or silently drop an applied LR shrink:
        ``retries_left`` and the cumulative ``lr_scale``."""
        return {"retries_left": self.retries_left, "lr_scale": self.lr_scale}

    def restore_budgets(self, retries_left: int, lr_scale: float) -> None:
        """Adopt checkpointed budgets on resume. ``retries_left`` is
        clamped to the configured budget (a config that *lowered*
        max_retries wins); the caller re-applies ``lr_scale`` to its
        optimizer (the supervisor only tracks it)."""
        self.retries_left = max(0, min(int(retries_left),
                                       self.config.max_retries))
        self.lr_scale = float(lr_scale)

    # -- good-state bookkeeping ---------------------------------------------
    def begin(self, tree_fn: Callable[[], Any]) -> None:
        """Seed the good slot at fit() start so an epoch-0 failure has a
        known-good state to restore (no-op when recovery is disabled)."""
        self.note_good(tree_fn)

    def note_good(self, tree_fn: Callable[[], Any]) -> None:
        """Persist the current (finiteness-checked) state as "last good".

        A failed save is itself a recoverable failure: record it, retry
        once, and otherwise keep training on the previous committed
        version — the one case a save failure must NOT do is kill a run
        that was healthy a moment ago.
        """
        if not self.enabled:
            return
        try:
            with tracing.span("good_save", slot=self.slot):
                t0 = time.perf_counter()
                self.ckpt.save(tree_fn(), self.slot, wait=True)
            # Checkpoint-I/O latency feeds the health score: a device
            # whose HBM reads crawl shows up here long before it NaNs.
            health.observe_io(self.device_ids, time.perf_counter() - t0)
            return
        except Exception as e:  # noqa: BLE001 - any save failure is handled
            self._telemetry.failure("checkpoint-save-failed", stage=self.slot,
                                    detail=_short(e))
            self.logger.log_line(
                f"resilience: save to {self.slot!r} failed "
                f"({type(e).__name__}) — retrying once")
        try:
            self.ckpt.save(tree_fn(), self.slot, wait=True)
        except Exception as e:  # noqa: BLE001
            self._telemetry.recovery(action="save-skipped", slot=self.slot,
                                     detail=_short(e))
            self.logger.log_line(
                "resilience: retry failed — keeping the previous committed "
                "version of the good slot")
        else:
            self._telemetry.recovery(action="save-retried", slot=self.slot)
            self.logger.log_line("resilience: good-slot save retry succeeded")

    # -- recovery actions ---------------------------------------------------
    def _restore_and_retry(self, *, epoch: int, label: str,
                           restore: Callable[[], None],
                           shrink_lr: Callable[[float], None] | None
                           ) -> bool:
        """Shared restore-the-good-slot-and-retry policy. Returns True when
        the epoch should be retried (state restored), False when the caller
        must re-raise (recovery disabled, budget exhausted, or nothing to
        restore)."""
        if not self.enabled:
            return False
        if self.retries_left <= 0:
            self.logger.log_line(
                f"resilience: {label} retry budget exhausted — raising")
            # The run is about to die unrecovered — capture the moment
            # (no-op without an installed flight recorder).
            flightrec.dump(f"unrecovered-{label}",
                           telemetry_run=self._telemetry)
            return False
        self.retries_left -= 1
        try:
            with tracing.span("recovery_restore", slot=self.slot,
                              label=label):
                restore()
        except FileNotFoundError:
            self.logger.log_line(
                f"resilience: no {self.slot!r} checkpoint to restore — "
                f"raising")
            flightrec.dump(f"unrecovered-{label}",
                           telemetry_run=self._telemetry)
            return False
        except Exception as e:  # noqa: BLE001 - e.g. every version torn
            # (CheckpointIntegrityError). The caller re-raises the original
            # error — the restore failure is context, not cause.
            self._telemetry.failure("recovery-restore-failed",
                                    slot=self.slot, detail=_short(e))
            self.logger.log_line(
                f"resilience: restoring {self.slot!r} failed "
                f"({type(e).__name__}: {str(e)[:160]}) — raising the "
                f"original {label} error")
            flightrec.dump(f"unrecovered-{label}",
                           telemetry_run=self._telemetry, error=e)
            return False
        if shrink_lr is not None and self.config.lr_shrink != 1.0:
            self.lr_scale *= self.config.lr_shrink
            shrink_lr(self.config.lr_shrink)
        elif label == "non-finite":
            # Elastic resume made retries deterministic: the restored
            # position replays the exact batch order and rng stream, so
            # without an LR shrink a DATA-deterministic NaN will recur
            # identically and burn the whole budget. (For transient
            # hardware faults — the common case — exact replay is the
            # point.) Say so instead of failing mysteriously N times.
            self.logger.log_line(
                "resilience: retrying with lr_shrink=1.0 replays the "
                "identical batch/rng trajectory — a deterministic "
                "non-finite will recur; set recovery.lr_shrink < 1.0 to "
                "perturb the retry")
        self._telemetry.recovery(action="restored", slot=self.slot,
                                 epoch=epoch, retries_left=self.retries_left,
                                 lr_scale=self.lr_scale, detail=label)
        self.logger.log_line(
            f"resilience: {label} at epoch {epoch} — restored "
            f"{self.slot!r}, lr x{self.lr_scale:g}, retrying "
            f"({self.retries_left} retries left)")
        return True

    def recover_nonfinite(self, exc: BaseException, *, epoch: int,
                          restore: Callable[[], None],
                          shrink_lr: Callable[[float], None] | None = None
                          ) -> bool:
        """Handle a NonFiniteError raised out of an epoch (see
        :meth:`_restore_and_retry` for the return contract)."""
        self._telemetry.failure("non-finite", epoch=epoch,
                                detail=_short(exc),
                                retries_left=self.retries_left)
        return self._restore_and_retry(epoch=epoch, label="non-finite",
                                       restore=restore, shrink_lr=shrink_lr)

    def recover_divergence(self, exc: BaseException, *, epoch: int,
                           restore: Callable[[], None]) -> bool:
        """Handle a no-quorum ReplicaDivergenceError from the consistency
        sentinel (train/consistency.py): with no majority-good replica to
        re-broadcast from, the only trustworthy state is the last good
        checkpoint — restore it and retry the epoch, on the same bounded
        budget as non-finite recovery. The sentinel already recorded the
        ``consistency``/``failure`` detection pair; this adds the matching
        ``recovery`` record. No LR shrink: divergence is a hardware/
        transport lie, not an optimization instability."""
        return self._restore_and_retry(epoch=epoch,
                                       label="replica-divergence",
                                       restore=restore, shrink_lr=None)

    def note_fallback(self, path: str, reason: str) -> None:
        """Checkpointer callback: the newest version was torn/corrupt and
        the restore is falling back to the previous committed one. One
        failure/recovery pair per torn path — a resume that retries
        several template layouts re-verifies the same candidates."""
        if path in self._fallback_reported:
            return
        self._fallback_reported.add(path)
        self._telemetry.failure("checkpoint-torn", detail=f"{path}: "
                                f"{reason}"[:300])
        self._telemetry.recovery(action="checkpoint-fallback", detail=path)
        self.logger.log_line(
            f"resilience: checkpoint {path} failed verification/restore "
            f"({reason[:120]}) — falling back to the previous version")

    def on_stall(self, what: str, blocked_s: float) -> None:
        """Watchdog escalation: record the stall; with ``stall_exit``,
        request a graceful checkpoint-and-exit (the preemption path then
        saves and emits the matching ``recovery`` record). The stall is
        also a hard device-health penalty for this slice
        (utils/health.py)."""
        health.observe_stall(self.device_ids, blocked_s)
        if self._stall_reported:
            return
        self._stall_reported = True
        self._telemetry.failure(
            "stall", detail=f"{what} blocked {blocked_s:.1f}s "
            f"(budget exceeded)")
        # Postmortem at the moment of the stall: the wedged collective's
        # thread stacks are exactly what a post-hoc stream can't show
        # (no-op without an installed flight recorder).
        flightrec.dump(f"stall-{what}", telemetry_run=self._telemetry)
        if self.config.stall_exit:
            self.logger.log_line(
                "resilience: stall budget exceeded — requesting graceful "
                "checkpoint-and-exit")
            self.preemption.request()
