"""End-to-end trainer for the Transformer LM flagship.

Drives ``parallel/spmd_pipeline.make_spmd_train_step`` — the single-jit
dp x pp x tp x sp program — with the same harness conveniences the CNN
trainers have (epoch loop, logging, checkpoint/resume, timing meters).
The dataset is a deterministic synthetic token stream (zero-egress
environment); real corpora drop in by replacing ``make_token_stream``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_model_parallel_tpu.config import (
    MeshConfig,
    OptimizerConfig,
    RecoveryConfig,
)
from distributed_model_parallel_tpu.mesh import MeshSpec, make_mesh
from distributed_model_parallel_tpu.models import transformer as tfm
from distributed_model_parallel_tpu.parallel.spmd_pipeline import (
    make_spmd_train_step,
    shard_params,
)
from distributed_model_parallel_tpu.train.checkpoint import Checkpointer
from distributed_model_parallel_tpu.train.logging_util import RunLogger
from distributed_model_parallel_tpu.utils import tracing
from distributed_model_parallel_tpu.utils.tracing import span
from distributed_model_parallel_tpu.train.metrics import AverageMeter, StepTimer
from distributed_model_parallel_tpu.train.optim import make_optimizer


def make_token_stream(vocab_size: int, n_tokens: int, seed: int = 0
                      ) -> np.ndarray:
    """Deterministic order-1 Markov token stream — learnable structure so
    loss visibly drops below the unigram entropy."""
    rng = np.random.default_rng(seed)
    # sparse transition matrix: each token prefers ~4 successors
    prefs = rng.integers(0, vocab_size, size=(vocab_size, 4))
    out = np.empty(n_tokens, np.int32)
    tok = 0
    for i in range(n_tokens):
        out[i] = tok
        if rng.random() < 0.8:
            tok = int(prefs[tok, rng.integers(0, 4)])
        else:
            tok = int(rng.integers(0, vocab_size))
    return out


@dataclasses.dataclass(frozen=True)
class LMTrainConfig:
    model: tfm.TransformerConfig = tfm.TransformerConfig()
    # "spmd" = run the configured mesh as-is (the single-jit
    # dp x pp x tp x sp x ep program); "auto" = let the parallelism
    # autotuner (autotune/, docs/AUTOTUNE.md) pick the axis degrees for
    # the LIVE device count — enumerate feasible factorizations, filter
    # by HBM feasibility, rank with the alpha-beta comm/compute cost
    # model, rewrite `mesh` (+ `num_microbatches`, and the model's
    # sp_axis when a sequence axis is planned) from the winner, and emit
    # a typed `plan` telemetry record. Elastic restarts re-plan on the
    # refitted mesh instead of blindly shrinking dp.
    strategy: str = "spmd"
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    optimizer: OptimizerConfig = dataclasses.field(
        default_factory=lambda: OptimizerConfig(learning_rate=0.1,
                                                weight_decay=0.0))
    batch_size: int = 8
    seq_len: int = 128
    num_microbatches: int = 1
    # SPMD pipeline schedule: "gpipe" (whole-program AD; all M microbatches'
    # residuals live at peak) or "1f1b" (hand-interleaved backward; peak
    # activation memory bounded by the stage count, not M —
    # parallel/spmd_pipeline.make_1f1b_loss_and_grad).
    pipeline_schedule: str = "gpipe"
    # Megatron interleaved virtual stages (1f1b only): device s owns V
    # model chunks; the trainer interleaves the block rows at init so the
    # whole run (optimizer state included) lives in storage order.
    virtual_stages: int = 1
    steps_per_epoch: int = 50
    epochs: int = 1
    n_tokens: int = 200_000
    seed: int = 0
    # Held-out evaluation (the reference evals every epoch,
    # data_parallel.py:160-172): the stream's trailing ``eval_fraction``
    # never appears in training batches; ``eval_batches`` fixed batches
    # from it are scored each ``eval_every`` epochs (0 disables eval).
    # ``eval_batches=None`` means auto: 8 when the held-out tail fits at
    # least one seq_len eval window, otherwise eval is disabled with a
    # warning. An explicit integer that cannot fit still raises — only
    # the auto default degrades silently.
    eval_fraction: float = 0.1
    eval_batches: int | None = None
    eval_every: int = 1
    log_dir: str = "./log"
    log_name: str = "lm"
    checkpoint_dir: str = "./checkpoint"
    resume: bool = False
    # Elastic resume — same semantics as TrainConfig.emergency_every /
    # TrainConfig.elastic (train/elastic.py): a step-cadence emergency
    # checkpoint slot carrying the exact continuation state (step cursor,
    # global step, recovery budgets), and startup mesh refit to the live
    # device count with resharded restore.
    emergency_every: int = 0
    elastic: bool = False
    # Guards (train/guards.py:GuardRunner) — same semantics as TrainConfig.
    check_finite_every: int = 0
    stall_budget_s: float | None = None
    # Cross-replica consistency sentinel cadence — same semantics as
    # TrainConfig.consistency_every (train/consistency.py). Params are
    # replicated over the data axis under the SPMD pipeline, so dp >= 2
    # gives real cross-replica detection; dp == 1 degrades to the
    # finiteness fingerprint.
    consistency_every: int = 0
    # Automatic recovery policy + fault-injection plan — same semantics as
    # TrainConfig.recovery (train/resilience.py, utils/faults.py).
    recovery: RecoveryConfig = dataclasses.field(
        default_factory=RecoveryConfig)
    # Live status/metrics exporter — same semantics as
    # TrainConfig.statusz_port (utils/statusz.py; DMP_STATUSZ_PORT
    # fallback, one exporter per process).
    statusz_port: int | None = None


class LMTrainer:
    def __init__(self, config: LMTrainConfig, spec: MeshSpec | None = None):
        if config.strategy not in ("spmd", "auto"):
            raise ValueError(
                f"LMTrainConfig.strategy must be 'spmd' or 'auto', got "
                f"{config.strategy!r} — no silent ignores")
        self.plan_decision = None
        if config.strategy == "auto" and spec is not None:
            raise ValueError(
                "strategy='auto' plans the mesh layout itself and cannot "
                "honor an explicit MeshSpec; resolve the plan first "
                "(autotune.plan_for_lm) or pass strategy='spmd' — no "
                "silent ignores")
        if config.strategy == "auto" and spec is None:
            # Cost-model-driven layout (autotune/, docs/AUTOTUNE.md):
            # enumerate every feasible (dp, pp, tp, sp, ep) factorization
            # of the LIVE device count, HBM-filter, rank alpha-beta, and
            # rewrite mesh/microbatches/sp_axis from the winner. An
            # elastic restart therefore RE-PLANS on the refitted mesh.
            from distributed_model_parallel_tpu.autotune.planner import (
                plan_for_lm,
            )
            from distributed_model_parallel_tpu.train.elastic import (
                live_device_count,
            )

            config, self.plan_decision = plan_for_lm(config,
                                                     live_device_count())
        self.elastic_decision = None
        if config.elastic and spec is None and self.plan_decision is None:
            # Elastic restart: refit the data axis to the live device count
            # (train/elastic.py); resume then reshards the checkpoint onto
            # the rebuilt mesh. strategy="auto" replans above instead.
            from distributed_model_parallel_tpu.train.elastic import (
                fit_mesh_to_devices,
                live_device_count,
            )

            mesh_cfg, self.elastic_decision = fit_mesh_to_devices(
                config.mesh, live_device_count(),
                batch_size=config.batch_size)
            config = dataclasses.replace(config, mesh=mesh_cfg)
        self.config = config
        self.spec = spec if spec is not None else make_mesh(config.mesh)
        cfg = config.model
        if cfg.max_seq_len < config.seq_len:
            raise ValueError("model max_seq_len < training seq_len")
        self.cfg = cfg
        if config.optimizer.ema_decay is not None:
            raise ValueError(
                "ema_decay is implemented by the data-parallel Trainer "
                "(gspmd/fsdp), not the LM trainer — no silent ignores")
        if config.optimizer.fused:
            raise ValueError(
                "OptimizerConfig.fused runs the update over flat "
                "coalesced parameter buckets; the LM trainer's params are "
                "stage/tensor-sharded (spmd_pipeline.shard_params), so "
                "the flat concat would gather them to full size every "
                "step — use it on the replicated-param CNN trainer paths "
                "(gspmd/ddp) — no silent ignores")
        self.tx = make_optimizer(config.optimizer, config.steps_per_epoch,
                                 config.epochs)
        self._step = make_spmd_train_step(
            cfg, self.spec, self.tx,
            num_microbatches=config.num_microbatches,
            schedule=config.pipeline_schedule,
            virtual_stages=config.virtual_stages)

        host_params = tfm.init_params(jax.random.key(config.seed), cfg)
        if config.virtual_stages > 1:
            from distributed_model_parallel_tpu.parallel.spmd_pipeline import (
                interleave_block_rows,
            )

            host_params["blocks"] = interleave_block_rows(
                host_params["blocks"], cfg.n_layers, self.spec.num_stages,
                config.virtual_stages)
        self.opt_state = jax.device_put(
            self.tx.init(host_params), NamedSharding(self.spec.mesh, P()))
        self.params = shard_params(host_params, cfg, self.spec)

        self.tokens = make_token_stream(cfg.vocab_size, config.n_tokens,
                                        config.seed)
        # Train/eval split: training samples only from the head of the
        # stream; eval scores fixed batches from the held-out tail.
        self._n_train = int(len(self.tokens) * (1.0 - config.eval_fraction))
        min_train = config.seq_len + 2
        if not (0.0 <= config.eval_fraction < 1.0):
            raise ValueError(
                f"eval_fraction must be in [0, 1), got {config.eval_fraction}")
        if self._n_train < min_train:
            raise ValueError(
                f"eval_fraction={config.eval_fraction} leaves only "
                f"{self._n_train} training tokens (< seq_len + 2)")
        self._eval_loss = None
        tail_fits = len(self.tokens) - config.seq_len - 1 > self._n_train
        if config.eval_batches is None:
            # Auto: eval when the tail fits a window, warn-and-skip when it
            # doesn't (long-context configs where 0.1*n_tokens < seq_len+1
            # must not become hard startup failures — ADVICE r3).
            self._n_eval_batches = 8 if tail_fits else 0
            if not tail_fits and config.eval_fraction > 0.0:
                import warnings

                warnings.warn(
                    f"held-out tail ({len(self.tokens) - self._n_train} "
                    f"tokens, eval_fraction={config.eval_fraction}) cannot "
                    f"fit one seq_len={config.seq_len} eval window; "
                    f"disabling eval (set eval_batches explicitly to make "
                    f"this an error)", stacklevel=2)
                # Nothing will ever read the carved-out tail — give it back
                # to training rather than silently dropping 10% of the
                # stream.
                self._n_train = len(self.tokens)
        else:
            self._n_eval_batches = config.eval_batches
        if self._n_eval_batches > 0 and config.eval_fraction > 0.0:
            # The held-out tail must fit at least one eval window, or
            # evaluate() would die mid-fit on an opaque rng bound error.
            if not tail_fits:
                raise ValueError(
                    f"eval tail ({len(self.tokens) - self._n_train} tokens, "
                    f"eval_fraction={config.eval_fraction}) cannot fit one "
                    f"seq_len={config.seq_len} eval window; raise "
                    f"eval_fraction/n_tokens or set eval_batches=0")
            from distributed_model_parallel_tpu.parallel.spmd_pipeline import (
                make_spmd_eval_loss,
            )

            self._eval_loss = make_spmd_eval_loss(
                cfg, self.spec, num_microbatches=config.num_microbatches)
        self._rng = np.random.default_rng(config.seed + 1)
        from distributed_model_parallel_tpu.train.preemption import (
            PreemptionGuard,
        )

        self.preemption = PreemptionGuard()
        # Analytic model FLOPs per train step (utils/profiling): lets the
        # report CLI compute MFU from the telemetry stream alone (flops /
        # n_devices / step_time / chip peak).
        from distributed_model_parallel_tpu.utils.profiling import (
            lm_model_flops,
        )

        self.logger = RunLogger(
            config.log_dir, config.log_name,
            meta=dict(workload="lm",
                      batch_size=config.batch_size,
                      seq_len=config.seq_len,
                      tokens_per_step=config.batch_size * config.seq_len,
                      mesh=config.mesh.axis_sizes(),
                      pipeline_schedule=config.pipeline_schedule,
                      model_flops_per_step=lm_model_flops(
                          cfg, config.batch_size, config.seq_len)))
        # Span sink for this thread (utils/tracing.py) — resume/checkpoint
        # spans below land on this run's stream.
        tracing.install(self.logger.telemetry)
        # Live status exporter (utils/statusz.py) — see Trainer: start or
        # join the process's exporter, publish this run under /statusz.
        from distributed_model_parallel_tpu.utils import statusz

        statusz.maybe_serve(config.statusz_port)
        statusz.register_trainer(self, "lm")
        from distributed_model_parallel_tpu.train.resilience import (
            RecoverySupervisor,
        )
        from distributed_model_parallel_tpu.utils.faults import FaultInjector

        self.faults = FaultInjector(config.recovery.faults)
        from distributed_model_parallel_tpu.utils.faults import (
            validate_corruption_plan,
        )

        # Topology validation before the supervisor: its "arm the
        # sentinel" advice is useless on a dp=1 mesh.
        validate_corruption_plan(self.faults.plan, self.spec.num_data,
                                 context=f"dp={self.spec.num_data}")
        # Slice identity for the device-health sentinel feeds
        # (utils/health.py; no-ops outside orchestrated runs).
        self._device_ids = tuple(sorted(
            d.id for d in np.asarray(self.spec.mesh.devices).flat))
        self.ckpt = Checkpointer(config.checkpoint_dir,
                                 keep=config.recovery.keep_checkpoints,
                                 injector=self.faults,
                                 meta_fn=self._ckpt_meta)
        self.resilience = RecoverySupervisor(
            config.recovery, logger=self.logger, ckpt=self.ckpt,
            preemption=self.preemption, slot="lm-good", injector=self.faults,
            check_finite_every=config.check_finite_every,
            consistency_every=config.consistency_every,
            device_ids=self._device_ids)
        from distributed_model_parallel_tpu.train.guards import GuardRunner

        self.guards = GuardRunner(
            check_finite_every=config.check_finite_every,
            stall_budget_s=config.stall_budget_s, logger=self.logger,
            watchdog_interval_s=config.recovery.watchdog_interval_s,
            on_stall=self.resilience.on_stall, injector=self.faults,
            device_ids=self._device_ids)
        from distributed_model_parallel_tpu.train.consistency import (
            ConsistencySentinel,
        )

        self.sentinel = ConsistencySentinel(
            config.consistency_every, self.spec, logger=self.logger,
            guards=self.guards,
            barrier_timeout_s=config.recovery.barrier_timeout_s)
        from distributed_model_parallel_tpu.train.elastic import (
            EmergencyCheckpointer,
        )

        self.emergency = EmergencyCheckpointer(
            self.ckpt, "lm-emergency", config.emergency_every,
            logger=self.logger)
        self.start_epoch = 0
        # Cooperative-scheduling hook (orchestrator/): called with this
        # trainer at every train-step boundary, before the preemption poll
        # — see Trainer.step_hook.
        self.step_hook = None
        # Exact-continuation position: the next (epoch, step) the training
        # loop will sample. Batches are derived statelessly from
        # (seed, epoch, step), so this pair IS the data-loader state
        # (train/elastic.py).
        self._pos_epoch = 0
        self._pos_step = 0
        self._global_step = 0
        if self.elastic_decision is not None and self.elastic_decision.changed:
            self.logger.log_line(self.elastic_decision.describe())
            self.logger.telemetry.event(self.elastic_decision.describe())
        if config.resume and any(self.ckpt.exists(n)
                                 for n in ("lm", "lm-preempt",
                                           "lm-emergency", "lm-good")):
            self._resume()
        if self.plan_decision is not None:
            # After _resume so an elastic re-plan is stamped with the
            # exact global step the run continues from.
            from distributed_model_parallel_tpu.autotune.planner import (
                emit_plan_record,
            )

            emit_plan_record(self.logger.telemetry, self.plan_decision,
                             global_step=self._global_step)
            self.logger.log_line(self.plan_decision.describe())

    # ------------------------------------------------------------------ data
    def sample_batch(self, epoch: int | None = None,
                     step: int | None = None
                     ) -> tuple[np.ndarray, np.ndarray]:
        """One training batch. With ``(epoch, step)`` the batch is derived
        statelessly from ``(seed, epoch, step)`` — the training loop's
        path, so a resumed run draws exactly the batches an uninterrupted
        run would have (train/elastic.py). Without them, the legacy
        consumed-rng stream (ad-hoc/interactive use)."""
        b, t = self.config.batch_size, self.config.seq_len
        if epoch is None or step is None:
            rng = self._rng
        else:
            rng = np.random.default_rng(
                (self.config.seed + 1, int(epoch), int(step)))
        starts = rng.integers(0, self._n_train - t - 1, size=b)
        idx = starts[:, None] + np.arange(t + 1)[None]
        chunk = self.tokens[idx]
        return chunk[:, :-1], chunk[:, 1:]

    def eval_batches(self):
        """Deterministic held-out batches from the stream's tail (same
        batches every epoch, so loss_val curves are comparable)."""
        b, t = self.config.batch_size, self.config.seq_len
        rng = np.random.default_rng(self.config.seed + 2)
        lo, hi = self._n_train, len(self.tokens) - t - 1
        for _ in range(self._n_eval_batches):
            starts = rng.integers(lo, hi, size=b)
            idx = starts[:, None] + np.arange(t + 1)[None]
            chunk = self.tokens[idx]
            yield chunk[:, :-1], chunk[:, 1:]

    def _canonical_params(self):
        """Params with blocks in canonical layer order. Under interleaved
        virtual stages the run's working layout is the interleaved storage
        order; the GPipe-forward eval loss composes layers in row order,
        so it must see the canonical stack (a layer-permuted model would
        evaluate silently wrong)."""
        if self.config.virtual_stages == 1:
            return self.params
        from distributed_model_parallel_tpu.parallel.spmd_pipeline import (
            deinterleave_block_rows,
        )

        out = dict(self.params)
        blocks_c = deinterleave_block_rows(
            self.params["blocks"], self.cfg.n_layers, self.spec.num_stages,
            self.config.virtual_stages)
        # The row gather drops the NamedSharding; pin each leaf back to its
        # working-layout sharding (same shapes, so specs carry over).
        out["blocks"] = jax.tree.map(
            lambda c, o: jax.device_put(c, o.sharding),
            blocks_c, self.params["blocks"])
        return out

    def evaluate(self) -> float:
        """Mean held-out loss over the fixed eval batches.

        All batches are dispatched back-to-back and fetched with ONE
        host sync (vectorized numpy mean) — the per-batch ``float()``
        drain serialized upload/compute across eval batches through a
        remote device transport (one blocking round trip each)."""
        if self._eval_loss is None:
            raise ValueError("eval disabled (eval_batches=0 or "
                             "eval_fraction=0)")
        eval_params = self._canonical_params()
        # Bounded run-ahead (the Trainer.evaluate _max_inflight pattern):
        # a large explicit eval_batches must not hold every batch's
        # input buffers + in-flight computations on device at once.
        max_inflight = 8
        vals: list = []
        pending: list = []
        for toks, tgts in self.eval_batches():
            pending.append(self._eval_loss(eval_params, jnp.asarray(toks),
                                           jnp.asarray(tgts)))
            if len(pending) >= max_inflight:
                vals.extend(jax.device_get(pending))
                pending.clear()
        vals.extend(jax.device_get(pending))
        if not vals:
            return 0.0
        return float(np.mean(np.asarray(vals, dtype=np.float64)))

    # ----------------------------------------------------------- checkpoint
    def _ckpt_meta(self):
        """Manifest stamp: saving topology + exact position
        (train/checkpoint.py, train/elastic.py)."""
        return {"workload": "lm",
                "mesh": {**self.config.mesh.axis_sizes(),
                         "dcn_data": self.config.mesh.dcn_data},
                "n_devices": int(np.asarray(self.spec.mesh.devices).size),
                "global_step": self._global_step}

    def _resume_tree(self):
        from distributed_model_parallel_tpu.train import elastic

        return elastic.build_resume_tree(
            self._pos_epoch, self._pos_step, self.config.steps_per_epoch,
            self._global_step, self.resilience.budgets())

    def _ckpt_tree(self):
        # virtual_stages is part of the checkpoint identity: params AND
        # optimizer state rows live in the interleaved storage order, so a
        # resume under a different V would restore a layer-permuted model
        # whose shapes all match — detectable only by this marker.
        return {"params": self.params, "opt_state": self.opt_state,
                "epoch": jnp.asarray(self.start_epoch, jnp.int32),
                "virtual_stages": jnp.asarray(
                    self.config.virtual_stages, jnp.int32),
                "resume": self._resume_tree()}

    def _apply_resume_tree(self, restored: dict, *, budgets: bool) -> None:
        """Adopt the exact-continuation position; see Trainer for the
        ``budgets`` contract (False on in-run recovery restores)."""
        from distributed_model_parallel_tpu.train import elastic

        ri = restored.get("resume")
        if ri is None:
            return
        (self._pos_epoch, self._pos_step, self._global_step,
         retries, lr_scale) = elastic.unpack_resume_tree(ri)
        if budgets:
            self.resilience.restore_budgets(retries, lr_scale)
            if lr_scale != 1.0:
                self._apply_lr_shrink(lr_scale)

    def _resume(self):
        from distributed_model_parallel_tpu.train import elastic

        # Newest-valid slot wins: end-of-epoch "lm", the preemption save,
        # or a step-cadence emergency save — restored through
        # restore_resharded so a checkpoint from a different mesh degree
        # lands in this mesh's shardings. Template ladder: current tree,
        # then pre-elastic (no "resume" subtree), then pre-round-5 (no
        # virtual_stages marker either; its absence means V=1).
        tmpl = self._ckpt_tree()
        t2 = {k: v for k, v in tmpl.items() if k != "resume"}
        t3 = {k: v for k, v in t2.items() if k != "virtual_stages"}
        name, restored = elastic.elastic_restore(
            self.ckpt, (tmpl, t2, t3),
            # The supervisor's good slot is the last resort: it makes a
            # torn preemption/emergency save survivable (dmp_soak.py).
            ("lm", "lm-preempt", "lm-emergency", "lm-good"),
            on_fallback=self.resilience.note_fallback)
        ckpt_v = int(restored.get("virtual_stages", 1))
        if ckpt_v != self.config.virtual_stages:
            raise ValueError(
                f"checkpoint was written with virtual_stages={ckpt_v} "
                f"(blocks+opt-state rows in that interleaved storage "
                f"order) but this run has virtual_stages="
                f"{self.config.virtual_stages}; convert the blocks with "
                f"parallel.spmd_pipeline.deinterleave_block_rows/"
                f"interleave_block_rows (optimizer state rows too) or "
                f"resume with the matching V")
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self.start_epoch = int(restored["epoch"])
        self._apply_resume_tree(restored, budgets=True)
        self.start_epoch = max(self.start_epoch, self._pos_epoch)
        # Provenance from the version actually read (a torn-newest
        # fallback may have restored an older one).
        from distributed_model_parallel_tpu.train.checkpoint import (
            read_manifest_meta,
        )

        saved_mesh = (read_manifest_meta(self.ckpt.last_restored_path)
                      if self.ckpt.last_restored_path else {}).get("mesh")
        current_mesh = self._ckpt_meta()["mesh"]
        self.logger.telemetry.resume(
            slot=name, epoch=self.start_epoch,
            loader_epoch=self._pos_epoch, batch_cursor=self._pos_step,
            global_step=self._global_step, mesh=current_mesh,
            **({"saved_mesh": saved_mesh}
               if saved_mesh and saved_mesh != current_mesh else {}))
        self.logger.log_line(
            f"resume: slot {name!r} -> epoch {self.start_epoch} "
            f"step {self._pos_step} (global step {self._global_step})"
            + (f", resharded from mesh {saved_mesh}"
               if saved_mesh and saved_mesh != current_mesh else ""))

    def _restore_good(self):
        """Recovery restore from the supervisor's "last good" slot
        (train/resilience.py), with torn-version fallback. Position rides
        along; budgets stay live (see Trainer._restore_good)."""
        restored = self.ckpt.restore(
            self._ckpt_tree(), self.resilience.slot, allow_fallback=True,
            on_fallback=self.resilience.note_fallback)
        self.params = restored["params"]
        self.opt_state = restored["opt_state"]
        self._apply_resume_tree(restored, budgets=False)

    def _apply_lr_shrink(self, factor: float) -> None:
        """Recovery-time LR shrink: rebuild the optimizer and the jitted
        train step at the scaled LR — opt_state structure is unchanged (the
        schedule is a closure), so the restored state carries over."""
        opt = dataclasses.replace(
            self.config.optimizer,
            learning_rate=self.config.optimizer.learning_rate * factor)
        self.config = dataclasses.replace(self.config, optimizer=opt)
        self.tx = make_optimizer(opt, self.config.steps_per_epoch,
                                 self.config.epochs)
        self._step = make_spmd_train_step(
            self.cfg, self.spec, self.tx,
            num_microbatches=self.config.num_microbatches,
            schedule=self.config.pipeline_schedule,
            virtual_stages=self.config.virtual_stages)

    # ----------------------------------------------------------------- loop
    def _poll_step_faults(self, step_m):
        """Serve planned step-site faults (utils/faults.py): poison this
        step's loss or the live params, silently corrupt one replica's
        params, or request a simulated preemption. Returns the (possibly
        poisoned) step metrics."""
        from distributed_model_parallel_tpu.utils.faults import (
            CORRUPTION_KINDS,
            corrupt_one_replica,
            poison,
        )

        for spec in self.faults.poll("step"):
            if spec.kind == "preempt":
                self.preemption.request()
            elif spec.kind == "nan_loss":
                step_m = poison(step_m)
            elif spec.kind == "nan_params":
                self.params = poison(self.params)
            elif spec.kind in CORRUPTION_KINDS:
                self.params = corrupt_one_replica(
                    self.params, self.spec, spec.kind, spec.param)
        return step_m

    def _run_sentinel(self, n_steps: int, *, flush: bool = False) -> None:
        """Advance the consistency sentinel (train/consistency.py) — or,
        with ``flush=True``, check any steps the cadence hasn't covered
        (end of epoch, before the good slot is stamped) — and splice a
        repaired params/opt_state pair back in place. No-quorum
        divergence raises into fit()'s recovery handler."""
        tree_fn = lambda: {"params": self.params,
                           "opt_state": self.opt_state}
        fixed = (self.sentinel.flush(tree_fn) if flush
                 else self.sentinel.after_sync(n_steps, tree_fn))
        if fixed is not None:
            self.params = fixed["params"]
            self.opt_state = fixed["opt_state"]

    def _train_one_epoch(self, epoch: int, epochs: int) -> dict | None:
        """One training epoch + eval. Returns the history record, or None
        when a preemption stopped the epoch mid-way (checkpoint already
        written). Raises NonFiniteError through to fit()'s recovery path."""
        meter = AverageMeter("loss")
        drop_meter = AverageMeter("moe_drop")
        timer = StepTimer()
        tokens_per_step = (self.config.batch_size
                           * self.config.seq_len)
        # Start of `epoch`, or the mid-epoch cursor a resumed run loaded
        # (train/elastic.py). Batches are stateless in (epoch, step), so
        # the continuation draws exactly what the uninterrupted run would.
        if epoch != self._pos_epoch:
            self._pos_epoch, self._pos_step = epoch, 0
        start = self._pos_step
        for step_i in range(start, self.config.steps_per_epoch):
            if self.step_hook is not None:
                self.step_hook(self)
            if self.preemption.requested():
                break
            toks, tgts = self.sample_batch(epoch, step_i)
            timer.data_ready()
            self.params, self.opt_state, step_m = self._step(
                self.params, self.opt_state, jnp.asarray(toks),
                jnp.asarray(tgts))
            if self.faults.enabled:
                step_m = self._poll_step_faults(step_m)
            with self.guards.watch():
                # the per-step sync point
                loss_host = float(step_m["loss"])
            if self.guards.enabled:
                self.guards.after_sync({"loss": loss_host}, 1,
                                       params=self.params)
            if self.sentinel.enabled:
                self._run_sentinel(1)
            meter.update(loss_host)
            if "moe_drop" in step_m:
                drop_meter.update(float(step_m["moe_drop"]))
            self._pos_step = step_i + 1
            self._global_step += 1
            timer.step_done()
            # Per-step health signal (the LM loop syncs every step, so
            # this is a true per-step time; utils/health.py — no-op
            # outside orchestrated runs, first compile window skipped).
            from distributed_model_parallel_tpu.utils import health

            health.observe_step_warmed(self, self._device_ids,
                                       timer.step.last, 1)
            # Per-step telemetry (the LM loop syncs every step, so
            # the per-step timing is real, not a window average).
            self.logger.telemetry.step(
                epoch=epoch, step=step_i, loss=loss_host,
                step_time_s=timer.step.last,
                data_time_s=timer.data.last,
                tokens_per_s=tokens_per_step
                / max(timer.step.last, 1e-9))
            self.emergency.after_step(1, self._ckpt_tree)
        if self.sentinel.enabled:
            # Cover any tail steps the cadence missed before the epoch is
            # declared clean (or a preempt checkpoint is written) — an
            # epoch shorter than the cadence would otherwise never be
            # checked at all (train/consistency.py flush).
            self._run_sentinel(0, flush=True)
        if self.preemption.requested():
            # Partial epoch: save for resume at this epoch and stop
            # cleanly (train/preemption.py).
            from distributed_model_parallel_tpu.train.preemption import (
                checkpoint_on_preempt,
            )

            self.start_epoch = epoch
            checkpoint_on_preempt(self.preemption, self.ckpt,
                                  self._ckpt_tree(), "lm-preempt",
                                  self.logger, epoch,
                                  global_step=self._global_step)
            return None
        from distributed_model_parallel_tpu.train.trainer import (
            eval_now,
        )

        if (self._eval_loss is not None
                and eval_now(epoch, epochs, self.config.eval_every)):
            with span("evaluate", epoch=epoch):
                loss_val = self.evaluate()
        else:
            loss_val = None
        record = dict(epoch=epoch, loss_train=meter.avg,
                      loss_val=loss_val,
                      time_per_batch=timer.step.avg,
                      time_load_per_batch=timer.data.avg,
                      tokens_per_s=self.config.batch_size
                      * self.config.seq_len / max(timer.step.avg, 1e-9))
        if drop_meter.count:
            # MoE router observability: mean fraction of
            # token-choices dropped at capacity this epoch
            # (ops/moe._route — silent overflow made visible).
            record["moe_drop_rate"] = drop_meter.avg
        return record

    def fit(self, epochs: int | None = None) -> list[dict]:
        """Epoch loop with eval, per-epoch checkpointing, preemption-safe
        stop, and (when ``recovery.max_retries > 0``) automatic restore-
        and-retry on non-finite detections (train/resilience.py)."""
        from distributed_model_parallel_tpu.train.guards import (
            NonFiniteError,
            ReplicaDivergenceError,
        )

        epochs = epochs if epochs is not None else self.config.epochs
        history = []
        with self.preemption.installed():
            self.resilience.begin(self._ckpt_tree)
            epoch = self.start_epoch
            while epoch < epochs:
                try:
                    with span("train_epoch", epoch=epoch):
                        record = self._train_one_epoch(epoch, epochs)
                except NonFiniteError as e:
                    if self.resilience.recover_nonfinite(
                            e, epoch=epoch, restore=self._restore_good,
                            shrink_lr=self._apply_lr_shrink):
                        continue        # state restored — redo the epoch
                    raise
                except ReplicaDivergenceError as e:
                    if self.resilience.recover_divergence(
                            e, epoch=epoch, restore=self._restore_good):
                        continue        # state restored — redo the epoch
                    raise
                if record is None:      # preempted mid-epoch
                    break
                self.logger.log_epoch(**record)
                self.logger.telemetry.memory()
                history.append(record)
                self.start_epoch = epoch + 1
                self.ckpt.save(self._ckpt_tree(), "lm")
                # Finite-checked epoch state = the recovery restore point.
                self.resilience.note_good(self._ckpt_tree)
                epoch += 1
        self.logger.finish(epochs_run=len(history))
        return history
