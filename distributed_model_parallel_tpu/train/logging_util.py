"""Run logging: reference-format text lines + structured JSONL.

The reference appends one line per epoch to a text file —
``step/loss_train/acc1_train/loss_val/acc1_val`` (+ per-batch timings in the
pipeline driver) — ``data_parallel.py:167-171``, ``model_parallel.py:119-124``,
and prints every 30 batches (``data_parallel.py:116-117``, ``utils.py:69-70``).
We keep that text format for diffability and add a JSONL stream for tooling.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any


class RunLogger:
    def __init__(self, log_dir: str, name: str, *, echo: bool = True):
        os.makedirs(log_dir, exist_ok=True)
        self.txt_path = os.path.join(log_dir, f"{name}.txt")
        self.jsonl_path = os.path.join(log_dir, f"{name}.jsonl")
        self.echo = echo

    def log_epoch(self, epoch: int, **metrics: Any) -> None:
        # Text line mirrors the reference's epoch record (data_parallel.py:167-171).
        parts = [f"epoch:{epoch}"] + [
            f"{k}:{v:.6g}" if isinstance(v, float) else f"{k}:{v}"
            for k, v in metrics.items()
        ]
        line = " ".join(parts)
        with open(self.txt_path, "a") as f:
            f.write(line + "\n")
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps({"ts": time.time(), "epoch": epoch, **{
                k: (float(v) if hasattr(v, "__float__") else v)
                for k, v in metrics.items()}}) + "\n")
        if self.echo:
            print(line, flush=True)

    def log_line(self, message: str) -> None:
        """Free-form event line (preemption, guard trips) to both sinks."""
        with open(self.txt_path, "a") as f:
            f.write(message + "\n")
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps({"ts": time.time(), "event": message}) + "\n")
        if self.echo:
            print(message, flush=True)

    def log_step(self, epoch: int, step: int, **metrics: Any) -> None:
        if self.echo:
            parts = [f"[{epoch}:{step}]"] + [
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in metrics.items()]
            print(" ".join(parts), flush=True)
