"""Run logging: reference-format text lines + the telemetry event stream.

The reference appends one line per epoch to a text file —
``step/loss_train/acc1_train/loss_val/acc1_val`` (+ per-batch timings in the
pipeline driver) — ``data_parallel.py:167-171``, ``model_parallel.py:119-124``,
and prints every 30 batches (``data_parallel.py:116-117``, ``utils.py:69-70``).
We keep that text format for diffability; the structured side is no longer a
parallel ad-hoc JSONL code path but a sink of ``utils/telemetry.TelemetryRun``
— the same ``{name}.jsonl`` file now carries the typed record stream
(``run_start``/``step``/``epoch``/``event``/...) that ``scripts/dmp_report.py``
renders into a run report.
"""

from __future__ import annotations

import os
from typing import Any, Mapping

from distributed_model_parallel_tpu.utils.telemetry import TelemetryRun


class RunLogger:
    def __init__(self, log_dir: str, name: str, *, echo: bool = True,
                 telemetry: TelemetryRun | None = None,
                 meta: Mapping[str, Any] | None = None):
        os.makedirs(log_dir, exist_ok=True)
        self.txt_path = os.path.join(log_dir, f"{name}.txt")
        self.jsonl_path = os.path.join(log_dir, f"{name}.jsonl")
        self.echo = echo
        # The JSONL sink IS the telemetry stream (no second format): callers
        # may inject a shared TelemetryRun; by default the logger opens one
        # at the historical jsonl path.
        self.telemetry = telemetry if telemetry is not None else TelemetryRun(
            self.jsonl_path, run=name, meta=meta)

    def log_epoch(self, epoch: int, **metrics: Any) -> None:
        # Text line mirrors the reference's epoch record (data_parallel.py:167-171).
        parts = [f"epoch:{epoch}"] + [
            f"{k}:{v:.6g}" if isinstance(v, float) else f"{k}:{v}"
            for k, v in metrics.items()
        ]
        line = " ".join(parts)
        with open(self.txt_path, "a") as f:
            f.write(line + "\n")
        self.telemetry.epoch(epoch=epoch, **metrics)
        if self.echo:
            print(line, flush=True)

    def log_line(self, message: str) -> None:
        """Free-form event line (preemption, guard trips) to both sinks."""
        with open(self.txt_path, "a") as f:
            f.write(message + "\n")
        self.telemetry.event(message)
        if self.echo:
            print(message, flush=True)

    def log_step(self, epoch: int, step: int, **metrics: Any) -> None:
        """Per-step record: echoed at the reference's cadence AND persisted
        as a telemetry ``step`` record (timing + throughput keys)."""
        self.telemetry.step(epoch=epoch, step=step, **metrics)
        if self.echo:
            parts = [f"[{epoch}:{step}]"] + [
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in metrics.items()]
            print(" ".join(parts), flush=True)

    def finish(self, **fields: Any) -> None:
        """Close out the run stream (registry snapshot + run_end)."""
        self.telemetry.finish(**fields)
