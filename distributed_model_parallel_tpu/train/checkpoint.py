"""Checkpoint / resume.

Capability parity with the reference's best-accuracy checkpointing —
save ``{net, acc, epoch}`` to ``./checkpoint/ckpt.pth`` when val accuracy
improves, restore on ``--resume`` (``data_parallel.py:80-87,143-155``) —
upgraded to the TPU-native form: orbax sharded pytree checkpoints that
save/restore distributed ``jax.Array``s directly (multi-host safe), covering
params, BN state, optimizer state, step and best-acc in one tree.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
import zlib
from typing import Any, Callable

import jax
import orbax.checkpoint as ocp

from distributed_model_parallel_tpu.utils.tracing import span
from distributed_model_parallel_tpu.utils.faults import (
    FaultInjector,
    InjectedFaultError,
    tear_checkpoint,
)

# Per-checkpoint integrity manifest, written into each version directory
# once its save has committed: relative path -> {size, crc32} for every
# file, plus an optional ``meta`` stamp (saving mesh shape/axis names,
# global step — the topology record elastic resume reads,
# train/elastic.py). A torn/truncated/partially-copied version fails
# verification and ``restore(..., allow_fallback=True)`` skips it. Absence
# of a manifest is "unverifiable" (legacy / foreign checkpoint), not "bad".
MANIFEST_FILENAME = "dmp_manifest.json"


class CheckpointIntegrityError(RuntimeError):
    """No committed checkpoint version survived verification/restore."""


class TopologyMismatchError(RuntimeError):
    """A checkpoint's *global* array shapes conflict with the restore
    target's — state that genuinely depends on the saving topology (e.g.
    the DDP engine's per-replica BatchNorm stats carry a leading
    ``num_replicas`` axis) cannot be resharded onto a mesh of a different
    degree. Carries both shapes per conflicting leaf; deliberately NOT a
    ``ValueError`` so the trainers' template-layout retry loops don't
    misread it as an EMA-layout mismatch."""

    def __init__(self, conflicts: list, *, saved_mesh=None,
                 current_mesh=None):
        self.conflicts = list(conflicts)
        self.saved_mesh = saved_mesh
        self.current_mesh = current_mesh
        detail = "; ".join(
            f"{path}: checkpoint {tuple(saved)} vs target {tuple(want)}"
            for path, saved, want in self.conflicts[:8])
        mesh = ""
        if saved_mesh or current_mesh:
            mesh = (f" (saved on mesh {saved_mesh}, restoring on "
                    f"{current_mesh})")
        super().__init__(
            f"checkpoint global shapes conflict with the restore target on "
            f"{len(self.conflicts)} leaves{mesh}: {detail}")


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def write_manifest(path: str, meta: dict | None = None) -> str:
    """Write the integrity manifest for a committed checkpoint directory
    (atomic: temp file + rename). ``meta`` is the caller's stamp (mesh
    shape/axis names, global step); it is recorded verbatim and never
    participates in verification. Returns the manifest path."""
    entries: dict[str, dict] = {}
    for root, _dirs, files in os.walk(path):
        for fn in files:
            if fn == MANIFEST_FILENAME:
                continue
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, path)
            entries[rel] = {"size": os.path.getsize(p),
                            "crc32": _file_crc32(p)}
    out = os.path.join(path, MANIFEST_FILENAME)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"created": time.time(), "files": entries,
                   "meta": dict(meta or {})}, f)
    os.replace(tmp, out)
    return out


def read_manifest_meta(path: str) -> dict:
    """The ``meta`` stamp of one checkpoint version directory; ``{}`` when
    there is no manifest or no stamp (legacy/foreign checkpoint)."""
    try:
        with open(os.path.join(path, MANIFEST_FILENAME)) as f:
            return dict(json.load(f).get("meta") or {})
    except (OSError, json.JSONDecodeError, ValueError, TypeError):
        return {}


def _keystr(path) -> str:
    """Normalize a jax keypath so a flax-struct attribute, a dict key and a
    tuple index spell the same as orbax's metadata dict-tree paths."""
    parts = []
    for k in path:
        if hasattr(k, "name"):
            parts.append(str(k.name))
        elif hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:                    # pragma: no cover - future key types
            parts.append(str(k))
    return "/".join(parts)


def tree_shape_map(tree: Any) -> dict[str, tuple]:
    """``normalized path -> global shape`` for every leaf that has one."""
    import jax.tree_util as jtu

    out = {}
    for path, leaf in jtu.tree_flatten_with_path(tree)[0]:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            out[_keystr(path)] = tuple(shape)
    return out


def verify_manifest(path: str) -> str | None:
    """Check a checkpoint directory against its manifest.

    Returns ``None`` when every recorded file matches (size + crc32),
    ``"missing"`` when there is no manifest to check (unverifiable, not
    necessarily bad), and a human-readable mismatch reason otherwise.
    """
    mpath = os.path.join(path, MANIFEST_FILENAME)
    if not os.path.exists(mpath):
        return "missing"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (json.JSONDecodeError, KeyError, OSError) as e:
        return f"unreadable manifest: {type(e).__name__}"
    for rel, want in files.items():
        p = os.path.join(path, rel)
        if not os.path.exists(p):
            return f"missing file {rel}"
        size = os.path.getsize(p)
        if size != want["size"]:
            return (f"size mismatch on {rel} "
                    f"({size} != {want['size']} bytes)")
        if _file_crc32(p) != want["crc32"]:
            return f"checksum mismatch on {rel}"
    return None


class Checkpointer:
    """Best-acc checkpoint + resume over an orbax StandardCheckpointer.

    Saves may be asynchronous (``wait=False``): orbax copies the arrays to
    host, then persists on a background thread while training continues —
    the step after a checkpoint no longer stalls behind filesystem writes.

    Crash safety: each save writes a fresh ``{name}-{v}`` directory (orbax
    commits it with an atomic rename); older versions are pruned only at the
    *next* save, after confirming the newer one committed, and the newest
    ``keep`` committed versions are retained per slot. So there is never a
    moment with zero committed checkpoints on disk, and a reader in another
    process sees whichever version last committed. ``restore`` / ``exists``
    resolve to the newest committed version (falling back to a bare legacy
    ``{name}`` directory).

    Integrity: once a save commits, an integrity manifest (file sizes +
    crc32 checksums) is written into the version directory.
    ``restore(..., allow_fallback=True)`` verifies each candidate version
    against its manifest (and survives a restore-time failure on
    manifest-less versions) and falls back to the previous committed
    version — the torn-newest-checkpoint recovery path
    (train/resilience.py).

    ``injector`` (utils/faults.py) is the chaos hook: ``save_fail`` /
    ``tear_save`` faults fire at their planned occurrence of the ``save``
    site. Disabled injectors cost one no-op poll per save.
    """

    def __init__(self, directory: str, *, keep: int = 2,
                 injector: FaultInjector | None = None,
                 meta_fn: Callable[[], dict] | None = None):
        self.directory = os.path.abspath(directory)
        self.keep = max(1, int(keep))
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()
        self._injector = injector
        # Stamp every committed version's manifest with this callable's
        # dict (mesh shape, global step — captured at save() call time,
        # not at async commit time): the topology record
        # restore_resharded / train/elastic.py read back.
        self.meta_fn = meta_fn
        # (path, meta) pairs whose manifest still needs writing once the
        # (possibly asynchronous) save commits.
        self._pending_manifest: list[tuple[str, dict]] = []
        # Version directory the last restore_resharded actually read —
        # may be an OLDER version than the slot's newest after a
        # torn-newest fallback, so provenance (read_manifest_meta) must
        # come from here, not from manifest_meta(name).
        self.last_restored_path: str | None = None

    def _path(self, name: str, version: int | None = None) -> str:
        leaf = name if version is None else f"{name}-{version}"
        return os.path.join(self.directory, leaf)

    def _versions(self, name: str) -> list[int]:
        """Committed version numbers for ``name``, ascending. Orbax tmp dirs
        carry a ``.orbax-checkpoint-tmp`` suffix and never match."""
        pat = re.compile(re.escape(name) + r"-(\d+)$")
        out = []
        for entry in os.listdir(self.directory):
            m = pat.match(entry)
            if m and os.path.isdir(os.path.join(self.directory, entry)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _latest_path(self, name: str) -> str | None:
        versions = self._versions(name)
        if versions:
            return self._path(name, versions[-1])
        legacy = self._path(name)
        return legacy if os.path.exists(legacy) else None

    def _candidate_paths(self, name: str) -> list[str]:
        """Restore candidates, newest committed version first, legacy bare
        directory last."""
        out = [self._path(name, v)
               for v in sorted(self._versions(name), reverse=True)]
        legacy = self._path(name)
        if os.path.exists(legacy):
            out.append(legacy)
        return out

    def save(self, tree: Any, name: str = "ckpt", *, force: bool = True,
             wait: bool = True, keep: int | None = None,
             meta: dict | None = None) -> str:
        # Checkpoint I/O on the span timeline (utils/tracing.py): saves
        # sit on a trainer's critical path, so a slow disk shows up as a
        # wide checkpoint_save bar, not an anonymous step-time bump.
        with span("checkpoint_save", slot=name, wait=wait):
            return self._save(tree, name, wait=wait, keep=keep, meta=meta)

    def _save(self, tree: Any, name: str, *, wait: bool,
              keep: int | None, meta: dict | None) -> str:
        self.wait_until_finished()  # the previous save has committed...
        versions = self._versions(name)
        # Retention is strictly per-slot: the version scan matches
        # ``{name}-{v}`` exactly, so rotating one slot (the per-epoch
        # "ckpt"/"good" saves) can never garbage-collect another (the
        # emergency slot) — tests/test_elastic.py pins this. ``keep``
        # overrides the default for this slot's own rotation.
        keep_n = max(1, int(keep)) if keep is not None else self.keep
        for v in versions[:-keep_n]:      # ...keep the newest K, prune older
            shutil.rmtree(self._path(name, v), ignore_errors=True)
        if versions and os.path.exists(self._path(name)):
            # A versioned save has committed, so a bare legacy `{name}` dir
            # (pre-versioning format) is stale — prune it too.
            shutil.rmtree(self._path(name), ignore_errors=True)
        next_v = versions[-1] + 1 if versions else 0
        path = self._path(name, next_v)
        faults = (self._injector.poll("save")
                  if self._injector is not None else [])
        if any(s.kind == "save_fail" for s in faults):
            # Die "mid-write": a torn version directory appears committed
            # to the version scan but holds no restorable checkpoint.
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "_DMP_TORN"), "w") as f:
                f.write("injected save failure\n")
            raise InjectedFaultError(f"injected save failure for {path}")
        tear = any(s.kind == "tear_save" for s in faults)
        self._ckpt.save(path, tree)
        stamp = dict(self.meta_fn() or {}) if self.meta_fn is not None else {}
        if meta:
            stamp.update(meta)
        self._pending_manifest.append((path, stamp))
        if wait or tear:
            self.wait_until_finished()
        if tear:
            tear_checkpoint(path)
        return path

    def wait_until_finished(self) -> None:
        """Block until any asynchronous save has fully committed, then
        write the integrity manifests for the newly committed versions."""
        self._ckpt.wait_until_finished()
        while self._pending_manifest:
            path, stamp = self._pending_manifest.pop()
            if os.path.isdir(path):
                write_manifest(path, meta=stamp)

    def manifest_meta(self, name: str = "ckpt") -> dict:
        """The newest committed version's manifest ``meta`` stamp (saving
        mesh, global step); ``{}`` when absent."""
        self.wait_until_finished()
        path = self._latest_path(name)
        return read_manifest_meta(path) if path is not None else {}

    def restore(self, target: Any, name: str = "ckpt", *,
                allow_fallback: bool = False,
                on_fallback: Callable[[str, str], None] | None = None) -> Any:
        """Restore the newest committed version into the structure/shardings
        of ``target`` (an abstract or concrete pytree). Raises
        FileNotFoundError if absent.

        With ``allow_fallback=True`` each candidate version (newest first)
        is verified against its integrity manifest before the restore is
        attempted, and a torn/corrupt/unrestorable version is skipped in
        favor of the previous committed one; ``on_fallback(path, reason)``
        observes every rejection (the supervisor turns it into
        failure/recovery telemetry). CheckpointIntegrityError when no
        version survives.
        """
        with span("checkpoint_restore", slot=name):
            return self._restore(target, name, allow_fallback=allow_fallback,
                                 on_fallback=on_fallback)

    def _restore(self, target: Any, name: str, *, allow_fallback: bool,
                 on_fallback: Callable[[str, str], None] | None) -> Any:
        self.wait_until_finished()
        candidates = self._candidate_paths(name)
        if not candidates:
            raise FileNotFoundError(self._path(name))
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        if not allow_fallback:
            return self._ckpt.restore(candidates[0], abstract)
        rejected: list[tuple[str, str]] = []
        for path in candidates:
            reason = verify_manifest(path)
            if reason is None:
                # Verified intact: a restore error here is a template /
                # structure problem (e.g. resuming under a different
                # config), not corruption — an older version of the same
                # run can't fix that, so fail fast with orbax's error.
                return self._ckpt.restore(path, abstract)
            if reason != "missing":
                rejected.append((path, reason))
                if on_fallback is not None:
                    on_fallback(path, reason)
                continue
            # Unverifiable (no manifest — legacy or foreign checkpoint):
            # attempt the restore and treat failure as a torn version.
            try:
                return self._ckpt.restore(path, abstract)
            except Exception as e:  # noqa: BLE001 - fall back on any failure
                detail = f"restore failed: {type(e).__name__}: {e}"
                rejected.append((path, detail))
                if on_fallback is not None:
                    on_fallback(path, detail)
        raise CheckpointIntegrityError(
            f"no restorable version of {name!r} in {self.directory}: "
            + "; ".join(f"{os.path.basename(p)} ({r[:160]})"
                        for p, r in rejected))

    def _check_topology(self, path: str, target: Any) -> None:
        """Raise :class:`TopologyMismatchError` when the checkpoint's
        *global* leaf shapes conflict with ``target``'s. Global shapes are
        mesh-independent for replicated/DDP/FSDP leaves (sharding splits a
        fixed global array), so a conflict means the state itself encodes
        the saving topology and cannot be resharded. Structure differences
        (missing/extra leaves) are left for the restore itself to report —
        they are template-layout problems, not topology ones. A metadata
        read failure is ignored here: the restore attempt will surface it
        through the normal fallback machinery."""
        try:
            meta = ocp.PyTreeCheckpointer().metadata(path)
            saved = tree_shape_map(meta)
        except Exception:  # noqa: BLE001 - torn version, fallback handles it
            return
        want = tree_shape_map(target)
        conflicts = [(k, saved[k], want[k]) for k in sorted(want)
                     if k in saved and tuple(saved[k]) != tuple(want[k])]
        if conflicts:
            raise TopologyMismatchError(
                conflicts, saved_mesh=read_manifest_meta(path).get("mesh"))

    def restore_resharded(self, target: Any, name: str = "ckpt", *,
                          allow_fallback: bool = True,
                          on_fallback: Callable[[str, str], None] | None = None,
                          verify_memo: dict | None = None) -> Any:
        """Topology-change-resilient restore: bring the newest committed
        version into the shardings of ``target`` — the *current* mesh's —
        regardless of the mesh it was saved under (a dp=8 checkpoint
        restores onto the degraded dp=4 slice a preempted TPU job got
        back). Mechanically: explicit per-leaf restore args carrying the
        target's shardings, so orbax never consults the sharding file
        written at save time (whose devices need not exist anymore).

        Global shapes must agree leaf-by-leaf; a genuine conflict (state
        that encodes the saving topology, e.g. DDP per-replica BN stats)
        raises :class:`TopologyMismatchError` with both shapes — and raises
        it *through* the fallback loop, because every version of the same
        run shares the conflict. Torn versions fall back exactly like
        :meth:`restore`.

        ``verify_memo`` caches per-path manifest verification (a full-file
        CRC sweep) across calls: elastic resume tries several template
        layouts against the same slot and must not re-read a multi-GB
        checkpoint directory once per layout (train/elastic.py).
        """
        with span("checkpoint_restore", slot=name, resharded=True):
            return self._restore_resharded(
                target, name, allow_fallback=allow_fallback,
                on_fallback=on_fallback, verify_memo=verify_memo)

    def _restore_resharded(self, target: Any, name: str, *,
                           allow_fallback: bool,
                           on_fallback: Callable[[str, str], None] | None,
                           verify_memo: dict | None) -> Any:
        self.wait_until_finished()
        candidates = self._candidate_paths(name)
        if not candidates:
            raise FileNotFoundError(self._path(name))
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)

        def _verify(path):
            if verify_memo is None:
                return verify_manifest(path)
            if path not in verify_memo:
                verify_memo[path] = verify_manifest(path)
            return verify_memo[path]

        def _restore(path):
            out = ocp.PyTreeCheckpointer().restore(
                path, args=ocp.args.PyTreeRestore(item=abstract,
                                                  restore_args=restore_args))
            self.last_restored_path = path
            return out

        if not allow_fallback:
            self._check_topology(candidates[0], target)
            return _restore(candidates[0])
        rejected: list[tuple[str, str]] = []
        for path in candidates:
            reason = _verify(path)
            if reason is not None and reason != "missing":
                rejected.append((path, reason))
                if on_fallback is not None:
                    on_fallback(path, reason)
                continue
            self._check_topology(path, target)
            if reason is None:
                # Verified intact: a restore failure here is structural
                # (wrong config/template), not corruption — fail fast.
                return _restore(path)
            try:
                return _restore(path)
            except Exception as e:  # noqa: BLE001 - unverifiable version
                detail = f"restore failed: {type(e).__name__}: {e}"
                rejected.append((path, detail))
                if on_fallback is not None:
                    on_fallback(path, detail)
        raise CheckpointIntegrityError(
            f"no restorable version of {name!r} in {self.directory}: "
            + "; ".join(f"{os.path.basename(p)} ({r[:160]})"
                        for p, r in rejected))

    def restore_subtree(self, target: Any, name: str = "ckpt") -> Any:
        """Restore only the top-level keys present in ``target`` (a dict),
        e.g. just the params of a full train-state checkpoint for
        inference. Uses orbax partial restore: only the requested subtrees
        are read from storage — a params-only restore never materializes
        the (larger) optimizer state."""
        self.wait_until_finished()
        path = self._latest_path(name)
        if path is None:
            raise FileNotFoundError(self._path(name))
        meta = self._ckpt.metadata(path)
        # Newer orbax wraps the tree in .item_metadata.tree; this
        # container's orbax returns the key->metadata mapping directly.
        tree = getattr(getattr(meta, "item_metadata", None), "tree", None)
        if tree is None:
            tree = meta
        missing = [k for k in target if k not in tree]
        if missing:
            raise KeyError(f"checkpoint {path} has no keys {missing}; "
                           f"available: {sorted(tree)}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        # Explicit per-leaf restore args carrying the TARGET's shardings:
        # without them PyTreeRestore falls back to the sharding file
        # written at save time, which breaks the moment the restoring
        # process has a different topology (e.g. a checkpoint trained on
        # an 8-device mesh restored for single-device inference —
        # scripts/generate.py's whole use case).
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)
        try:
            return ocp.PyTreeCheckpointer().restore(
                path, args=ocp.args.PyTreeRestore(item=abstract,
                                                  restore_args=restore_args,
                                                  partial_restore=True))
        except TypeError:
            # Older orbax has no partial_restore kwarg; transforms={} is its
            # spelling of the same thing (checkpoint keys absent from
            # ``item`` are dropped instead of restored).
            return ocp.PyTreeCheckpointer().restore(
                path, args=ocp.args.PyTreeRestore(item=abstract,
                                                  restore_args=restore_args,
                                                  transforms={}))

    def exists(self, name: str = "ckpt") -> bool:
        self.wait_until_finished()
        return self._latest_path(name) is not None

    def names_by_recency(self, names: tuple[str, ...]) -> list[str]:
        """The subset of ``names`` with a committed version on disk,
        ordered newest-first by the latest version's mtime — the slot
        preference order elastic resume walks (train/elastic.py)."""
        self.wait_until_finished()
        stamped = []
        for name in names:
            path = self._latest_path(name)
            if path is not None:
                stamped.append((os.path.getmtime(path), name))
        return [name for _, name in sorted(stamped, reverse=True)]

    def newest_name(self, names: tuple[str, ...]) -> str | None:
        """The name whose latest committed version is most recent on disk
        (by mtime); None if none exist."""
        ordered = self.names_by_recency(names)
        return ordered[0] if ordered else None
