"""Checkpoint / resume.

Capability parity with the reference's best-accuracy checkpointing —
save ``{net, acc, epoch}`` to ``./checkpoint/ckpt.pth`` when val accuracy
improves, restore on ``--resume`` (``data_parallel.py:80-87,143-155``) —
upgraded to the TPU-native form: orbax sharded pytree checkpoints that
save/restore distributed ``jax.Array``s directly (multi-host safe), covering
params, BN state, optimizer state, step and best-acc in one tree.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any

import jax
import orbax.checkpoint as ocp


class Checkpointer:
    """Best-acc checkpoint + resume over an orbax StandardCheckpointer.

    Saves may be asynchronous (``wait=False``): orbax copies the arrays to
    host, then persists on a background thread while training continues —
    the step after a checkpoint no longer stalls behind filesystem writes.

    Crash safety: each save writes a fresh ``{name}-{v}`` directory (orbax
    commits it with an atomic rename); the previous version is pruned only at
    the *next* save, after confirming the newer one committed. So there is
    never a moment with zero committed checkpoints on disk, and a reader in
    another process sees whichever version last committed. ``restore`` /
    ``exists`` resolve to the newest committed version (falling back to a
    bare legacy ``{name}`` directory).
    """

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()

    def _path(self, name: str, version: int | None = None) -> str:
        leaf = name if version is None else f"{name}-{version}"
        return os.path.join(self.directory, leaf)

    def _versions(self, name: str) -> list[int]:
        """Committed version numbers for ``name``, ascending. Orbax tmp dirs
        carry a ``.orbax-checkpoint-tmp`` suffix and never match."""
        pat = re.compile(re.escape(name) + r"-(\d+)$")
        out = []
        for entry in os.listdir(self.directory):
            m = pat.match(entry)
            if m and os.path.isdir(os.path.join(self.directory, entry)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _latest_path(self, name: str) -> str | None:
        versions = self._versions(name)
        if versions:
            return self._path(name, versions[-1])
        legacy = self._path(name)
        return legacy if os.path.exists(legacy) else None

    def save(self, tree: Any, name: str = "ckpt", *, force: bool = True,
             wait: bool = True) -> str:
        del force  # kept for API compatibility; versioning never overwrites
        self._ckpt.wait_until_finished()  # the previous save has committed...
        versions = self._versions(name)
        for v in versions[:-1]:           # ...so all but the newest can go
            shutil.rmtree(self._path(name, v), ignore_errors=True)
        if versions and os.path.exists(self._path(name)):
            # A versioned save has committed, so a bare legacy `{name}` dir
            # (pre-versioning format) is stale — prune it too.
            shutil.rmtree(self._path(name), ignore_errors=True)
        next_v = versions[-1] + 1 if versions else 0
        path = self._path(name, next_v)
        self._ckpt.save(path, tree)
        if wait:
            self._ckpt.wait_until_finished()
        return path

    def wait_until_finished(self) -> None:
        """Block until any asynchronous save has fully committed."""
        self._ckpt.wait_until_finished()

    def restore(self, target: Any, name: str = "ckpt") -> Any:
        """Restore the newest committed version into the structure/shardings
        of ``target`` (an abstract or concrete pytree). Raises
        FileNotFoundError if absent."""
        self.wait_until_finished()
        path = self._latest_path(name)
        if path is None:
            raise FileNotFoundError(self._path(name))
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        return self._ckpt.restore(path, abstract)

    def restore_subtree(self, target: Any, name: str = "ckpt") -> Any:
        """Restore only the top-level keys present in ``target`` (a dict),
        e.g. just the params of a full train-state checkpoint for
        inference. Uses orbax partial restore: only the requested subtrees
        are read from storage — a params-only restore never materializes
        the (larger) optimizer state."""
        self.wait_until_finished()
        path = self._latest_path(name)
        if path is None:
            raise FileNotFoundError(self._path(name))
        meta = self._ckpt.metadata(path)
        # Newer orbax wraps the tree in .item_metadata.tree; this
        # container's orbax returns the key->metadata mapping directly.
        tree = getattr(getattr(meta, "item_metadata", None), "tree", None)
        if tree is None:
            tree = meta
        missing = [k for k in target if k not in tree]
        if missing:
            raise KeyError(f"checkpoint {path} has no keys {missing}; "
                           f"available: {sorted(tree)}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        # Explicit per-leaf restore args carrying the TARGET's shardings:
        # without them PyTreeRestore falls back to the sharding file
        # written at save time, which breaks the moment the restoring
        # process has a different topology (e.g. a checkpoint trained on
        # an 8-device mesh restored for single-device inference —
        # scripts/generate.py's whole use case).
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)
        try:
            return ocp.PyTreeCheckpointer().restore(
                path, args=ocp.args.PyTreeRestore(item=abstract,
                                                  restore_args=restore_args,
                                                  partial_restore=True))
        except TypeError:
            # Older orbax has no partial_restore kwarg; transforms={} is its
            # spelling of the same thing (checkpoint keys absent from
            # ``item`` are dropped instead of restored).
            return ocp.PyTreeCheckpointer().restore(
                path, args=ocp.args.PyTreeRestore(item=abstract,
                                                  restore_args=restore_args,
                                                  transforms={}))

    def exists(self, name: str = "ckpt") -> bool:
        self.wait_until_finished()
        return self._latest_path(name) is not None

    def newest_name(self, names: tuple[str, ...]) -> str | None:
        """The name whose latest committed version is most recent on disk
        (by mtime) — used to resume from the newer of the best-accuracy and
        preemption checkpoint slots. None if none exist."""
        self.wait_until_finished()
        best: tuple[float, str] | None = None
        for name in names:
            path = self._latest_path(name)
            if path is None:
                continue
            mtime = os.path.getmtime(path)
            if best is None or mtime > best[0]:
                best = (mtime, name)
        return best[1] if best else None
