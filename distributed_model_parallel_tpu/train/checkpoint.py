"""Checkpoint / resume.

Capability parity with the reference's best-accuracy checkpointing —
save ``{net, acc, epoch}`` to ``./checkpoint/ckpt.pth`` when val accuracy
improves, restore on ``--resume`` (``data_parallel.py:80-87,143-155``) —
upgraded to the TPU-native form: orbax sharded pytree checkpoints that
save/restore distributed ``jax.Array``s directly (multi-host safe), covering
params, BN state, optimizer state, step and best-acc in one tree.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
import zlib
from typing import Any, Callable

import jax
import orbax.checkpoint as ocp

from distributed_model_parallel_tpu.utils.faults import (
    FaultInjector,
    InjectedFaultError,
    tear_checkpoint,
)

# Per-checkpoint integrity manifest, written into each version directory
# once its save has committed: relative path -> {size, crc32} for every
# file. A torn/truncated/partially-copied version fails verification and
# ``restore(..., allow_fallback=True)`` skips it. Absence of a manifest is
# "unverifiable" (legacy / foreign checkpoint), not "bad".
MANIFEST_FILENAME = "dmp_manifest.json"


class CheckpointIntegrityError(RuntimeError):
    """No committed checkpoint version survived verification/restore."""


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def write_manifest(path: str) -> str:
    """Write the integrity manifest for a committed checkpoint directory
    (atomic: temp file + rename). Returns the manifest path."""
    entries: dict[str, dict] = {}
    for root, _dirs, files in os.walk(path):
        for fn in files:
            if fn == MANIFEST_FILENAME:
                continue
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, path)
            entries[rel] = {"size": os.path.getsize(p),
                            "crc32": _file_crc32(p)}
    out = os.path.join(path, MANIFEST_FILENAME)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"created": time.time(), "files": entries}, f)
    os.replace(tmp, out)
    return out


def verify_manifest(path: str) -> str | None:
    """Check a checkpoint directory against its manifest.

    Returns ``None`` when every recorded file matches (size + crc32),
    ``"missing"`` when there is no manifest to check (unverifiable, not
    necessarily bad), and a human-readable mismatch reason otherwise.
    """
    mpath = os.path.join(path, MANIFEST_FILENAME)
    if not os.path.exists(mpath):
        return "missing"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (json.JSONDecodeError, KeyError, OSError) as e:
        return f"unreadable manifest: {type(e).__name__}"
    for rel, want in files.items():
        p = os.path.join(path, rel)
        if not os.path.exists(p):
            return f"missing file {rel}"
        size = os.path.getsize(p)
        if size != want["size"]:
            return (f"size mismatch on {rel} "
                    f"({size} != {want['size']} bytes)")
        if _file_crc32(p) != want["crc32"]:
            return f"checksum mismatch on {rel}"
    return None


class Checkpointer:
    """Best-acc checkpoint + resume over an orbax StandardCheckpointer.

    Saves may be asynchronous (``wait=False``): orbax copies the arrays to
    host, then persists on a background thread while training continues —
    the step after a checkpoint no longer stalls behind filesystem writes.

    Crash safety: each save writes a fresh ``{name}-{v}`` directory (orbax
    commits it with an atomic rename); older versions are pruned only at the
    *next* save, after confirming the newer one committed, and the newest
    ``keep`` committed versions are retained per slot. So there is never a
    moment with zero committed checkpoints on disk, and a reader in another
    process sees whichever version last committed. ``restore`` / ``exists``
    resolve to the newest committed version (falling back to a bare legacy
    ``{name}`` directory).

    Integrity: once a save commits, an integrity manifest (file sizes +
    crc32 checksums) is written into the version directory.
    ``restore(..., allow_fallback=True)`` verifies each candidate version
    against its manifest (and survives a restore-time failure on
    manifest-less versions) and falls back to the previous committed
    version — the torn-newest-checkpoint recovery path
    (train/resilience.py).

    ``injector`` (utils/faults.py) is the chaos hook: ``save_fail`` /
    ``tear_save`` faults fire at their planned occurrence of the ``save``
    site. Disabled injectors cost one no-op poll per save.
    """

    def __init__(self, directory: str, *, keep: int = 2,
                 injector: FaultInjector | None = None):
        self.directory = os.path.abspath(directory)
        self.keep = max(1, int(keep))
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()
        self._injector = injector
        # Version paths whose manifest still needs writing once the
        # (possibly asynchronous) save commits.
        self._pending_manifest: list[str] = []

    def _path(self, name: str, version: int | None = None) -> str:
        leaf = name if version is None else f"{name}-{version}"
        return os.path.join(self.directory, leaf)

    def _versions(self, name: str) -> list[int]:
        """Committed version numbers for ``name``, ascending. Orbax tmp dirs
        carry a ``.orbax-checkpoint-tmp`` suffix and never match."""
        pat = re.compile(re.escape(name) + r"-(\d+)$")
        out = []
        for entry in os.listdir(self.directory):
            m = pat.match(entry)
            if m and os.path.isdir(os.path.join(self.directory, entry)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _latest_path(self, name: str) -> str | None:
        versions = self._versions(name)
        if versions:
            return self._path(name, versions[-1])
        legacy = self._path(name)
        return legacy if os.path.exists(legacy) else None

    def _candidate_paths(self, name: str) -> list[str]:
        """Restore candidates, newest committed version first, legacy bare
        directory last."""
        out = [self._path(name, v)
               for v in sorted(self._versions(name), reverse=True)]
        legacy = self._path(name)
        if os.path.exists(legacy):
            out.append(legacy)
        return out

    def save(self, tree: Any, name: str = "ckpt", *, force: bool = True,
             wait: bool = True) -> str:
        del force  # kept for API compatibility; versioning never overwrites
        self.wait_until_finished()  # the previous save has committed...
        versions = self._versions(name)
        for v in versions[:-self.keep]:   # ...keep the newest K, prune older
            shutil.rmtree(self._path(name, v), ignore_errors=True)
        if versions and os.path.exists(self._path(name)):
            # A versioned save has committed, so a bare legacy `{name}` dir
            # (pre-versioning format) is stale — prune it too.
            shutil.rmtree(self._path(name), ignore_errors=True)
        next_v = versions[-1] + 1 if versions else 0
        path = self._path(name, next_v)
        faults = (self._injector.poll("save")
                  if self._injector is not None else [])
        if any(s.kind == "save_fail" for s in faults):
            # Die "mid-write": a torn version directory appears committed
            # to the version scan but holds no restorable checkpoint.
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "_DMP_TORN"), "w") as f:
                f.write("injected save failure\n")
            raise InjectedFaultError(f"injected save failure for {path}")
        tear = any(s.kind == "tear_save" for s in faults)
        self._ckpt.save(path, tree)
        self._pending_manifest.append(path)
        if wait or tear:
            self.wait_until_finished()
        if tear:
            tear_checkpoint(path)
        return path

    def wait_until_finished(self) -> None:
        """Block until any asynchronous save has fully committed, then
        write the integrity manifests for the newly committed versions."""
        self._ckpt.wait_until_finished()
        while self._pending_manifest:
            path = self._pending_manifest.pop()
            if os.path.isdir(path):
                write_manifest(path)

    def restore(self, target: Any, name: str = "ckpt", *,
                allow_fallback: bool = False,
                on_fallback: Callable[[str, str], None] | None = None) -> Any:
        """Restore the newest committed version into the structure/shardings
        of ``target`` (an abstract or concrete pytree). Raises
        FileNotFoundError if absent.

        With ``allow_fallback=True`` each candidate version (newest first)
        is verified against its integrity manifest before the restore is
        attempted, and a torn/corrupt/unrestorable version is skipped in
        favor of the previous committed one; ``on_fallback(path, reason)``
        observes every rejection (the supervisor turns it into
        failure/recovery telemetry). CheckpointIntegrityError when no
        version survives.
        """
        self.wait_until_finished()
        candidates = self._candidate_paths(name)
        if not candidates:
            raise FileNotFoundError(self._path(name))
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        if not allow_fallback:
            return self._ckpt.restore(candidates[0], abstract)
        rejected: list[tuple[str, str]] = []
        for path in candidates:
            reason = verify_manifest(path)
            if reason is None:
                # Verified intact: a restore error here is a template /
                # structure problem (e.g. resuming under a different
                # config), not corruption — an older version of the same
                # run can't fix that, so fail fast with orbax's error.
                return self._ckpt.restore(path, abstract)
            if reason != "missing":
                rejected.append((path, reason))
                if on_fallback is not None:
                    on_fallback(path, reason)
                continue
            # Unverifiable (no manifest — legacy or foreign checkpoint):
            # attempt the restore and treat failure as a torn version.
            try:
                return self._ckpt.restore(path, abstract)
            except Exception as e:  # noqa: BLE001 - fall back on any failure
                detail = f"restore failed: {type(e).__name__}: {e}"
                rejected.append((path, detail))
                if on_fallback is not None:
                    on_fallback(path, detail)
        raise CheckpointIntegrityError(
            f"no restorable version of {name!r} in {self.directory}: "
            + "; ".join(f"{os.path.basename(p)} ({r[:160]})"
                        for p, r in rejected))

    def restore_subtree(self, target: Any, name: str = "ckpt") -> Any:
        """Restore only the top-level keys present in ``target`` (a dict),
        e.g. just the params of a full train-state checkpoint for
        inference. Uses orbax partial restore: only the requested subtrees
        are read from storage — a params-only restore never materializes
        the (larger) optimizer state."""
        self.wait_until_finished()
        path = self._latest_path(name)
        if path is None:
            raise FileNotFoundError(self._path(name))
        meta = self._ckpt.metadata(path)
        # Newer orbax wraps the tree in .item_metadata.tree; this
        # container's orbax returns the key->metadata mapping directly.
        tree = getattr(getattr(meta, "item_metadata", None), "tree", None)
        if tree is None:
            tree = meta
        missing = [k for k in target if k not in tree]
        if missing:
            raise KeyError(f"checkpoint {path} has no keys {missing}; "
                           f"available: {sorted(tree)}")
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        # Explicit per-leaf restore args carrying the TARGET's shardings:
        # without them PyTreeRestore falls back to the sharding file
        # written at save time, which breaks the moment the restoring
        # process has a different topology (e.g. a checkpoint trained on
        # an 8-device mesh restored for single-device inference —
        # scripts/generate.py's whole use case).
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)
        try:
            return ocp.PyTreeCheckpointer().restore(
                path, args=ocp.args.PyTreeRestore(item=abstract,
                                                  restore_args=restore_args,
                                                  partial_restore=True))
        except TypeError:
            # Older orbax has no partial_restore kwarg; transforms={} is its
            # spelling of the same thing (checkpoint keys absent from
            # ``item`` are dropped instead of restored).
            return ocp.PyTreeCheckpointer().restore(
                path, args=ocp.args.PyTreeRestore(item=abstract,
                                                  restore_args=restore_args,
                                                  transforms={}))

    def exists(self, name: str = "ckpt") -> bool:
        self.wait_until_finished()
        return self._latest_path(name) is not None

    def newest_name(self, names: tuple[str, ...]) -> str | None:
        """The name whose latest committed version is most recent on disk
        (by mtime) — used to resume from the newer of the best-accuracy and
        preemption checkpoint slots. None if none exist."""
        self.wait_until_finished()
        best: tuple[float, str] | None = None
        for name in names:
            path = self._latest_path(name)
            if path is None:
                continue
            mtime = os.path.getmtime(path)
            if best is None or mtime > best[0]:
                best = (mtime, name)
        return best[1] if best else None
