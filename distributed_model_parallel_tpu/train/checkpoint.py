"""Checkpoint / resume.

Capability parity with the reference's best-accuracy checkpointing —
save ``{net, acc, epoch}`` to ``./checkpoint/ckpt.pth`` when val accuracy
improves, restore on ``--resume`` (``data_parallel.py:80-87,143-155``) —
upgraded to the TPU-native form: orbax sharded pytree checkpoints that
save/restore distributed ``jax.Array``s directly (multi-host safe), covering
params, BN state, optimizer state, step and best-acc in one tree.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


class Checkpointer:
    """Best-acc checkpoint + resume over an orbax StandardCheckpointer."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def save(self, tree: Any, name: str = "ckpt", *, force: bool = True) -> str:
        path = self._path(name)
        self._ckpt.save(path, tree, force=force)
        self._ckpt.wait_until_finished()
        return path

    def restore(self, target: Any, name: str = "ckpt") -> Any:
        """Restore into the structure/shardings of ``target`` (an abstract or
        concrete pytree). Raises FileNotFoundError if absent."""
        path = self._path(name)
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
        return self._ckpt.restore(path, abstract)

    def exists(self, name: str = "ckpt") -> bool:
        return os.path.exists(self._path(name))
