"""Training guards: replica-divergence and non-finite detection.

The reference has no race/failure detection at all (SURVEY.md §5): DDP's
implicit guarantee that replicas stay in lockstep is trusted blindly, and a
dead rank simply hangs the NCCL ring. The single-controller SPMD model
removes whole classes of those failures (there is one program; collectives
cannot mismatch), so the remaining failure surface is numerical and
placement drift — which these guards check cheaply:

* ``assert_replicated`` — verifies a pytree whose arrays claim to be
  replicated really is bitwise-identical across devices (the invariant DDP
  maintains by construction and silently corrupts when broken; here it can
  only break through user error like donating a stale buffer, and a test
  can check it directly).
* ``check_finite`` — raises on NaN/Inf in a pytree (e.g. loss explosion),
  replacing silent divergence with a loud failure; cheap enough to run every
  N steps. The whole pytree is fetched with ONE ``jax.device_get`` (one host
  sync total, not one per leaf) and the scan raises at the first non-finite
  leaf.
* ``StallDetector`` — the original post-hoc step-budget flag, kept for
  standalone use. The trainers now run the *live*
  ``train/resilience.Watchdog`` instead: it logs "still blocked after Ns"
  lines while the sync is still wedged (the observable symptom of a dead
  collective, which in the reference just blocks forever on ``dist.recv``,
  ``distributed_layers.py:20``) and can escalate to checkpoint-and-exit.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np


class ReplicaDivergenceError(AssertionError):
    pass


def assert_replicated(tree: Any, *, atol: float = 0.0, name: str = "tree") -> None:
    """Check every array's shards are identical across its devices.

    ``atol=0`` (the default) compares BIT PATTERNS, matching the
    consistency sentinel's fingerprint semantics: ``-0.0`` vs ``+0.0``
    diverges (a sign-bit SDC), while replicas that all hold the same NaN
    bytes are identical (a non-finite incident, not a replication one —
    ``check_finite`` is the guard for that). ``atol > 0`` falls back to
    a value comparison via ``np.allclose``."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if not hasattr(leaf, "addressable_shards"):
            continue
        shards = leaf.addressable_shards
        if len(shards) < 2:
            continue
        if shards[0].data.shape != leaf.shape:
            continue  # actually sharded, not replicated
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            got = np.asarray(s.data)
            if atol == 0.0:
                same = ref.tobytes() == got.tobytes()
                detail = "bit patterns differ"
            else:
                same = np.allclose(ref, got, atol=atol, rtol=0.0)
                detail = (f"max abs diff {np.abs(ref - got).max()}"
                          if not same else "")
            if not same:
                raise ReplicaDivergenceError(
                    f"{name}{jax.tree_util.keystr(path)} diverges between "
                    f"device {shards[0].device} and {s.device} ({detail})")


class NonFiniteError(FloatingPointError):
    pass


def check_finite(tree: Any, *, name: str = "tree") -> None:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    if not flat:
        return
    # ONE device->host fetch for the whole tree: per-leaf device_get would
    # pay one blocking round trip per leaf (hundreds for a real model, each
    # a full tunnel RTT on remote-device transports).
    host = jax.device_get([leaf for _path, leaf in flat])
    for (path, _leaf), arr in zip(flat, host):
        arr = np.asarray(arr)
        if not np.isfinite(arr).all():
            # Short-circuit on the first bad leaf — no point scanning the
            # rest of an already-condemned tree.
            raise NonFiniteError(
                f"{name}{jax.tree_util.keystr(path)} contains "
                f"{np.isnan(arr).sum()} NaN / {np.isinf(arr).sum()} Inf values")


class StallDetector:
    """Flags steps exceeding ``budget_s``. Usage:

        stall = StallDetector(budget_s=60)
        with stall.step():
            train_step(...)
        if stall.stalled: ...
    """

    def __init__(self, budget_s: float):
        self.budget_s = budget_s
        self.stalled = False
        self.worst_s = 0.0

    class _Ctx:
        def __init__(self, outer):
            self.outer = outer

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            dt = time.perf_counter() - self.t0
            self.outer.worst_s = max(self.outer.worst_s, dt)
            if dt > self.outer.budget_s:
                self.outer.stalled = True
            return False

    def step(self) -> "_Ctx":
        return self._Ctx(self)


class GuardRunner:
    """Config-driven guard harness the trainers wire in (off by default).

    ``TrainConfig.check_finite_every=N`` turns on finiteness checking: every
    drained metrics window is checked (those values are already on host — the
    check is free), and every N steps the parameters are fetched and checked
    too (a device→host sync, hence the coarser, explicit cadence).
    ``TrainConfig.stall_budget_s=S`` arms a live
    :class:`~distributed_model_parallel_tpu.train.resilience.Watchdog`
    around every blocking drain: while the sync is still blocked it logs
    "still blocked after Ns" lines, and an overrun flips ``stall.stalled``
    and (when the recovery supervisor wires ``on_stall`` with
    ``recovery.stall_exit``) escalates to a graceful checkpoint-and-exit —
    it never raises mid-sync, because wall-clock slowness can be transport
    noise while NaN is always a bug. ``injector`` serves planned ``stall``
    faults inside the watched region (utils/faults.py).
    """

    def __init__(self, *, check_finite_every: int = 0,
                 stall_budget_s: float | None = None, logger=None,
                 watchdog_interval_s: float | None = None,
                 on_stall=None, injector=None,
                 device_ids: tuple = ()):
        self.every = check_finite_every
        # Slice attribution for the device-health sentinel feeds
        # (utils/health.py): every watched sync's wall time is an
        # observation for these devices.
        self.device_ids = tuple(device_ids)
        if stall_budget_s:
            from distributed_model_parallel_tpu.train.resilience import (
                Watchdog,
            )

            self.stall = Watchdog(stall_budget_s,
                                  interval_s=watchdog_interval_s,
                                  logger=logger, on_escalate=on_stall)
        else:
            self.stall = None
        self.logger = logger
        self.injector = (injector if injector is not None
                         and injector.enabled else None)
        self._seen = 0
        self._next_params_check = check_finite_every

    @property
    def enabled(self) -> bool:
        return self.every > 0 or self.stall is not None

    def watch(self, what: str = "sync"):
        """Context manager wrapping a blocking sync point. ``what`` labels
        the watchdog's "still blocked" lines (the consistency sentinel
        passes "consistency-fingerprint" so a divergence check wedged on a
        dead mesh is attributed to the check, not a training sync)."""
        import contextlib

        from distributed_model_parallel_tpu.utils import health

        if (self.stall is None and self.injector is None
                and health.installed() is None):
            return contextlib.nullcontext()
        return self._watched(what)

    def _watched(self, what: str):
        import contextlib
        import time

        from distributed_model_parallel_tpu.utils import health

        @contextlib.contextmanager
        def ctx():
            wd = (self.stall.watch(what) if self.stall is not None
                  else contextlib.nullcontext())
            t0 = time.perf_counter()
            try:
                with wd:
                    if self.injector is not None:
                        # Injected stalls sleep INSIDE the watched region,
                        # so the watchdog observes them like a real wedged
                        # sync. Polling is keyed by ``what``: the
                        # sentinel's "consistency-fingerprint" fetches
                        # advance their own occurrence counter, so arming
                        # the sentinel never shifts which training drain a
                        # planned ``stall@N`` fires at (stall specs target
                        # site "sync" only).
                        self.injector.maybe_stall(what)
                    yield
            finally:
                # Every watched sync's wall time feeds the device-health
                # sentinel (no-op unless a monitor is installed): the
                # sentinel's labeled fetches land in the per-replica
                # "fetch" signal, training drains in "sync".
                dt = time.perf_counter() - t0
                if what == "consistency-fingerprint":
                    health.observe_fetch(self.device_ids, dt)
                else:
                    health.observe_sync(self.device_ids, dt)
        return ctx()

    def after_sync(self, host_metrics: Any, n_steps: int,
                   params: Any = None) -> None:
        """Run after a drain: ``host_metrics`` are the already-fetched
        values (checked every time), ``params`` the live model params
        (checked when the step counter crosses the N-step cadence)."""
        if self.every <= 0:
            return
        check_finite(host_metrics, name="metrics")
        self._seen += n_steps
        if params is not None and self._seen >= self._next_params_check:
            self._next_params_check = self._seen + self.every
            check_finite(params, name="params")
