"""Metrics: top-k accuracy and running meters.

Parity with the reference's ``accuracy(output, target, topk)``
(``utils.py:215-229``) and its per-batch/data-load timing meters
(``utils.py:41-74``). The accuracy math runs on-device inside the jitted step
(no logits transfer to host); meters are host-side plain Python.
"""

from __future__ import annotations

import time

import jax.numpy as jnp


def topk_correct(logits: jnp.ndarray, labels: jnp.ndarray,
                 ks: tuple[int, ...] = (1, 5)) -> dict[str, jnp.ndarray]:
    """Number of correct predictions at each k (summed over the batch).

    Returns counts rather than percentages so values psum/accumulate cleanly
    across shards and batches.
    """
    k_max = max(ks)
    # top-k via sorted indices; k is static so this lowers to a single sort.
    top = jnp.argsort(-logits, axis=-1)[..., :k_max]
    hit = top == labels[..., None]
    return {f"correct@{k}": jnp.sum(hit[..., :k]) for k in ks}


class AverageMeter:
    """Running average (reference keeps ad-hoc ``x_avg = x_avg + x`` sums,
    ``utils.py:64-74`` — including the latent bug of accumulating live graph
    tensors, ``utils.py:68,102``; here values must be plain floats)."""

    def __init__(self, name: str = ""):
        self.name = name
        self.reset()

    def reset(self):
        self.sum = 0.0
        self.count = 0
        self.last = 0.0

    def update(self, value: float, n: int = 1):
        self.last = float(value)
        self.sum += float(value) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.sum / max(1, self.count)


class StepTimer:
    """Separates data-loading time from step (compute) time per batch,
    like the reference's ``time_load``/``time_batch`` meters
    (``utils.py:41,48,64-67``)."""

    def __init__(self):
        self.data = AverageMeter("data_time")
        self.step = AverageMeter("step_time")
        self._mark = time.perf_counter()

    def data_ready(self):
        now = time.perf_counter()
        self.data.update(now - self._mark)
        self._mark = now

    def step_done(self):
        now = time.perf_counter()
        self.step.update(now - self._mark)
        self._mark = now

    def mark(self):
        """Reset the reference point without attributing the elapsed time
        (for loops that account step time as a wall-clock residual)."""
        self._mark = time.perf_counter()

    def window_done(self, n_steps: int):
        """Attribute the time since the last mark to ``n_steps`` batches.

        For async-dispatch loops that only synchronize every N steps: the
        window's wall time (dispatch + the blocking drain) is compute time
        spread evenly over the window's batches. No-op for an empty window.
        """
        now = time.perf_counter()
        if n_steps > 0:
            self.step.update((now - self._mark) / n_steps, n_steps)
        self._mark = now
