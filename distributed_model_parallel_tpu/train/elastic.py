"""Elastic restart supervisor: topology-change-resilient resume.

Production TPU pods get maintenance-preempted constantly, and the slice a
job gets back is frequently *smaller* than the one it lost. Before this
module, a restart had three gaps: restore was epoch-granular (a mid-epoch
kill replayed the whole epoch), the replayed epoch saw a *different* data
order (the loader's rng was consumed statefully), and a checkpoint saved
on a dp=8 mesh could not restore onto a dp=4 slice at all. This module +
its collaborators close all three:

* **exact mid-epoch resume** — ``BatchLoader.state_dict`` (epoch + batch
  cursor over a stateless ``(seed, epoch)`` permutation, data/loader.py),
  a per-step rng derived from the global step, and the step-cadence
  **emergency checkpoint slot** (:class:`EmergencyCheckpointer`,
  ``TrainConfig.emergency_every``) that ``checkpoint_on_preempt`` also
  writes — so "kill -TERM mid-epoch, restart, converge identically" is a
  tested property (tests/test_elastic.py, ``dmp_chaos.py preempt``);
* **topology-change-resilient restore** — every save stamps the saving
  mesh + global step into the integrity manifest, and
  ``Checkpointer.restore_resharded`` restores any checkpoint into the
  *current* mesh's shardings (replicated, DDP, FSDP/ZeRO leaves), raising
  a typed ``TopologyMismatchError`` when global shapes genuinely conflict
  (train/checkpoint.py);
* **restart supervision** — :func:`fit_mesh_to_devices` rebuilds the mesh
  at the largest compatible dp degree for the live device count
  (``TrainConfig.elastic``), and :func:`elastic_restore` picks the
  newest-valid of the {emergency, preempt, epoch} slots — walking past
  torn versions via the integrity-manifest fallback, and past a fully
  torn slot to the next-newest one — then the trainers resume at the
  exact global step.

Every resume emits a typed telemetry ``resume`` record
(utils/telemetry.py) that ``scripts/dmp_report.py`` renders on the
resilience timeline.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

from distributed_model_parallel_tpu.config import MeshConfig
from distributed_model_parallel_tpu.utils import tracing
from distributed_model_parallel_tpu.train.checkpoint import (
    Checkpointer,
    CheckpointIntegrityError,
    TopologyMismatchError,
)

__all__ = [
    "ElasticDecision",
    "EmergencyCheckpointer",
    "TopologyMismatchError",
    "elastic_restore",
    "fit_mesh_to_devices",
    "live_device_count",
]


def live_device_count() -> int:
    """Devices visible to this restart — the single seam every elastic
    topology decision reads (``fit_mesh_to_devices`` callers AND the
    autotuner's ``strategy="auto"`` re-plan, autotune/planner.py), so
    tests and orchestrators can present a shrunk slice in one place."""
    import jax

    return len(jax.devices())


def build_resume_tree(epoch: int, cursor: int, epoch_len: int,
                      global_step: int, budgets: dict) -> dict:
    """The exact-continuation subtree every trainer checkpoint carries:
    normalized loader position (a fully-consumed epoch is the start of the
    next one), global step, and the supervisor's live budgets. One schema,
    one place — the trainers differ only in where the position lives."""
    import jax.numpy as jnp

    ep, cur = int(epoch), int(cursor)
    if cur >= epoch_len:
        ep, cur = ep + 1, 0
    return {"loader_epoch": jnp.asarray(ep, jnp.int32),
            "batch_cursor": jnp.asarray(cur, jnp.int32),
            "global_step": jnp.asarray(global_step, jnp.int32),
            "retries_left": jnp.asarray(budgets["retries_left"], jnp.int32),
            "lr_scale": jnp.asarray(budgets["lr_scale"], jnp.float32)}


def unpack_resume_tree(ri: dict) -> tuple[int, int, int, int, float]:
    """``(epoch, cursor, global_step, retries_left, lr_scale)`` from a
    restored resume subtree."""
    return (int(ri["loader_epoch"]), int(ri["batch_cursor"]),
            int(ri["global_step"]), int(ri["retries_left"]),
            float(ri["lr_scale"]))


@dataclasses.dataclass(frozen=True)
class ElasticDecision:
    """What :func:`fit_mesh_to_devices` decided and why — logged by the
    trainers so a degraded restart is visible, never silent."""

    n_devices: int
    requested: dict           # requested axis sizes (+ dcn_data)
    resolved: dict
    changed: bool

    def describe(self) -> str:
        if not self.changed:
            return (f"elastic: mesh {self.resolved} fits the "
                    f"{self.n_devices} live devices unchanged")
        return (f"elastic: rebuilt mesh for {self.n_devices} live devices: "
                f"{self.requested} -> {self.resolved}")


def fit_mesh_to_devices(mesh: MeshConfig, n_devices: int, *,
                        batch_size: int | None = None
                        ) -> tuple[MeshConfig, ElasticDecision]:
    """Shrink the data axis to the largest degree the live device count
    (and global batch divisibility) supports.

    Only data parallelism is elastic: dp replicas are interchangeable, so
    shedding some changes throughput, not math. The other axes partition
    the *model* — a pipeline that lost a stage's devices cannot run at
    all, so too few devices for ``stage*model*seq*expert`` raises instead
    of silently training a different model. The dcn factor is kept when it
    still divides the resolved degree and dropped to 1 otherwise (the
    degraded slice's host layout is unknown).
    """
    if n_devices < 1:
        raise ValueError(f"need at least one live device, got {n_devices}")
    other = mesh.stage * mesh.model * mesh.seq * mesh.expert
    if other > n_devices:
        raise ValueError(
            f"non-data mesh axes need {other} devices "
            f"(stage={mesh.stage}, model={mesh.model}, seq={mesh.seq}, "
            f"expert={mesh.expert}) but only {n_devices} are live — "
            f"model-partitioning axes are not elastic")
    dp = min(mesh.data, n_devices // other)
    while dp > 1 and batch_size is not None and batch_size % dp:
        dp -= 1
    dcn = mesh.dcn_data if mesh.dcn_data > 1 and dp % mesh.dcn_data == 0 \
        else 1
    resolved = dataclasses.replace(mesh, data=dp, dcn_data=dcn)
    requested_sizes = {**mesh.axis_sizes(), "dcn_data": mesh.dcn_data}
    resolved_sizes = {**resolved.axis_sizes(), "dcn_data": resolved.dcn_data}
    return resolved, ElasticDecision(
        n_devices=n_devices, requested=requested_sizes,
        resolved=resolved_sizes, changed=resolved_sizes != requested_sizes)


def elastic_restore(ckpt: Checkpointer, templates: Sequence[Any],
                    names: Sequence[str], *,
                    on_fallback: Callable[[str, str], None] | None = None
                    ) -> tuple[str, Any]:
    """Newest-valid-slot restore: walk ``names`` newest-first (by latest
    committed version mtime), trying each template layout against each
    slot via ``restore_resharded``.

    Fallback ladder, from cheapest to last-resort:

    1. a torn *version* of a slot → previous committed version (the PR 2
       integrity-manifest fallback inside ``restore_resharded``);
    2. a slot where EVERY template layout hit ``CheckpointIntegrityError``
       → the next-newest slot (an intact epoch checkpoint beats a torn
       emergency save). Every template must get its try first: on a
       manifest-less version (pre-manifest checkpoint, bare legacy dir,
       async save killed before its manifest) a template mismatch is
       indistinguishable from a tear, so giving up on the slot after
       template 1 would skip the very legacy layouts templates 2..N exist
       for;
    3. a structural mismatch against every template on the newest valid
       slot → raise (resuming under the wrong config must not silently
       fall back to a *stale* slot that happens to match);
    4. ``TopologyMismatchError`` propagates immediately — every version
       and slot of the same run shares the conflict.

    Manifest verification (full-file CRC sweeps) is memoized across
    template attempts, and ``on_fallback`` fires once per rejected
    version, not once per template.

    Returns ``(slot_name, restored_tree)``; ``FileNotFoundError`` when no
    slot exists at all.
    """
    ordered = ckpt.names_by_recency(tuple(names))
    if not ordered:
        raise FileNotFoundError(
            f"no checkpoint under any of {tuple(names)} in {ckpt.directory}")
    with tracing.span("elastic_restore", slots=",".join(ordered)):
        return _elastic_restore_ladder(ckpt, templates, ordered,
                                       on_fallback=on_fallback)


def _elastic_restore_ladder(ckpt: Checkpointer, templates: Sequence[Any],
                            ordered: Sequence[str], *,
                            on_fallback: Callable[[str, str], None] | None
                            ) -> tuple[str, Any]:
    verify_memo: dict = {}
    seen_fallbacks: set[str] = set()

    def _on_fallback(path: str, reason: str) -> None:
        if path in seen_fallbacks:
            return                  # same version, next template attempt
        seen_fallbacks.add(path)
        if on_fallback is not None:
            on_fallback(path, reason)

    slot_errors: list[tuple[str, BaseException]] = []
    for name in ordered:
        last: BaseException | None = None
        integrity: BaseException | None = None
        for tmpl in templates:
            try:
                return name, ckpt.restore_resharded(
                    tmpl, name, allow_fallback=True,
                    on_fallback=_on_fallback, verify_memo=verify_memo)
            except TopologyMismatchError:
                raise
            except CheckpointIntegrityError as e:
                integrity = e       # torn — or a template mismatch on an
                continue            # unverifiable version; try them all
            except (ValueError, KeyError, TypeError) as e:
                last = e            # layout mismatch — try the next template
        if last is not None:
            # The newest valid slot exists but matches no template: that is
            # a configuration error, not corruption — do NOT fall back to
            # an older slot (silently resuming stale state is worse).
            raise ValueError(
                f"checkpoint slot {name!r} does not match any of the "
                f"{len(templates)} resume template layouts — resuming "
                f"requires the same model and optimizer as the saving run"
            ) from last
        slot_errors.append((name, integrity))
    raise CheckpointIntegrityError(
        "no restorable slot among "
        + ", ".join(f"{n} ({type(e).__name__})" for n, e in slot_errors))


class EmergencyCheckpointer:
    """Step-cadence writer for the emergency checkpoint slot.

    Counts consumed steps across drains and, every ``every`` steps, saves
    the trainer's full resume tree (train state + loader position + global
    step + recovery budgets) under the dedicated slot. The slot keeps its
    own two newest committed versions (torn-newest fallback) and is never
    touched by other slots' keep-K rotation. Disabled (``every=0``) it
    costs one integer compare per call.
    """

    def __init__(self, ckpt: Checkpointer, slot: str, every: int, *,
                 logger=None, wait: bool = True, keep: int = 2):
        if every < 0:
            raise ValueError(f"emergency_every must be >= 0, got {every}")
        self.ckpt = ckpt
        self.slot = slot
        self.every = int(every)
        self.logger = logger
        self.wait = wait
        self.keep = keep
        self.saves = 0
        self._since = 0

    @property
    def enabled(self) -> bool:
        return self.every > 0

    def after_step(self, n_steps: int, tree_fn: Callable[[], Any]) -> bool:
        """Advance by ``n_steps`` consumed steps; save when the cadence
        elapses. Returns whether a save was written."""
        if not self.enabled or n_steps <= 0:
            return False
        self._since += int(n_steps)
        if self._since < self.every:
            return False
        # Carry the overshoot (multi-step dispatches land past the cadence
        # boundary) so the average interval stays `every`, modulo so a
        # single K > every dispatch doesn't queue up back-to-back saves of
        # the same state.
        self._since %= self.every
        self.ckpt.save(tree_fn(), self.slot, wait=self.wait, keep=self.keep)
        self.saves += 1
        if self.logger is not None:
            from distributed_model_parallel_tpu.utils.telemetry import (
                registry,
            )

            registry().counter("emergency_saves").inc()
            self.logger.telemetry.record("event",
                                         message=f"emergency checkpoint "
                                                 f"#{self.saves} -> "
                                                 f"{self.slot}")
        return True
