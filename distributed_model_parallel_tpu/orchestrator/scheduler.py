"""Device-pool admission control + priority preemption.

The policy half of the orchestrator: which queued tenant gets which
devices, and who gets preempted to make room. Everything here is pure
deterministic bookkeeping — no threads, no JAX — so a fixed submission
order replays the identical schedule (the property
tests/test_orchestrator.py pins).

Placement rules:

* a tenant's granted slice is EXACTLY the devices its resolved mesh
  needs (``fit_mesh_to_devices`` shrinks the data axis to what the free
  pool and batch divisibility allow; non-data axes, and the pipeline
  stage count, are not elastic);
* slices never overlap — the pool hands out each device to at most one
  tenant, and :meth:`DevicePool.assign` enforces it with a hard check;
* queued tenants are served in (priority desc, submission order) with
  head-of-line blocking: when the front tenant cannot be placed, nothing
  behind it is — a lower-priority late arrival must not steal the
  devices a draining preemption is about to free;
* preemption is chosen lowest-priority-first (newest admission first
  within a priority), only from strictly lower-priority victims, and
  only when the freed devices actually make the waiter schedulable —
  no pointless churn.
"""

from __future__ import annotations

from typing import Sequence

from distributed_model_parallel_tpu.orchestrator.tenants import (
    Tenant,
    TenantSpec,
    TenantState,
)

__all__ = ["DevicePool", "Scheduler"]


class DevicePool:
    """Ownership ledger for the fleet's devices.

    ``revoke``/``restore`` model topology shrink/grow (a maintenance
    event taking a sub-slice away and giving it back): revoked devices
    exist but are not schedulable. Devices are keyed by ``id`` so the
    ledger is printable and test-assertable.
    """

    def __init__(self, devices: Sequence):
        self.devices = tuple(devices)
        if not self.devices:
            raise ValueError("device pool needs at least one device")
        self._free = [d.id for d in self.devices]
        self._revoked: list[int] = []
        self._assigned: dict[str, tuple[int, ...]] = {}
        self._by_id = {d.id: d for d in self.devices}

    # -- views ---------------------------------------------------------------
    @property
    def free_ids(self) -> tuple[int, ...]:
        return tuple(sorted(self._free))

    @property
    def revoked_ids(self) -> tuple[int, ...]:
        return tuple(sorted(self._revoked))

    def assigned_ids(self, tenant: str) -> tuple[int, ...]:
        return self._assigned.get(tenant, ())

    def assignments(self) -> dict[str, tuple[int, ...]]:
        return dict(self._assigned)

    @property
    def n_free(self) -> int:
        return len(self._free)

    # -- transitions ---------------------------------------------------------
    def assign(self, tenant: str, n: int) -> tuple:
        """Grant ``n`` free devices (lowest ids first — deterministic) to
        ``tenant``. Raises when the pool cannot satisfy the request or
        the tenant already holds a slice (overlap would be a scheduling
        bug, not a recoverable condition)."""
        if tenant in self._assigned:
            raise RuntimeError(f"tenant {tenant!r} already holds devices "
                               f"{self._assigned[tenant]}")
        if n > len(self._free):
            raise RuntimeError(
                f"cannot grant {n} devices to {tenant!r}: only "
                f"{len(self._free)} free")
        grant = sorted(self._free)[:n]
        self._free = [i for i in self._free if i not in grant]
        self._assigned[tenant] = tuple(grant)
        return tuple(self._by_id[i] for i in grant)

    def release(self, tenant: str) -> tuple[int, ...]:
        """Return a tenant's slice to the pool (preemption drained or job
        finished). Devices revoked while held go to the revoked set, not
        the free list."""
        ids = self._assigned.pop(tenant, ())
        for i in ids:
            if i in self._revoked:
                continue            # revoked mid-hold: stays out of service
            self._free.append(i)
        return ids

    def revoke(self, n: int) -> tuple[int, ...]:
        """Take ``n`` devices out of service (topology shrink). Free
        devices go first (highest ids first, so low-id grants stay
        stable); if that is not enough, the remainder is marked revoked
        in place — the scheduler must preempt the holders and their
        release will not re-free the revoked ids."""
        out: list[int] = []
        free_take = sorted(self._free, reverse=True)[:n]
        self._free = [i for i in self._free if i not in free_take]
        out += free_take
        if len(out) < n:
            held = sorted((i for ids in self._assigned.values() for i in ids
                           if i not in self._revoked), reverse=True)
            out += held[:n - len(out)]
        if len(out) < n:
            raise ValueError(
                f"cannot revoke {n} devices: pool has "
                f"{len(self.devices) - len(self._revoked)} in service")
        self._revoked += out
        return tuple(sorted(out))

    def restore(self, n: int | None = None) -> tuple[int, ...]:
        """Return revoked devices to service (topology grow); ids still
        held by a tenant are un-revoked in place. ``None`` restores all."""
        n = len(self._revoked) if n is None else min(n, len(self._revoked))
        back = sorted(self._revoked)[:n]
        self._revoked = [i for i in self._revoked if i not in back]
        held = {i for ids in self._assigned.values() for i in ids}
        for i in back:
            if i not in held:
                self._free.append(i)
        return tuple(back)

    def holders_of_revoked(self) -> list[str]:
        """Tenants currently holding a revoked device — the ones a shrink
        must preempt."""
        rev = set(self._revoked)
        return sorted(t for t, ids in self._assigned.items()
                      if rev & set(ids))


class Scheduler:
    """Deterministic placement policy over a :class:`DevicePool`."""

    def __init__(self, pool: DevicePool):
        self.pool = pool

    # -- placement -----------------------------------------------------------
    def resolve_slice(self, spec: TenantSpec, n_free: int) -> int | None:
        """How many devices ``spec`` would take from an ``n_free`` pool:
        the resolved mesh size after shrinking the data axis to fit (and
        to divide the batch), or None when the tenant cannot run on
        ``n_free`` at all (non-data axes too wide, pipeline short of
        stages, or a corruption drill squeezed below two replicas)."""
        need = spec.min_devices()
        if n_free < need:
            return None
        if spec.workload == "pipeline":
            return spec.config.mesh.stage
        from distributed_model_parallel_tpu.train.elastic import (
            fit_mesh_to_devices,
        )

        try:
            mesh_cfg, _ = fit_mesh_to_devices(spec.config.mesh, n_free,
                                              batch_size=spec.batch_size)
        except ValueError:
            return None
        n = mesh_cfg.num_devices
        return n if n >= need else None

    def pick_victims(self, waiter: Tenant, running: Sequence[Tenant]
                     ) -> list[Tenant] | None:
        """Choose the strictly-lower-priority victims whose slices, added
        to the free pool (and to slices already draining), make
        ``waiter`` placeable. Lowest priority first; newest admission
        first within a priority. None when no such set exists."""
        draining = sum(len(t.devices) for t in running
                       if t.state is TenantState.PREEMPTING)
        avail = self.pool.n_free + draining
        if self.resolve_slice(waiter.spec, avail) is not None:
            return []               # already satisfiable once drains land
        candidates = sorted(
            (t for t in running if t.state is TenantState.RUNNING
             and t.priority < waiter.priority),
            key=lambda t: (t.priority, -t.admit_seq))
        chosen: list[Tenant] = []
        for v in candidates:
            chosen.append(v)
            avail += len(v.devices)
            if self.resolve_slice(waiter.spec, avail) is not None:
                return chosen
        return None
