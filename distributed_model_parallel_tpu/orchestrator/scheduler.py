"""Device-pool admission control + priority preemption.

The policy half of the orchestrator: which queued tenant gets which
devices, and who gets preempted to make room. Everything here is pure
deterministic bookkeeping — no threads, no JAX — so a fixed submission
order replays the identical schedule (the property
tests/test_orchestrator.py pins).

Placement rules:

* a tenant's granted slice is EXACTLY the devices its resolved mesh
  needs (``fit_mesh_to_devices`` shrinks the data axis to what the free
  pool and batch divisibility allow; non-data axes, and the pipeline
  stage count, are not elastic);
* slices never overlap — the pool hands out each device to at most one
  tenant, and :meth:`DevicePool.assign` enforces it with a hard check;
* queued tenants are served in (priority desc, submission order) with
  head-of-line blocking: when the front tenant cannot be placed, nothing
  behind it is — a lower-priority late arrival must not steal the
  devices a draining preemption is about to free;
* preemption is chosen lowest-priority-first (newest admission first
  within a priority), only from strictly lower-priority victims, and
  only when the freed devices actually make the waiter schedulable —
  no pointless churn.
"""

from __future__ import annotations

from typing import Sequence

from distributed_model_parallel_tpu.orchestrator.tenants import (
    Tenant,
    TenantSpec,
    TenantState,
)

__all__ = ["DevicePool", "Scheduler"]


class DevicePool:
    """Ownership ledger for the fleet's devices.

    ``revoke``/``restore`` model topology shrink/grow (a maintenance
    event taking a sub-slice away and giving it back): revoked devices
    exist but are not schedulable. ``quarantine``/``reinstate`` are the
    *health-driven* counterpart (utils/health.py): same not-schedulable
    effect, but auto-reversible — the health sentinel quarantines a
    degrading device proactively and reinstates it after probation,
    while a revoke lasts until the maintenance event ends. The two sets
    are disjoint (a device is out of service for one adjudicated reason
    at a time). Devices are keyed by ``id`` so the ledger is printable
    and test-assertable.
    """

    def __init__(self, devices: Sequence):
        self.devices = tuple(devices)
        if not self.devices:
            raise ValueError("device pool needs at least one device")
        self._free = [d.id for d in self.devices]
        self._revoked: list[int] = []
        self._quarantined: list[int] = []
        self._assigned: dict[str, tuple[int, ...]] = {}
        self._by_id = {d.id: d for d in self.devices}

    # -- views ---------------------------------------------------------------
    @property
    def free_ids(self) -> tuple[int, ...]:
        return tuple(sorted(self._free))

    @property
    def revoked_ids(self) -> tuple[int, ...]:
        return tuple(sorted(self._revoked))

    @property
    def quarantined_ids(self) -> tuple[int, ...]:
        return tuple(sorted(self._quarantined))

    def assigned_ids(self, tenant: str) -> tuple[int, ...]:
        return self._assigned.get(tenant, ())

    def assignments(self) -> dict[str, tuple[int, ...]]:
        return dict(self._assigned)

    @property
    def n_free(self) -> int:
        return len(self._free)

    # -- transitions ---------------------------------------------------------
    def assign(self, tenant: str, n: int) -> tuple:
        """Grant ``n`` free devices (lowest ids first — deterministic) to
        ``tenant``. Raises when the pool cannot satisfy the request or
        the tenant already holds a slice (overlap would be a scheduling
        bug, not a recoverable condition)."""
        if tenant in self._assigned:
            raise RuntimeError(f"tenant {tenant!r} already holds devices "
                               f"{self._assigned[tenant]}")
        if n > len(self._free):
            raise RuntimeError(
                f"cannot grant {n} devices to {tenant!r}: only "
                f"{len(self._free)} free")
        grant = sorted(self._free)[:n]
        if set(grant) & set(self._quarantined):
            # Quarantined ids never sit in the free list; reaching this
            # means the ledger itself is corrupt — typed so tests (and
            # operators) see the health subsystem, not a generic crash.
            from distributed_model_parallel_tpu.utils.health import (
                DeviceDegradedError,
            )

            raise DeviceDegradedError(
                f"grant for {tenant!r} includes quarantined devices "
                f"{sorted(set(grant) & set(self._quarantined))}")
        self._free = [i for i in self._free if i not in grant]
        self._assigned[tenant] = tuple(grant)
        return tuple(self._by_id[i] for i in grant)

    def assign_ids(self, tenant: str, ids: Sequence[int]) -> tuple:
        """Grant a SPECIFIC free id set to ``tenant`` — the serving
        fleet's grow-back path (serve/fleet.py): a reinstated replica
        re-claims its exact pre-quarantine slice so the replica->device
        mapping stays stable across quarantine cycles. Every id must be
        free (reinstated ids are; a raise means the caller's ledger and
        this one disagree)."""
        want = sorted(int(i) for i in ids)
        if tenant in self._assigned:
            raise RuntimeError(f"tenant {tenant!r} already holds devices "
                               f"{self._assigned[tenant]}")
        unknown = [i for i in want if i not in self._by_id]
        if unknown:
            raise KeyError(f"unknown device ids {unknown}")
        missing = [i for i in want if i not in self._free]
        if missing:
            raise RuntimeError(
                f"cannot grant {missing} to {tenant!r}: not free "
                f"(revoked {self.revoked_ids}, quarantined "
                f"{self.quarantined_ids})")
        self._free = [i for i in self._free if i not in want]
        self._assigned[tenant] = tuple(want)
        return tuple(self._by_id[i] for i in want)

    def release(self, tenant: str) -> tuple[int, ...]:
        """Return a tenant's slice to the pool (preemption drained or job
        finished). Devices revoked or quarantined while held go to their
        out-of-service set, not the free list."""
        ids = self._assigned.pop(tenant, ())
        for i in ids:
            if i in self._revoked or i in self._quarantined:
                continue            # taken out mid-hold: stays out of service
            self._free.append(i)
        return ids

    def revoke(self, n: int) -> tuple[int, ...]:
        """Take ``n`` devices out of service (topology shrink). Free
        devices go first (highest ids first, so low-id grants stay
        stable); if that is not enough, the remainder is marked revoked
        in place — the scheduler must preempt the holders and their
        release will not re-free the revoked ids. Quarantined devices are
        already out of service and are never double-claimed by a revoke."""
        out: list[int] = []
        free_take = sorted(self._free, reverse=True)[:n]
        self._free = [i for i in self._free if i not in free_take]
        out += free_take
        if len(out) < n:
            held = sorted((i for ids in self._assigned.values() for i in ids
                           if i not in self._revoked
                           and i not in self._quarantined), reverse=True)
            out += held[:n - len(out)]
        if len(out) < n:
            raise ValueError(
                f"cannot revoke {n} devices: pool has "
                f"{len(self.devices) - len(self._revoked) - len(self._quarantined)}"
                f" in service")
        self._revoked += out
        return tuple(sorted(out))

    def restore(self, n: int | None = None) -> tuple[int, ...]:
        """Return revoked devices to service (topology grow); ids still
        held by a tenant are un-revoked in place. ``None`` restores all."""
        n = len(self._revoked) if n is None else min(n, len(self._revoked))
        back = sorted(self._revoked)[:n]
        self._revoked = [i for i in self._revoked if i not in back]
        held = {i for ids in self._assigned.values() for i in ids}
        for i in back:
            if i not in held:
                self._free.append(i)
        return tuple(back)

    def holders_of_revoked(self) -> list[str]:
        """Tenants currently holding a revoked device — the ones a shrink
        must preempt."""
        rev = set(self._revoked)
        return sorted(t for t, ids in self._assigned.items()
                      if rev & set(ids))

    # -- health-driven transitions (utils/health.py) -------------------------
    def quarantine(self, ids: Sequence[int]) -> tuple[int, ...]:
        """Take degrading devices out of service on the health sentinel's
        verdict. Free ids leave the free list; held ids are marked in
        place (the orchestrator preempts the holders — their release
        will not re-free them). Already-quarantined ids are idempotent
        no-ops; revoking and quarantining the same device is a policy
        conflict and raises."""
        out: list[int] = []
        for i in ids:
            i = int(i)
            if i not in self._by_id:
                raise KeyError(f"unknown device id {i}")
            if i in self._quarantined:
                continue
            if i in self._revoked:
                raise ValueError(
                    f"device {i} is revoked (maintenance) — it cannot "
                    f"also be health-quarantined; restore it first")
            self._quarantined.append(i)
            if i in self._free:
                self._free.remove(i)
            out.append(i)
        return tuple(sorted(out))

    def reinstate(self, ids: Sequence[int] | None = None) -> tuple[int, ...]:
        """Return quarantined devices to service after probation
        (utils/health.py hysteresis); ids still held by a draining
        tenant are un-quarantined in place. ``None`` reinstates all."""
        take = (sorted(self._quarantined) if ids is None
                else [int(i) for i in ids if int(i) in self._quarantined])
        self._quarantined = [i for i in self._quarantined if i not in take]
        held = {i for a in self._assigned.values() for i in a}
        for i in take:
            if i not in held:
                self._free.append(i)
        return tuple(sorted(take))

    def holders_of_quarantined(self) -> list[str]:
        """Tenants currently holding a quarantined device — the ones the
        health loop must migrate off it."""
        bad = set(self._quarantined)
        return sorted(t for t, ids in self._assigned.items()
                      if bad & set(ids))


class Scheduler:
    """Deterministic placement policy over a :class:`DevicePool`."""

    def __init__(self, pool: DevicePool):
        self.pool = pool

    # -- placement -----------------------------------------------------------
    def resolve_slice(self, spec: TenantSpec, n_free: int) -> int | None:
        """How many devices ``spec`` would take from an ``n_free`` pool:
        the resolved mesh size after shrinking the data axis to fit (and
        to divide the batch), or None when the tenant cannot run on
        ``n_free`` at all (non-data axes too wide, pipeline short of
        stages, or a corruption drill squeezed below two replicas)."""
        need = spec.min_devices()
        if n_free < need:
            return None
        if spec.workload == "pipeline":
            return spec.config.mesh.stage
        from distributed_model_parallel_tpu.train.elastic import (
            fit_mesh_to_devices,
        )

        try:
            mesh_cfg, _ = fit_mesh_to_devices(spec.config.mesh, n_free,
                                              batch_size=spec.batch_size)
        except ValueError:
            return None
        n = mesh_cfg.num_devices
        return n if n >= need else None

    def pick_victims(self, waiter: Tenant, running: Sequence[Tenant]
                     ) -> list[Tenant] | None:
        """Choose the strictly-lower-priority victims whose slices, added
        to the free pool (and to slices already draining), make
        ``waiter`` placeable. Lowest priority first; newest admission
        first within a priority. None when no such set exists. Held
        devices that are revoked or quarantined will NOT return to the
        free pool when their holder drains — counting them would make a
        waiter look satisfiable by devices that are out of service."""
        out_of_service = (set(self.pool.revoked_ids)
                          | set(self.pool.quarantined_ids))

        def reclaimable(t: Tenant) -> int:
            return sum(1 for d in t.devices if d.id not in out_of_service)

        draining = sum(reclaimable(t) for t in running
                       if t.state is TenantState.PREEMPTING)
        avail = self.pool.n_free + draining
        if self.resolve_slice(waiter.spec, avail) is not None:
            return []               # already satisfiable once drains land
        candidates = sorted(
            (t for t in running if t.state is TenantState.RUNNING
             and t.priority < waiter.priority),
            key=lambda t: (t.priority, -t.admit_seq))
        chosen: list[Tenant] = []
        for v in candidates:
            chosen.append(v)
            avail += reclaimable(v)
            if self.resolve_slice(waiter.spec, avail) is not None:
                return chosen
        return None
