"""Multi-tenant run orchestration over a shared device pool.

Composes the robustness stack (fault injection, recovery supervisor,
consistency sentinel, elastic resume — PRs 2-4) into the scenario it was
built for: many concurrent heterogeneous jobs (CNN, LM/MoE, pipeline) on
one device fleet, with per-job priorities, admission control, and
priority preemption. Preempting a job goes through the real
preempt/emergency checkpoint machinery (train/preemption.py,
train/elastic.py); rescheduling it onto whatever slice is free goes
through ``fit_mesh_to_devices`` + ``restore_resharded`` — elastic resume
as the scheduling substrate, not a manual recovery path.

``scripts/dmp_soak.py`` drives a seeded chaos-soak campaign on top of
this package; ``scripts/dmp_report.py --fleet`` renders the merged
tenant telemetry.
"""

from distributed_model_parallel_tpu.orchestrator.scheduler import (
    DevicePool,
    Scheduler,
)
from distributed_model_parallel_tpu.orchestrator.tenants import (
    Tenant,
    TenantSpec,
    TenantState,
)
from distributed_model_parallel_tpu.orchestrator.orchestrator import (
    Orchestrator,
    UnschedulableError,
)

__all__ = [
    "DevicePool",
    "Orchestrator",
    "Scheduler",
    "Tenant",
    "TenantSpec",
    "TenantState",
    "UnschedulableError",
]
