"""Tenant lifecycle: one training job co-resident on the shared fleet.

A :class:`Tenant` wraps one trainer (CNN ``train/trainer.Trainer``, LM/MoE
``train/lm_trainer.LMTrainer``, or ``train/pipeline_trainer
.PipelineTrainer``) and runs its unmodified ``fit()`` on a dedicated
thread, gated step-by-step through the trainers' ``step_hook``: the hook
parks the thread at every train-step boundary until the orchestrator
grants the next step (a baton, not a time slice), so the fleet advances
under the orchestrator's deterministic control — one tenant computes at a
time, every scheduling decision observes settled state, and a fixed seed
replays the identical campaign.

Preemption is the REAL preemption path: the orchestrator sets the
trainer's :class:`~distributed_model_parallel_tpu.train.preemption
.PreemptionGuard` flag and grants one more step; the trainer breaks at
the boundary, writes its preempt checkpoint (exact position, budgets,
topology stamp), and ``fit()`` returns. Re-admission constructs a fresh
trainer with ``resume=True`` on whatever slice the scheduler granted —
``fit_mesh_to_devices`` refits the data axis and ``restore_resharded``
lands the checkpoint in the new mesh's shardings, so a tenant preempted
off a dp=4 slice continues at the exact global step on dp=2.

Trainer construction and the whole fit run execute inside
``telemetry.tenant_scope(name)``, so every record the trainer's stream
writes carries the tenant tag the fleet report groups by.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Any

from distributed_model_parallel_tpu.utils.telemetry import tenant_scope

__all__ = ["Tenant", "TenantSpec", "TenantState"]

WORKLOADS = ("cnn", "lm", "pipeline")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One job submission: which trainer drives it, its full config, and
    its scheduling priority (higher preempts lower).

    ``workload`` selects the trainer class: ``"cnn"`` =
    ``train/trainer.Trainer`` (TrainConfig; gspmd/ddp/fsdp strategies,
    any zoo model), ``"lm"`` = ``train/lm_trainer.LMTrainer``
    (LMTrainConfig; a MoE tenant is an LM config with
    ``model.moe_experts > 0``), ``"pipeline"`` =
    ``train/pipeline_trainer.PipelineTrainer`` (TrainConfig with
    ``mesh.stage`` stages; the stage axis is not elastic, so this tenant
    needs exactly that many devices).

    The config's ``mesh`` is a CEILING, not a demand: on every admission
    the data axis is refit to the granted slice
    (``fit_mesh_to_devices``), so ``mesh.data`` is the largest dp the
    tenant will use. ``checkpoint_dir`` / ``log_dir`` must be
    tenant-unique (the orchestrator rejects collisions at submit).
    """

    name: str
    workload: str
    config: Any                 # TrainConfig (cnn/pipeline) | LMTrainConfig
    priority: int = 0

    def __post_init__(self):
        if self.workload not in WORKLOADS:
            raise ValueError(f"unknown workload {self.workload!r}; known: "
                             f"{WORKLOADS}")
        if self.workload == "pipeline" and self.config.mesh.stage < 2:
            raise ValueError(
                f"pipeline tenant {self.name!r} needs mesh.stage >= 2, "
                f"got {self.config.mesh.stage}")

    @property
    def epochs(self) -> int:
        return int(self.config.epochs)

    @property
    def batch_size(self) -> int:
        cfg = self.config
        return int(cfg.batch_size if hasattr(cfg, "batch_size")
                   else cfg.data.batch_size)

    def requested_devices(self) -> int:
        """The slice this tenant ASKED for: the full config mesh (the
        admission ceiling). A tenant granted less — re-admitted onto a
        shrunken slice after a preemption or quarantine — is below
        request, and the orchestrator's grow-back pass expands it once
        devices free up (orchestrator.py _maybe_grow_back)."""
        if self.workload == "pipeline":
            return self.config.mesh.stage
        return self.config.mesh.num_devices

    def min_devices(self) -> int:
        """Smallest slice this tenant can run on at all: the non-data
        mesh axes (not elastic), times two replicas when the fault plan
        injects silent corruption (the corruption drills need redundancy
        — the trainers reject a dp=1 corruption plan loudly, so the
        scheduler must not grant one)."""
        mesh = self.config.mesh
        if self.workload == "pipeline":
            return mesh.stage
        other = mesh.stage * mesh.model * mesh.seq * mesh.expert
        from distributed_model_parallel_tpu.utils.faults import (
            CORRUPTION_KINDS,
            parse_faults,
        )

        min_dp = 1
        for f in self.config.recovery.faults or ():
            kind = f.kind if hasattr(f, "kind") else parse_faults(f)[0].kind
            if kind in CORRUPTION_KINDS:
                min_dp = 2
        return other * min_dp


class TenantState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    PREEMPTING = "preempting"     # preemption requested, draining to save
    COMPLETED = "completed"
    FAILED = "failed"             # unrecovered error — the soak ledger
    CANCELLED = "cancelled"


class _Baton:
    """Step-boundary handoff between the orchestrator thread and one
    tenant thread. The tenant parks in :meth:`hook` at every boundary;
    the orchestrator's grant wakes it for exactly one step."""

    def __init__(self):
        self.at_boundary = threading.Event()
        self.go = threading.Event()

    def hook(self, _trainer) -> None:          # runs on the tenant thread
        self.at_boundary.set()
        self.go.wait()
        self.go.clear()

    def release(self) -> None:
        """Unpark the tenant unconditionally (shutdown/abandon path)."""
        self.go.set()


class Tenant:
    """Runtime state of one submitted job across admissions."""

    def __init__(self, spec: TenantSpec, seq: int):
        self.spec = spec
        self.seq = seq                  # submission order (FIFO tie-break)
        self.state = TenantState.QUEUED
        self.devices: tuple = ()        # granted slice while RUNNING
        self.admit_seq = -1             # order of the LAST admission
        self.attempts = 0               # trainer constructions (1 + resumes)
        self.preemptions = 0
        self.grow_backs = 0             # below-request expansions GRANTED
        # Slice size before a pending grow-back preemption; the next
        # admission compares its grant against it and clears it
        # (orchestrator.py _maybe_grow_back / _admit).
        self._grow_back_from: int | None = None
        # Per-tenant registry counter totals (utils/telemetry.py
        # attributes counter increments to the thread's tenant_scope), so
        # lifecycle summaries carry THIS tenant's compiles/comm volume,
        # not fleet totals. Captured at the end of every attempt.
        self.counter_deltas: dict = {}
        self.preempted_at_step: int | None = None   # step when last preempted
        self.resume_exact: list[bool] = []          # per-resume step parity
        # Resumes that legitimately could NOT land at the exact step: the
        # newest checkpoint was torn (e.g. an injected tear_save hitting
        # the preemption save) and the restore provably fell back to an
        # older committed state — exempt from the exactness gate, counted
        # here so the campaign summary still surfaces them.
        self.resume_fallbacks = 0
        self.trainer = None
        self.error: BaseException | None = None
        self.outcome: str | None = None     # completed | preempted | failed
        self.jsonl_path: str | None = None
        # Faults fired across ALL attempts (the trainer — and its
        # injector — is rebuilt on every admission, so per-attempt fired
        # lists must be accumulated here for the campaign ledger).
        self.fired_faults: list = []
        self._cancel_on_reap = False
        self._thread: threading.Thread | None = None
        self._baton = _Baton()

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def priority(self) -> int:
        return self.spec.priority

    @property
    def global_step(self) -> int:
        t = self.trainer
        return int(getattr(t, "_global_step", 0)) if t is not None else 0

    # -- trainer construction (on the tenant thread) ------------------------
    def _attempt_config(self, n_devices: int):
        """The config for THIS admission: resume on after the first
        attempt, data axis refit to the granted slice, and the fault plan
        stripped on resumes — FaultInjector occurrence counters are
        per-construction, so replaying the plan would re-inject every
        fault on every resume (an accidental infinite preempt loop);
        chaos on resumed attempts comes from the campaign schedule, not
        from replay."""
        spec = self.spec
        cfg = spec.config
        resume = self.attempts > 1
        kw: dict[str, Any] = {"resume": resume}
        if resume and (cfg.recovery.faults or ()):
            kw["recovery"] = dataclasses.replace(cfg.recovery, faults=())
        if spec.workload != "pipeline":
            from distributed_model_parallel_tpu.train.elastic import (
                fit_mesh_to_devices,
            )

            mesh_cfg, _ = fit_mesh_to_devices(cfg.mesh, n_devices,
                                              batch_size=spec.batch_size)
            if mesh_cfg.num_devices != n_devices:
                raise ValueError(
                    f"tenant {spec.name!r}: granted {n_devices} devices "
                    f"but the mesh resolves to {mesh_cfg.num_devices} — "
                    f"the scheduler must grant exactly the resolved slice")
            kw["mesh"] = mesh_cfg
        return dataclasses.replace(cfg, **kw)

    def _build_trainer(self, devices):
        spec = self.spec
        cfg = self._attempt_config(len(devices))
        if spec.workload == "pipeline":
            from distributed_model_parallel_tpu.train.pipeline_trainer import (
                PipelineTrainer,
            )

            return PipelineTrainer(cfg, devices=list(devices))
        from distributed_model_parallel_tpu.mesh import make_mesh

        mesh_spec = make_mesh(cfg.mesh, list(devices))
        if spec.workload == "lm":
            from distributed_model_parallel_tpu.train.lm_trainer import (
                LMTrainer,
            )

            return LMTrainer(cfg, mesh_spec)
        from distributed_model_parallel_tpu.train.trainer import Trainer

        return Trainer(cfg, mesh_spec)

    def _completed(self, trainer, history) -> bool:
        total = self.spec.epochs
        if any(h.get("epoch") == total - 1 for h in history or ()):
            return True
        # Zero-work resume (preempted exactly at the final epoch
        # boundary): the restored position already sits past the last
        # epoch, so fit() ran nothing and recorded nothing.
        return int(getattr(trainer, "start_epoch", 0)) >= total

    def _run(self, devices) -> None:
        # Drop the previous attempt's trainer BEFORE building the new one:
        # a failed re-admission must not let the finally block read the
        # stale trainer and re-append fired faults it already accumulated.
        self.trainer = None
        try:
            with tenant_scope(self.name):
                trainer = self._build_trainer(devices)
                self.trainer = trainer
                self.jsonl_path = trainer.logger.jsonl_path
                if self.attempts > 1 and self.preempted_at_step is not None:
                    # The acceptance gate for the whole orchestration
                    # story: a resumed tenant continues at the EXACT
                    # global step it was preempted at. The one legitimate
                    # exception: the supervisor recorded a torn-checkpoint
                    # fallback during THIS restore — the exact position
                    # was destroyed with the torn version, and resuming
                    # older-but-intact state is the correct behavior.
                    exact = trainer._global_step == self.preempted_at_step
                    if not exact and trainer.resilience._fallback_reported:
                        self.resume_fallbacks += 1
                    else:
                        self.resume_exact.append(exact)
                trainer.step_hook = self._baton.hook
                history = trainer.fit()
                self.outcome = ("completed"
                                if self._completed(trainer, history)
                                else "preempted")
        except BaseException as e:  # noqa: BLE001 - ledger, not crash
            self.error = e
            self.outcome = "failed"
            # Postmortem at the moment of the unrecovered failure,
            # captured ON the failing thread while its traceback (and
            # every peer thread's live stack) is still available —
            # the reap round would only see a dead thread. No-op
            # without an installed flight recorder (utils/flightrec.py).
            from distributed_model_parallel_tpu.utils import flightrec

            t = self.trainer
            flightrec.dump(
                f"tenant-failed-{self.name}",
                telemetry_run=(t.logger.telemetry if t is not None
                               else None),
                error=e)
        finally:
            faults = getattr(self.trainer, "faults", None)
            if faults is not None:
                self.fired_faults.extend(faults.fired)
            from distributed_model_parallel_tpu.utils.telemetry import (
                registry,
            )

            self.counter_deltas = {
                k: v for k, v in registry().snapshot(
                    tenant=self.name).get("counters", {}).items() if v}
            # Drop this attempt's /statusz provider: a reaped tenant must
            # not keep pinning its trainer (params, opt state) or feed
            # stale watchdog state into /healthz for the rest of the
            # campaign. A re-admission registers afresh.
            from distributed_model_parallel_tpu.utils import statusz

            statusz.unregister(self.name)
            # The thread's death IS the completion signal; make sure the
            # boundary flag can't wedge an orchestrator mid-wait.
            self._baton.at_boundary.set()

    # -- orchestrator-side controls -----------------------------------------
    def start(self, devices, admit_seq: int) -> None:
        assert self._thread is None or not self._thread.is_alive()
        self.devices = tuple(devices)
        self.admit_seq = admit_seq
        self.attempts += 1
        self.state = TenantState.RUNNING
        self.outcome = None
        self._baton = _Baton()
        self._thread = threading.Thread(
            target=self._run, args=(self.devices,), daemon=True,
            name=f"tenant-{self.name}")
        self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def wait_boundary(self, poll_s: float = 0.01) -> bool:
        """Block until the tenant parks at a step boundary (or its
        thread finishes — the death path sets the flag too, so callers
        can never wedge here; they distinguish by checking ``alive``)."""
        while not self._baton.at_boundary.wait(poll_s):
            if not self.alive:
                return False
        return True

    def grant_steps(self, n: int) -> bool:
        """Advance the tenant by up to ``n`` steps, synchronously: each
        grant waits for the tenant to re-park (or finish) before the
        next, so exactly one tenant computes at a time and control
        returns with the tenant settled. Returns False once the thread
        has finished."""
        for _ in range(n):
            if not self.wait_boundary() or not self.alive:
                return False
            self._baton.at_boundary.clear()
            self._baton.go.set()
        self.wait_boundary()
        return self.alive

    def request_preemption(self) -> None:
        """Flip the trainer's cooperative stop flag — the same flag a TPU
        maintenance SIGTERM sets. The tenant honors it at the next
        granted boundary and exits through its preempt checkpoint."""
        if self.trainer is not None:
            self.trainer.preemption.request()
        self.state = TenantState.PREEMPTING

    def drain(self, max_steps: int = 10_000) -> None:
        """Grant steps until the thread finishes (used after a
        preemption request: the trainer needs one boundary to observe
        the flag, then runs its checkpoint-and-exit path)."""
        for _ in range(max_steps):
            if not self.alive:
                break
            if not self.wait_boundary():
                break
            self._baton.at_boundary.clear()
            self._baton.go.set()
        if self._thread is not None:
            self._thread.join(timeout=300)

    def join(self) -> None:
        if self._thread is not None:
            self._thread.join(timeout=300)
            if self._thread.is_alive():
                raise RuntimeError(
                    f"tenant {self.name!r} thread failed to exit")
