"""The multi-tenant control loop: admission, stepping, preemption, reap.

One :class:`Orchestrator` owns a :class:`~.scheduler.DevicePool` over the
visible devices and drives every submitted :class:`~.tenants.Tenant`
through its lifecycle:

    submit -> QUEUED -> (admission: exact slice granted, trainer built,
    possibly resumed/resharded) -> RUNNING -> step grants in deterministic
    round-robin -> {COMPLETED | PREEMPTING -> re-queued | FAILED}

Scheduling decisions happen only between settled states: tenants advance
one at a time (``Tenant.grant_steps`` is synchronous), so a fixed
submission order + seeds replays the identical campaign — the property
the chaos-soak's determinism rests on.

The orchestrator writes its own fleet-level telemetry stream
(``fleet.jsonl``: typed ``tenant`` records for every lifecycle event,
``event`` records for topology changes) next to the per-tenant streams
the trainers write; ``utils/telemetry.merge_streams`` +
``scripts/dmp_report.py --fleet`` join them into one report.
"""

from __future__ import annotations

import os
from typing import Callable, Sequence

from distributed_model_parallel_tpu.orchestrator.scheduler import (
    DevicePool,
    Scheduler,
)
from distributed_model_parallel_tpu.orchestrator.tenants import (
    Tenant,
    TenantSpec,
    TenantState,
)
from distributed_model_parallel_tpu.utils import tracing
from distributed_model_parallel_tpu.utils.telemetry import TelemetryRun
from distributed_model_parallel_tpu.utils.tracing import span

__all__ = ["Orchestrator", "UnschedulableError"]


class UnschedulableError(RuntimeError):
    """The queue cannot make progress: tenants are waiting, nothing is
    running or draining, and no admission is possible (e.g. a pipeline
    tenant needs more devices than the shrunken pool has)."""


class Orchestrator:
    """Runs many heterogeneous training jobs on a shared device fleet.

    ``quantum`` is the number of train steps granted per RUNNING tenant
    per round — the fairness knob, not a correctness one (every trainer
    checkpoint carries its exact position regardless of where the
    quantum falls).
    """

    def __init__(self, devices: Sequence | None = None, *,
                 workdir: str = "./orchestrator",
                 quantum: int = 2,
                 max_stagnant_rounds: int = 50,
                 health=None,
                 grow_back: bool = True,
                 statusz_port: int | None = None,
                 alerts=None,
                 flight_recorder=None):
        if devices is None:
            import jax

            devices = jax.devices()
        self.pool = DevicePool(devices)
        self.scheduler = Scheduler(self.pool)
        self.quantum = max(1, int(quantum))
        self.max_stagnant_rounds = max_stagnant_rounds
        # Device-health sentinel (utils/health.DeviceHealthMonitor): when
        # given, it is installed process-wide so the tenants' trainers
        # feed it timing signals, and every round consumes its
        # transitions — quarantine + proactive migration, probation
        # reinstate + grow-back. None = reactive-only orchestration.
        self.health = health
        self.grow_back = bool(grow_back)
        if health is not None:
            from distributed_model_parallel_tpu.utils import health as hm

            hm.install(health)
        # Crash flight recorder (utils/flightrec.FlightRecorder): when
        # given, installed process-wide so every tenant's telemetry tees
        # into its ring and a failing tenant/stall dumps a postmortem
        # bundle. None = no ring, no bundles (the no-op default).
        self.flight_recorder = flight_recorder
        if flight_recorder is not None:
            from distributed_model_parallel_tpu.utils import flightrec

            flightrec.install(flight_recorder)
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.telemetry = TelemetryRun(
            os.path.join(workdir, "fleet.jsonl"), run="fleet",
            meta={"n_devices": len(self.pool.devices)})
        # SLO alert engine (utils/alerts.AlertEngine): every round it
        # live-tails the tenants' streams, re-evaluates its rules, and
        # writes deduplicated typed ``alert`` records (firing/resolved)
        # onto the fleet stream. None = no alerting.
        self.alerts = alerts
        if alerts is not None and alerts.sink is None:
            alerts.sink = self.telemetry
        self.tenants: dict[str, Tenant] = {}
        self.rounds = 0
        self._seq = 0
        self._admit_seq = 0
        # Every (tenant, device-ids) grant ever made, for the
        # no-overlap/auditing tests and the fleet summary.
        self.assignment_log: list[dict] = []
        # Live status exporter (utils/statusz.py): the fleet's tenant
        # table / pool state under /statusz. Tenants join THIS exporter
        # as providers (one exporter per process; tenants are labels,
        # not ports). No-op when no port is configured anywhere.
        from distributed_model_parallel_tpu.utils import statusz

        statusz.maybe_serve(statusz_port)
        statusz.register("fleet", self._status)

    # -- bookkeeping ----------------------------------------------------------
    def _status(self) -> dict:
        """The fleet's /statusz provider payload: the tenant table
        (state / devices / attempt / step), pool state, firing alerts."""
        return {
            "workload": "fleet",
            "rounds": self.rounds,
            "tenants": {
                t.name: {
                    "state": t.state.value,
                    "workload": t.spec.workload,
                    "priority": t.priority,
                    "devices": list(t.devices),
                    "attempt": t.attempts,
                    "global_step": t.global_step,
                } for t in sorted(self.tenants.values(),
                                  key=lambda t: t.seq)},
            "pool": {
                "n_devices": len(self.pool.devices),
                "n_free": self.pool.n_free,
                "revoked": list(self.pool.revoked_ids),
                "quarantined": list(self.pool.quarantined_ids),
            },
            "alerts_firing": (self.alerts.firing
                              if self.alerts is not None else []),
            "failed_tenants": [t.name for t in self.tenants.values()
                               if t.state is TenantState.FAILED],
            # The control loop being alive IS fleet liveness: one failed
            # tenant is that tenant's problem (its row + alerts say so);
            # flipping the whole process's /healthz to 503 over it would
            # make a liveness probe restart a healthy fleet.
            "healthy": True,
        }

    def _apply_alerts(self) -> None:
        """One alert-engine pass: tail every tenant stream that exists,
        refresh the level signals (health scores), and tick — each
        firing/resolved transition lands as a typed ``alert`` record on
        the fleet stream (the engine's sink)."""
        if self.alerts is None:
            return
        for path in self.telemetry_paths()[1:]:
            self.alerts.watch(path)
        if self.health is not None:
            snap = self.health.snapshot()
            self.alerts.set_signal("health_scores",
                                   {int(k): v
                                    for k, v in snap["scores"].items()})
        self.alerts.poll()
        self.alerts.tick()

    def _record(self, tenant: Tenant, event: str, **fields) -> None:
        self.telemetry.record("tenant", name=tenant.name, event=event,
                              priority=tenant.priority, round=self.rounds,
                              **fields)

    def _by_state(self, *states: TenantState) -> list[Tenant]:
        return sorted((t for t in self.tenants.values()
                       if t.state in states), key=lambda t: t.seq)

    # -- submission / churn ---------------------------------------------------
    def submit(self, spec: TenantSpec) -> Tenant:
        if spec.name in self.tenants:
            raise ValueError(f"tenant name {spec.name!r} already submitted")
        log_key = (spec.config.log_dir, spec.config.log_name)
        for other in self.tenants.values():
            if other.spec.config.checkpoint_dir == spec.config.checkpoint_dir:
                raise ValueError(
                    f"tenant {spec.name!r} shares checkpoint_dir "
                    f"{spec.config.checkpoint_dir!r} with "
                    f"{other.name!r} — slots would clobber each other")
            if (other.spec.config.log_dir,
                    other.spec.config.log_name) == log_key:
                raise ValueError(
                    f"tenant {spec.name!r} shares telemetry stream "
                    f"{os.path.join(*log_key)}.jsonl with {other.name!r} — "
                    f"two tenants appending to one stream would merge "
                    f"under mixed attribution")
        tenant = Tenant(spec, self._seq)
        self._seq += 1
        self.tenants[spec.name] = tenant
        self._record(tenant, "submitted", workload=spec.workload)
        return tenant

    def cancel(self, name: str) -> None:
        """Tenant churn: withdraw a job. Queued jobs drop immediately; a
        running job is preempted (its checkpoint survives for a later
        campaign) and not re-queued."""
        tenant = self.tenants[name]
        if tenant.state is TenantState.QUEUED:
            tenant.state = TenantState.CANCELLED
            self._record(tenant, "cancelled")
        elif tenant.state in (TenantState.RUNNING, TenantState.PREEMPTING):
            tenant._cancel_on_reap = True
            self._preempt(tenant, reason="cancelled")

    # -- preemption -----------------------------------------------------------
    def preempt(self, name: str, *, reason: str = "manual") -> None:
        """Operator-initiated preemption of a running tenant (the
        scheduler's priority preemptions and topology shrinks route
        through the same path). The tenant drains through its preempt
        checkpoint on the next round and re-queues for resumption."""
        tenant = self.tenants[name]
        if tenant.state not in (TenantState.RUNNING,
                                TenantState.PREEMPTING):
            raise ValueError(f"tenant {name!r} is {tenant.state.value}, "
                             f"not running")
        self._preempt(tenant, reason=reason)

    def _preempt(self, tenant: Tenant, *, reason: str) -> None:
        if tenant.state is TenantState.PREEMPTING:
            return
        tenant.preemptions += 1
        tenant.request_preemption()
        self._record(tenant, "preempt-requested", reason=reason,
                     global_step=tenant.global_step)

    # -- topology churn -------------------------------------------------------
    def shrink(self, n: int) -> tuple[int, ...]:
        """Topology shrink: take ``n`` devices out of service. Tenants
        holding a revoked device are preempted; re-admission refits them
        to whatever remains (``fit_mesh_to_devices`` + resharded
        restore)."""
        ids = self.pool.revoke(n)
        self.telemetry.record("event",
                              message=f"topology shrink: revoked {ids}")
        for name in self.pool.holders_of_revoked():
            self._preempt(self.tenants[name], reason="topology-shrink")
        return ids

    def grow(self, n: int | None = None) -> tuple[int, ...]:
        """Topology grow: return revoked devices to service."""
        ids = self.pool.restore(n)
        if ids:
            self.telemetry.record("event",
                                  message=f"topology grow: restored {ids}")
        return ids

    # -- device health (utils/health.py) --------------------------------------
    def _apply_health(self) -> None:
        """Consume the health monitor's transitions for this round: every
        event becomes a typed ``health`` record on the fleet stream;
        newly quarantined devices leave the pool and their holders are
        proactively migrated — preempted through the ordinary
        preempt-checkpoint path *before* the degradation becomes a crash
        — and reinstated devices return to the free pool (where the
        grow-back pass may expand a shrunken tenant onto them)."""
        if self.health is None:
            return
        events = self.health.tick()
        quarantine: list[int] = []
        reinstate: list[int] = []
        for ev in events:
            self.telemetry.record("health", round=self.rounds, **ev)
            if ev["event"] == "quarantine":
                quarantine += ev["devices"]
            elif ev["event"] == "reinstate":
                reinstate += ev["devices"]
        if reinstate:
            back = self.pool.reinstate(reinstate)
            if back:
                self.telemetry.record(
                    "event", message=f"health reinstate: {back} back in "
                                     f"service after probation")
        if quarantine:
            # A maintenance-revoked device is already out of service —
            # quarantining it on top is a policy conflict the pool
            # rejects; it re-enters health scoring when restored.
            eligible = [i for i in quarantine
                        if i not in self.pool.revoked_ids]
            ids = self.pool.quarantine(eligible) if eligible else ()
            if ids:
                self.telemetry.record(
                    "event",
                    message=f"health quarantine: {ids} out of service")
            for name in self.pool.holders_of_quarantined():
                self._preempt(self.tenants[name], reason="device-degraded")

    def _maybe_grow_back(self) -> None:
        """Grow-back elasticity: a tenant running below its requested
        data-parallel degree (it was re-admitted onto a shrunken slice)
        is preempt-checkpointed and re-queued as soon as enough devices
        are free to place it larger — re-admission then lands it on the
        bigger slice at the exact global step. Only fires when the queue
        is empty (queued tenants own freed devices first — grow-back
        must not starve admissions) and at most one tenant per round
        (the re-queued tenant's own admission settles before the next
        candidate is considered, so growth never thrashes)."""
        if not self.grow_back or self.pool.n_free == 0:
            return
        if self._by_state(TenantState.QUEUED):
            return
        for t in sorted(self._by_state(TenantState.RUNNING),
                        key=lambda t: t.admit_seq):
            if not t.alive or t.spec.workload == "pipeline":
                continue
            cur = len(t.devices)
            want = self.scheduler.resolve_slice(
                t.spec, self.pool.n_free + cur)
            if want is not None and want > cur:
                # grow_backs counts GRANTED expansions: _admit compares
                # the re-admission grant against this size (the pool can
                # shrink again while the tenant drains, in which case
                # the cycle was churn, not growth).
                t._grow_back_from = cur
                self._preempt(t, reason="grow-back")
                self._record(t, "grow-back", devices=list(t.devices),
                             target_devices=want,
                             global_step=t.global_step)
                return

    # -- the control loop -----------------------------------------------------
    def _admit(self) -> int:
        """Serve the queue in (priority desc, submission order): grant
        free slices, or arrange preemptions for strictly-lower-priority
        victims. Head-of-line blocking — see scheduler.py. Returns how
        many tenants were admitted."""
        admitted = 0
        queue = sorted(self._by_state(TenantState.QUEUED),
                       key=lambda t: (-t.priority, t.seq))
        running = self._by_state(TenantState.RUNNING, TenantState.PREEMPTING)
        for waiter in queue:
            n = self.scheduler.resolve_slice(waiter.spec, self.pool.n_free)
            if n is not None:
                devices = self.pool.assign(waiter.name, n)
                granted = self.pool.assigned_ids(waiter.name)
                # Hard no-overlap invariant, independently of pool
                # internals: the grant must be disjoint from every other
                # live assignment.
                for other, ids in self.pool.assignments().items():
                    if other != waiter.name and set(ids) & set(granted):
                        raise RuntimeError(
                            f"device overlap: {waiter.name!r} granted "
                            f"{granted} while {other!r} holds {ids}")
                if getattr(waiter, "_grow_back_from", None) is not None:
                    if n > waiter._grow_back_from:
                        waiter.grow_backs += 1
                    waiter._grow_back_from = None
                waiter.start(devices, self._admit_seq)
                self._admit_seq += 1
                self.assignment_log.append(
                    {"round": self.rounds, "tenant": waiter.name,
                     "devices": granted, "attempt": waiter.attempts})
                self._record(waiter, "admitted", devices=list(granted),
                             attempt=waiter.attempts)
                # Settle construction (and any resume/reshard) before the
                # next scheduling decision; a construction that dies
                # immediately is reaped this same round.
                waiter.wait_boundary()
                admitted += 1
                continue
            victims = self.scheduler.pick_victims(waiter, running)
            if victims:
                for v in victims:
                    self._preempt(v, reason=f"priority:{waiter.name}")
            # Whether drains are pending or the waiter is simply too big
            # right now: hold the line so later (lower-priority) arrivals
            # can't steal the devices it is waiting for.
            break
        return admitted

    def _reap(self) -> None:
        """Collect finished tenant threads: free their devices and route
        the outcome — completed, preempted (re-queue with resume), or
        failed (the unrecovered ledger)."""
        for tenant in self._by_state(TenantState.RUNNING,
                                     TenantState.PREEMPTING):
            if tenant.alive:
                continue
            tenant.join()
            ids = self.pool.release(tenant.name)
            tenant.devices = ()
            if tenant.outcome == "failed":
                tenant.state = TenantState.FAILED
                self._record(tenant, "failed", devices=list(ids),
                             error=f"{type(tenant.error).__name__}: "
                                   f"{tenant.error}"[:300])
            elif tenant.outcome == "completed":
                tenant.state = TenantState.COMPLETED
                self._record(tenant, "completed", devices=list(ids),
                             global_step=tenant.global_step,
                             attempts=tenant.attempts)
            else:                   # preempted — checkpointed, resumable
                if tenant.state is TenantState.RUNNING:
                    # Self-preemption: an injected preempt fault or a
                    # stall-watchdog escalation inside the tenant, not an
                    # orchestrator decision — count it the same.
                    tenant.preemptions += 1
                tenant.preempted_at_step = tenant.global_step
                if getattr(tenant, "_cancel_on_reap", False):
                    tenant.state = TenantState.CANCELLED
                    self._record(tenant, "cancelled", devices=list(ids),
                                 global_step=tenant.global_step)
                else:
                    tenant.state = TenantState.QUEUED
                    self._record(tenant, "preempted", devices=list(ids),
                                 global_step=tenant.global_step)

    def pending(self) -> bool:
        return any(t.state in (TenantState.QUEUED, TenantState.RUNNING,
                               TenantState.PREEMPTING)
                   for t in self.tenants.values())

    def run_round(self) -> bool:
        """One scheduling round: admit, advance every running tenant by
        the quantum (admission order — deterministic), reap. Returns
        whether any tenant advanced or changed state. Each round is a
        ``round`` span on the fleet stream (utils/tracing.py) so the
        control loop's own cadence — and which rounds spent their time
        admitting/draining — renders on the fleet timeline next to the
        tenant lifecycle records."""
        before = {n: t.state for n, t in self.tenants.items()}
        # The exporter may have been started AFTER construction (a
        # tenant's statusz_port arriving first): re-registering is one
        # idempotent dict write, and keeps the fleet provider on
        # whatever exporter the process ended up with.
        from distributed_model_parallel_tpu.utils import statusz

        statusz.register("fleet", self._status)
        with tracing.sink_scope(self.telemetry), \
                span("round", round=self.rounds) as sp:
            self._apply_health()
            self._apply_alerts()
            admitted = self._admit()
            self._maybe_grow_back()
            moved = admitted > 0
            for tenant in sorted(self._by_state(TenantState.RUNNING,
                                                TenantState.PREEMPTING),
                                 key=lambda t: t.admit_seq):
                if tenant.state is TenantState.PREEMPTING:
                    with span("drain_tenant", tenant=tenant.name):
                        tenant.drain()
                    moved = True
                elif tenant.alive:
                    tenant.grant_steps(self.quantum)
                    moved = True
            with span("reap"):
                self._reap()
            sp.annotate(admitted=admitted)
        self.rounds += 1
        after = {n: t.state for n, t in self.tenants.items()}
        return moved or after != before

    def run(self, *, on_round: Callable[["Orchestrator", int], None]
            | None = None, max_rounds: int | None = None) -> dict:
        """Drive rounds until every tenant reaches a terminal state.

        ``on_round(orchestrator, round_index)`` fires before each round —
        the chaos-soak campaign's injection point for topology churn and
        late tenant submissions. Raises :class:`UnschedulableError` when
        the queue stagnates (nothing running, nothing admissible) and
        RuntimeError past ``max_rounds``.
        """
        stagnant = 0
        try:
            while self.pending():
                if max_rounds is not None and self.rounds >= max_rounds:
                    raise RuntimeError(
                        f"orchestrator exceeded {max_rounds} rounds with "
                        f"tenants still pending: "
                        f"{[t.name for t in self._by_state(TenantState.QUEUED, TenantState.RUNNING, TenantState.PREEMPTING)]}")
                if on_round is not None:
                    on_round(self, self.rounds)
                if self.run_round():
                    stagnant = 0
                else:
                    stagnant += 1
                    if stagnant > self.max_stagnant_rounds:
                        waiting = [t.name for t in
                                   self._by_state(TenantState.QUEUED)]
                        raise UnschedulableError(
                            f"no progress for {stagnant} rounds; queued "
                            f"tenants {waiting} cannot be placed on "
                            f"{self.pool.n_free} free devices "
                            f"(revoked: {self.pool.revoked_ids}, "
                            f"quarantined: {self.pool.quarantined_ids})")
        except BaseException:
            # A campaign dying mid-run never reaches close(): the
            # process-wide health monitor must not keep collecting (and
            # queueing events for) a dead campaign from later runs in
            # the same process.
            self._uninstall_health()
            self._uninstall_flightrec()
            raise
        # Final alert pass: the last tenants' tail records (written
        # after their final round) must still be able to resolve a
        # firing alert before the campaign summary reads it.
        self._apply_alerts()
        return self.summary()

    # -- results --------------------------------------------------------------
    def telemetry_paths(self) -> list[str]:
        """Every telemetry stream of this campaign: the fleet stream plus
        one per tenant (deduplicated — a resumed tenant appends to the
        same stream)."""
        paths = [self.telemetry.path]
        for t in sorted(self.tenants.values(), key=lambda t: t.seq):
            if t.jsonl_path and t.jsonl_path not in paths:
                paths.append(t.jsonl_path)
        return paths

    def summary(self) -> dict:
        """Fleet outcome: per-tenant states, preemption/resume exactness
        accounting, and the unrecovered-failure ledger."""
        tenants = {}
        for t in sorted(self.tenants.values(), key=lambda t: t.seq):
            grants = [a["devices"] for a in self.assignment_log
                      if a["tenant"] == t.name]
            tenants[t.name] = {
                "workload": t.spec.workload,
                "priority": t.priority,
                "state": t.state.value,
                "attempts": t.attempts,
                "preemptions": t.preemptions,
                "grow_backs": t.grow_backs,
                "resumed_exact_step": t.resume_exact,
                "resume_fallbacks": t.resume_fallbacks,
                "global_step": t.global_step,
                "faults_injected": [s.kind for s in t.fired_faults],
                # Slice trajectory across admissions: the shrink/grow-back
                # story in one list (requested = the config-mesh ceiling).
                "requested_devices": t.spec.requested_devices(),
                "granted_sizes": [len(g) for g in grants],
                "counters": t.counter_deltas,
            }
        failed = {t.name: f"{type(t.error).__name__}: {t.error}"[:300]
                  for t in self.tenants.values()
                  if t.state is TenantState.FAILED}
        return {
            "rounds": self.rounds,
            "tenants": tenants,
            "unrecovered": failed,
            "all_resumes_exact": all(
                all(t.resume_exact) for t in self.tenants.values()),
            "assignments": self.assignment_log,
            # The campaign's alert story: every firing/resolved
            # transition the engine emitted, plus what is STILL firing
            # at summary time (an operator's "anything red?" answer).
            "alerts": (list(self.alerts.events)
                       if self.alerts is not None else []),
            "alerts_firing": (self.alerts.firing
                              if self.alerts is not None else []),
            "postmortems": (list(self.flight_recorder.dumps)
                            if self.flight_recorder is not None else []),
        }

    def _uninstall_health(self) -> None:
        if self.health is not None:
            from distributed_model_parallel_tpu.utils import health as hm

            if hm.installed() is self.health:
                hm.uninstall()

    def _uninstall_flightrec(self) -> None:
        if self.flight_recorder is not None:
            from distributed_model_parallel_tpu.utils import flightrec

            if flightrec.installed() is self.flight_recorder:
                flightrec.uninstall()

    def close(self, **fields) -> None:
        self._uninstall_health()
        self._uninstall_flightrec()
        from distributed_model_parallel_tpu.utils import statusz

        statusz.unregister("fleet")
        self.telemetry.finish(**fields)
