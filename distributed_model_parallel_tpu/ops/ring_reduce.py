"""Explicit ring allreduce: the DDP Reducer's wire algorithm, on ICI.

The reference analyzes (but never implements) NCCL's bucketed ring-allreduce
inside PyTorch's C++ ``Reducer`` (reference ``Readme.md:14,148-157``). On TPU
the idiomatic move is a single ``lax.psum`` and letting XLA pick the
algorithm — that is what the DDP path defaults to. This module implements the
classic bandwidth-optimal ring explicitly — N-1 reduce-scatter steps + N-1
all-gather steps over neighbor ``ppermute``s, each moving 1/N of the buffer,
total traffic 2(N-1)/N of the buffer per device — for three reasons:

* parity: it is the actual algorithm the reference's analysis documents;
* benchmarking: comparing it against ``psum`` exposes what XLA's built-in
  collective achieves on the same mesh;
* control: neighbor-only ``ppermute`` traffic is guaranteed to ride ICI
  ring links, never DCN, which matters on multi-slice meshes.

Chunk convention matches ``lax.psum_scatter(..., tiled=True)``: device i ends
the reduce-scatter phase owning reduced chunk i.

All functions must be called inside ``shard_map`` over ``axis_name``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.ops.collectives import (
    axis_size,
    bucketed_psum,
)


def _neighbor_perm(n: int) -> list[tuple[int, int]]:
    return [(i, (i + 1) % n) for i in range(n)]


def _reduce_scatter_phase(chunks: jax.Array, axis_name: str) -> jax.Array:
    """N-1 steps; afterwards device i's row i holds sum of all devices' row i.

    At step s, device i sends chunk (i - s - 1) mod N to its right neighbor
    and accumulates the incoming chunk (i - s - 2) mod N.
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = _neighbor_perm(n)

    def step(s, chunks):
        send = chunks[(idx - s - 1) % n]
        recv = jax.lax.ppermute(send, axis_name, perm)
        return chunks.at[(idx - s - 2) % n].add(recv)

    return jax.lax.fori_loop(0, n - 1, step, chunks)


def _all_gather_phase(chunks: jax.Array, axis_name: str) -> jax.Array:
    """N-1 steps; starting from device i owning reduced chunk i, afterwards
    every device holds all reduced chunks.

    At step s, device i sends chunk (i - s) mod N and stores the incoming
    chunk (i - s - 1) mod N.
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = _neighbor_perm(n)

    def step(s, chunks):
        send = chunks[(idx - s) % n]
        recv = jax.lax.ppermute(send, axis_name, perm)
        return chunks.at[(idx - s - 1) % n].set(recv)

    return jax.lax.fori_loop(0, n - 1, step, chunks)


def ring_all_reduce(x: jax.Array, axis_name: str, *, mean: bool = False
                    ) -> jax.Array:
    """Allreduce ``x`` over ``axis_name`` via the explicit 2-phase ring.

    Result equals ``lax.psum(x, axis_name)`` (divided by N when ``mean``),
    for any shape — the buffer is flattened and zero-padded to N chunks.
    """
    n = axis_size(axis_name)
    if n == 1:
        return x
    shape, size = x.shape, x.size
    flat = x.reshape(-1)
    pad = (-size) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunks = flat.reshape(n, -1)
    chunks = _reduce_scatter_phase(chunks, axis_name)
    chunks = _all_gather_phase(chunks, axis_name)
    out = chunks.reshape(-1)[:size].reshape(shape)
    return out / n if mean else out


def ring_reduce_scatter(x: jax.Array, axis_name: str, *, mean: bool = False
                        ) -> jax.Array:
    """Reduce-scatter over the ring: device i gets slice i of the reduced
    buffer — same semantics as ``lax.psum_scatter(..., tiled=True)`` along
    axis 0. Requires ``x.shape[0] % N == 0``.
    """
    n = axis_size(axis_name)
    if n == 1:
        return x
    if x.shape[0] % n:
        raise ValueError(f"leading dim {x.shape[0]} not divisible by {n}")
    idx = jax.lax.axis_index(axis_name)
    chunks = x.reshape(n, x.shape[0] // n, *x.shape[1:])
    chunks = _reduce_scatter_phase(chunks, axis_name)
    out = chunks[idx]
    return out / n if mean else out


def ring_psum_tree(tree: Any, axis_name: str, *,
                   bucket_bytes: int = 25 * 1024 * 1024,
                   mean: bool = True) -> Any:
    """Bucketed ring allreduce of a gradient pytree.

    Drop-in for ``collectives.bucketed_psum`` but with the explicit ring as
    transport: leaves are coalesced into flat size-capped buckets (the DDP
    Reducer's trick, reference ``Readme.md:148-157``), each bucket makes one
    trip around the ring.
    """
    return bucketed_psum(tree, axis_name, bucket_bytes=bucket_bytes,
                         mean=mean, reduce_fn=ring_all_reduce)
