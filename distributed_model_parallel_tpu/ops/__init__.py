"""Communication and kernel ops:

* ``collectives`` — psum/ppermute/all_gather/reduce_scatter wrappers,
  bucketed coalesced allreduce, unused-param reporting
* ``ring_reduce`` — explicit bandwidth-optimal ring allreduce/reduce-scatter
  (the DDP Reducer's wire algorithm) over neighbor ppermutes
* ``ring_attention`` — ring + Ulysses sequence-parallel attention
* ``pallas_attention`` — on-chip blockwise flash attention kernel
* ``paged_attention`` — the serving engine's paged-KV-cache read: shared
  attend math, XLA gather fallback, Pallas paged-decode kernel with
  scalar-prefetched page tables (serve/, docs/SERVING.md)
* ``sparse`` — COO embedding gradients + DDP-style sparse allreduce
* ``moe`` — top-1 routed mixture-of-experts with expert-parallel all_to_all
"""

from distributed_model_parallel_tpu.ops.collectives import (  # noqa: F401
    all_gather_concat,
    bucketed_psum,
    ppermute_shift,
    psum_mean,
    reduce_scatter_mean,
    unused_param_mask,
)
from distributed_model_parallel_tpu.ops.ring_reduce import (  # noqa: F401
    ring_all_reduce,
    ring_psum_tree,
    ring_reduce_scatter,
)
from distributed_model_parallel_tpu.ops.ring_attention import (  # noqa: F401
    full_attention,
    ring_attention,
    ulysses_attention,
)
