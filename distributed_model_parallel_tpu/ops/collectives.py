"""Collective/communication layer.

TPU-native replacements for the native communication machinery the reference
consumes (SURVEY.md §2.2/§2.4):

* ``dist.send``/``dist.recv`` P2P with a 3-message dynamic-shape wire protocol
  (reference ``distributed_layers.py:11-13,20-24,42-45,52,58-60``) →
  ``ppermute_shift``: shapes are static under ``jit`` so the shape negotiation
  disappears; a stage-to-stage transfer is one collective-permute over ICI.
* the DDP ``Reducer``'s bucketed NCCL ring-allreduce fired from autograd hooks
  (reference ``Readme.md:14,148-157``) → ``psum_mean`` (XLA schedules
  overlap with the backward) and ``bucketed_psum`` (explicit flat-bucket
  allreduce — fewer, larger collectives, the Reducer's actual trick).
* ``comm.scatter``/``broadcast_coalesced``/``comm.gather`` used by
  DataParallel (``Readme.md:20,28-30,49-56,109-143``) → sharding-based
  ``scatter``/``replicate``/``gather`` in ``parallel/data_parallel.py``.

All functions taking ``axis_name`` must be called inside ``shard_map`` (or
another named-axis context) over that axis.

Every wrapper accounts its communication volume into the telemetry
registry (``utils/telemetry.record_collective``) **at trace time** — once
per compilation, tagged by kind and mesh axis, with per-device wire bytes
AND per-device message counts under the ring cost model
(``wire_bytes_estimate`` / ``wire_ops_estimate`` — the beta and alpha
terms of an alpha-beta comm model; the parallelism autotuner's cost model
is built on the same two estimators, so its analytic schedule and this
trace-time accounting are one currency, autotune/cost_model.py).
``scripts/dmp_report.py`` renders the totals; see the telemetry module
docstring for the per-compile (not per-step) semantics.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.utils.telemetry import record_collective


def _tree_bytes(tree: Any) -> int:
    """Static payload size of a pytree (works on tracers: shape/dtype only)."""
    return sum(l.size * np.dtype(l.dtype).itemsize
               for l in jax.tree.leaves(tree))


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis. ``jax.lax.axis_size`` is the
    stable spelling only in newer jax; the psum-of-1 idiom constant-folds
    to the same int everywhere."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def flatten_padded(tree: Any, n_shards: int, dtype=jnp.float32) -> jax.Array:
    """Concatenate all leaves (cast to ``dtype``, f32 by default) into one
    flat vector padded to a multiple of ``n_shards`` — the canonical
    pre-shape for contiguous scatter/gather collectives. Shared by the ZeRO
    optimizer sharding (parallel/zero.py, which wants the f32 master copy)
    and the hierarchical allreduce below (which passes the native gradient
    dtype so the wire payload matches the per-leaf transports)."""
    flat = jnp.concatenate(
        [l.astype(dtype).reshape(-1) for l in jax.tree.leaves(tree)])
    pad = (-flat.size) % n_shards
    return jnp.pad(flat, (0, pad))


def unflatten_like(flat: jax.Array, tree: Any) -> Any:
    """Inverse of ``flatten_padded`` (drops padding, restores dtypes)."""
    leaves, treedef = jax.tree.flatten(tree)
    out, off = [], 0
    for l in leaves:
        out.append(flat[off:off + l.size].reshape(l.shape).astype(l.dtype))
        off += l.size
    return jax.tree.unflatten(treedef, out)


def psum_mean(tree: Any, axis_name: str) -> Any:
    """Gradient averaging over the data axis — DDP's allreduce-mean."""
    n = jax.lax.psum(1, axis_name)
    record_collective("psum", axis_name, _tree_bytes(tree), n)
    return jax.tree.map(lambda g: jax.lax.psum(g, axis_name) / n, tree)


def ppermute_shift(x: jax.Array, axis_name: str, *, shift: int = 1) -> jax.Array:
    """Rotate values around a mesh axis ring: src i -> dst (i+shift) % n.

    The TPU-native equivalent of the reference's rank-to-rank activation
    send/recv (``distributed_layers.py:7-62``); on hardware this rides the ICI
    ring neighbor links.
    """
    n = axis_size(axis_name)
    record_collective("ppermute", axis_name, _tree_bytes(x), n)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def all_gather_concat(x: jax.Array, axis_name: str, *, axis: int = 0) -> jax.Array:
    """Gather shards along ``axis`` (DataParallel's output ``gather``)."""
    n = axis_size(axis_name)
    record_collective("all_gather", axis_name, _tree_bytes(x) * n, n)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def reduce_scatter_mean(x: jax.Array, axis_name: str, *, axis: int = 0) -> jax.Array:
    """psum_scatter-mean: each shard gets one slice of the reduced result —
    the building block of ZeRO-style sharded optimizers and of halving
    allreduce traffic when parameters are sharded."""
    n = axis_size(axis_name)
    record_collective("reduce_scatter", axis_name, _tree_bytes(x), n)
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True) / n


# ----------------------------------------------------------------------------
# Bucketed allreduce: the DDP Reducer capability (reference Readme.md:148-157).
# ----------------------------------------------------------------------------

def plan_buckets(tree: Any, bucket_bytes: int = 25 * 1024 * 1024
                 ) -> list[list[int]]:
    """Group flattened leaf indices into size-capped buckets, in reverse leaf
    order (the Reducer fills buckets in (roughly) reverse parameter order so
    early buckets become ready first during backward)."""
    leaves = jax.tree.leaves(tree)
    buckets: list[list[int]] = [[]]
    used = 0
    for idx in reversed(range(len(leaves))):
        nbytes = leaves[idx].size * np.dtype(leaves[idx].dtype).itemsize
        if buckets[-1] and used + nbytes > bucket_bytes:
            buckets.append([])
            used = 0
        buckets[-1].append(idx)
        used += nbytes
    return buckets


def bucketed_psum(tree: Any, axis_name: str, *,
                  bucket_bytes: int = 25 * 1024 * 1024,
                  mean: bool = True, reduce_fn: Any = None,
                  accum_dtype: Any = None) -> Any:
    """Allreduce a gradient pytree in flat coalesced buckets.

    Each bucket is flattened+concatenated into one vector, reduced with a
    single ``psum``, then split back — mirroring
    ``_broadcast_coalesced``/Reducer bucketing (``Readme.md:49-56,148-157``)
    with XLA free to overlap bucket collectives with compute.

    ``reduce_fn(flat, axis_name) -> flat`` swaps the transport (default
    ``lax.psum``; see ``ops/ring_reduce.ring_psum_tree`` for the explicit
    ring).

    Reduction dtype: by default each bucket is flattened in its own
    *promoted leaf dtype* (bf16 gradients reduce as bf16, like torch DDP; a
    stray f32 leaf upcasts only its own bucket) so the wire payload matches
    the per-leaf ``psum`` transport byte-for-byte. Note the conflation this
    implies: the accumulation across replicas then also happens at bf16
    precision, and the error grows with replica count. ``accum_dtype=
    jnp.float32`` decouples them — reduce (and mean-divide) in f32,
    downcast to the leaf dtype after — at the cost of a 2x wire payload
    for bf16 buckets (the XLA collective carries the accumulation dtype);
    the same trade torch DDP exposes via fp32-reduce comm hooks.
    """
    if reduce_fn is None:
        reduce_fn = jax.lax.psum
    leaves, treedef = jax.tree.flatten(tree)
    n = jax.lax.psum(1, axis_name) if mean else 1
    n_axis = axis_size(axis_name)
    out: list[Any] = [None] * len(leaves)
    for bucket in plan_buckets(tree, bucket_bytes):
        wire_dtype = (jnp.dtype(accum_dtype) if accum_dtype is not None
                      else jnp.result_type(*(leaves[i] for i in bucket)))
        flat = jnp.concatenate(
            [leaves[i].astype(wire_dtype).reshape(-1) for i in bucket])
        record_collective("bucketed_psum", axis_name,
                          flat.size * wire_dtype.itemsize, n_axis)
        red = reduce_fn(flat, axis_name)
        if mean:
            red = red / n
        offset = 0
        for i in bucket:
            size = leaves[i].size
            out[i] = red[offset:offset + size].reshape(
                leaves[i].shape).astype(leaves[i].dtype)
            offset += size
    return jax.tree.unflatten(treedef, out)


def hierarchical_psum(x: jax.Array, inner_axis: str, outer_axis: str, *,
                      mean: bool = False) -> jax.Array:
    """Two-level allreduce: reduce-scatter over ``inner_axis`` (ICI), psum
    over ``outer_axis`` (DCN), all-gather back over ``inner_axis``.

    Semantically equal to ``psum(x, (inner, outer))``; the staging is the
    bandwidth play for multi-host meshes — each host moves only 1/|inner| of
    the payload across the slow DCN hop, with the fast ICI links doing the
    full-size scatter/gather. (The same trick as NCCL's hierarchical rings,
    which is what DDP's Reducer rides on multi-node GPU clusters,
    ``Readme.md:148-157``.) Requires ``x``'s leading dim divisible by
    |inner|; use ``hierarchical_psum_tree`` for arbitrary pytrees.
    """
    n_in = axis_size(inner_axis)
    n_out = axis_size(outer_axis)
    record_collective("reduce_scatter", inner_axis, _tree_bytes(x), n_in)
    shard = jax.lax.psum_scatter(x, inner_axis, scatter_dimension=0,
                                 tiled=True)
    record_collective("psum", outer_axis, _tree_bytes(shard), n_out)
    shard = jax.lax.psum(shard, outer_axis)
    record_collective("all_gather", inner_axis, _tree_bytes(x), n_in)
    out = jax.lax.all_gather(shard, inner_axis, axis=0, tiled=True)
    if mean:
        out = out / (jax.lax.psum(1, inner_axis) * jax.lax.psum(1, outer_axis))
    return out


def hierarchical_psum_tree(tree: Any, inner_axis: str, outer_axis: str, *,
                           mean: bool = False) -> Any:
    """Hierarchical allreduce of a gradient pytree: flatten + pad to one
    vector (so the scatter is contiguous and every leaf shape is legal),
    two-level reduce, split back. Like ``hierarchical_psum`` (and
    ``lax.psum``) this sums by default; pass ``mean=True`` for DDP-style
    gradient averaging. The flat vector uses the promoted leaf dtype, not
    f32 — same wire-payload rule as ``bucketed_psum``."""
    flat = flatten_padded(tree, axis_size(inner_axis),
                          dtype=jnp.result_type(*jax.tree.leaves(tree)))
    red = hierarchical_psum(flat, inner_axis, outer_axis, mean=mean)
    return unflatten_like(red, tree)


_BARRIER_CACHE: dict = {}


def mesh_barrier(spec: Any) -> float:
    """Device-level rendezvous over EVERY axis of ``spec.mesh``: a
    scalar psum that cannot complete until all devices (and, on a
    multi-process mesh, all hosts) participate — then blocks until done.

    The building block the consistency sentinel's pre-check barrier uses
    on multiprocess runs: wrapped in ``mesh.barrier_with_timeout`` it
    turns a wedged or missing host into a reported straggler instead of
    an eternal hang in the first cross-host collective
    (train/consistency.py). Returns the world size (= psum of 1), which
    doubles as a cheap sanity check.
    """
    import jax

    mesh = spec.mesh
    # pop + reinsert keeps insertion order = recency, so the bound below
    # evicts the LEAST-recently-used entry, never a hot mesh's barrier.
    fn = _BARRIER_CACHE.pop(mesh, None)
    if fn is None:
        names = tuple(mesh.axis_names)
        n = int(np.prod(mesh.devices.shape))
        record_collective("psum", names, 4, n)

        def body():
            return jax.lax.psum(jnp.ones((), jnp.float32), names)

        from jax.sharding import PartitionSpec as P

        fn = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(), out_specs=P(), check_vma=False))
    _BARRIER_CACHE[mesh] = fn
    if len(_BARRIER_CACHE) > 8:              # bound the compiled-fn cache
        _BARRIER_CACHE.pop(next(iter(_BARRIER_CACHE)))
    out = fn()
    out.block_until_ready()
    return float(out)


def unused_param_mask(grads: Any) -> Any:
    """Per-leaf boolean: True where a gradient is identically zero.

    The capability analog of DDP's ``find_unused_parameters``
    (``Readme.md:153-157``): JAX autodiff already produces zero gradients for
    parameters not on the loss path (no hang to avoid — there are no autograd
    hooks waiting), so "detection" reduces to reporting which leaves were
    untouched, useful for debugging partially-frozen models.

    Caveat: this is a *value* test, not a graph-reachability test — a
    parameter that IS on the loss path but happens to receive an exactly-zero
    gradient at this step (e.g. behind a relu that is off for the whole
    batch) is also flagged. Treat a True as "no gradient signal this step";
    for a structural unused-parameter check, inspect the jaxpr of the loss
    instead (a leaf is structurally unused iff the grad jaxpr pipes a
    symbolic zero to it, which this debugging aid deliberately does not
    compute — it would force a retrace per call).
    """
    return jax.tree.map(lambda g: jnp.all(g == 0), grads)
