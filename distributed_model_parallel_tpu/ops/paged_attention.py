"""Paged decode attention — the KV-cache read path of the serving engine.

The dense decode cache (``models/transformer._cached_block``) is one
``[L, B, T_max, Hkv, Dh]`` buffer padded to the longest sequence the batch
will ever reach: a sequence that finished early keeps its whole slab until
the batch drains, and the batch width is frozen at prefill. The serving
engine (``serve/``) replaces it with a vLLM-style **paged** cache: a pool
of fixed-size pages ``[n_pages, page_size, Hkv, Dh]`` per layer plus a
per-sequence page table, so a sequence holds exactly
``ceil(len / page_size)`` pages and returns them the moment it finishes.

This module is the attention read over that pool. Three tiers, one math:

* :func:`attend_rows` — the single softmax/score definition every path
  shares (mirrors ``_cached_block``'s grouped-head scores + ``band_keep``
  masking), so paged and dense decoding cannot diverge numerically;
* :func:`paged_attention_xla` — gather the table's pages into a
  contiguous ``[B, T, Hkv, Dh]`` view and run :func:`attend_rows`; works
  on every backend (the off-TPU fallback, the prefill path, and the
  speculative-decoding verify step — its ``width``-token windows ride
  the same per-row-position support prefill chunks use);
* :func:`paged_attention_kernel` — the Pallas TPU kernel: the page table
  rides in scalar-prefetch SMEM and feeds the K/V block index maps, so
  pages stream HBM→VMEM directly (``pl.when`` skips the DMA + copy for
  logical pages past the sequence's length — the block-quantized-read
  idiom from ``generate()``'s read-boundary segments, at page
  granularity) and the gathered ``[B, T, ...]`` intermediate never
  exists in HBM. The final grid step runs the *same* :func:`attend_rows`
  on the VMEM-resident pages, which is what makes the kernel bitwise
  against the XLA path in interpreter mode (the parity contract
  tests/test_paged_attention.py pins).

Masking is sanitizing, not just causal: positions past a row's length are
zeroed in K/V *and* banded out of the scores, so stale page contents
(freed pages are reused without clearing) contribute exact ``0.0`` to
every reduction — a row's values depend only on its own written tokens,
never on who held the page before. That invariant is what makes
continuous batching per-request deterministic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_model_parallel_tpu.ops.pallas_attention import band_keep


def attend_rows(q: jax.Array, kr: jax.Array, vr: jax.Array,
                positions: jax.Array, lengths: jax.Array,
                window: int | None = None) -> jax.Array:
    """Grouped-head cached attention over per-row contiguous K/V.

    q: [B, C, H, Dh] queries (C contiguous tokens per row); kr/vr:
    [B, T, Hkv, Dh]; positions: [B, C] absolute token positions;
    lengths: [B] valid K prefix per row (everything at k_pos >= length is
    zeroed before any reduction — see module docstring). Returns
    [B, C, H, Dh].

    The score/softmax expression is ``_cached_block``'s exactly (query
    head h attends kv head h // G; same ``band_keep`` predicate), so the
    paged paths stay numerically on the dense path's definition.
    """
    b, c, h, dh = q.shape
    t, hkv = kr.shape[1], kr.shape[2]
    valid = jnp.arange(t)[None, :] < lengths[:, None]            # [B, T]
    kr = jnp.where(valid[:, :, None, None], kr, 0)
    vr = jnp.where(valid[:, :, None, None], vr, 0)
    qg = q.reshape(b, c, hkv, h // hkv, dh)
    # Scores and softmax accumulate in f32 regardless of the cache dtype
    # (preferred_element_type): bf16-accumulated dots are not bitwise
    # stable across lowerings (XLA gather path vs pallas interpret), and
    # pinning the accumulator is also just better serving numerics.
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kr,
                   preferred_element_type=jnp.float32) * (dh ** -0.5)
    keep = band_keep(positions[:, :, None],
                     jnp.arange(t)[None, None, :], window)       # [B, C, T]
    keep = jnp.logical_and(keep, valid[:, None, :])
    s = jnp.where(keep[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bkhd->bqhgd", p,
                   vr.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    return o.reshape(b, c, h, dh).astype(q.dtype)


def paged_attention_xla(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                        tables: jax.Array, positions: jax.Array,
                        lengths: jax.Array,
                        window: int | None = None) -> jax.Array:
    """Pure-XLA paged attention: gather then :func:`attend_rows`.

    q: [B, C, H, Dh]; k_pool/v_pool: [P, page, Hkv, Dh] (ONE layer's
    slab); tables: [B, N] physical page ids (rows padded with any
    in-range id — padded pages are masked by ``lengths``); positions:
    [B, C]; lengths: [B]. Materializes the gathered [B, N*page, Hkv, Dh]
    view in HBM — fine off-TPU and for prefill chunks; the decode hot
    loop on TPU wants :func:`paged_attention_kernel`.
    """
    b, n = tables.shape
    page = k_pool.shape[1]
    kr = k_pool[tables].reshape(b, n * page, *k_pool.shape[2:])
    vr = v_pool[tables].reshape(b, n * page, *v_pool.shape[2:])
    return attend_rows(q, kr, vr, positions, lengths, window)


# ---------------------------------------------------------------------------
# Pallas kernel (decode: one query token per row)
# ---------------------------------------------------------------------------

def _paged_decode_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         k_scr, v_scr, *, page: int, n_pages: int,
                         hkv: int, dh: int, window: int | None):
    """Grid: (B, n_pages). Scalar prefetch: tables [B, N], pos [B]. Each
    minor step DMAs one of the row's pages (the index map reads the page
    table; out-of-range steps re-map to the last used page so Mosaic
    elides the repeat DMA) and copies it into the contiguous VMEM
    scratch; ``pl.when`` skips the copy for logical pages past the row's
    length, so a short sequence reads only its own pages. The last step
    runs the shared :func:`attend_rows` on the assembled [T, Hkv, Dh]
    scratch — same ops as the XLA path, which is the bitwise-parity
    contract (interpreter). The dense-softmax-in-VMEM final step bounds
    T at VMEM capacity (serving contexts; a multi-kilobyte-page online-
    softmax variant is the long-context extension point).
    """
    b = pl.program_id(0)
    j = pl.program_id(1)
    pos = pos_ref[b]

    @pl.when(j <= pos // page)
    def _copy():
        k_scr[pl.dslice(j * page, page), :] = k_ref[0].reshape(
            page, hkv * dh)
        v_scr[pl.dslice(j * page, page), :] = v_ref[0].reshape(
            page, hkv * dh)

    @pl.when(j == n_pages - 1)
    def _finalize():
        t = n_pages * page
        q = q_ref[...][None]                           # [1, 1, H, Dh]
        kr = k_scr[...].reshape(1, t, hkv, dh)
        vr = v_scr[...].reshape(1, t, hkv, dh)
        # lengths zeroes everything past pos (including scratch rows no
        # copy step ever wrote — uninitialized VMEM must not reach a
        # reduction even multiplied by an exact-zero weight).
        o = attend_rows(q, kr, vr, pos[None, None], pos[None] + 1, window)
        o_ref[...] = o[0].astype(o_ref.dtype)


def paged_attention_kernel(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, tables: jax.Array,
                           positions: jax.Array,
                           window: int | None = None,
                           interpret: bool | None = None) -> jax.Array:
    """Pallas paged decode attention. q: [B, 1, H, Dh] (decode is one
    token per row); pools [P, page, Hkv, Dh]; tables [B, N]; positions
    [B] (the query token's absolute position; the row attends positions
    [0, pos], band-clamped under ``window``). Returns [B, 1, H, Dh].

    ``interpret=None`` auto-selects interpret mode off-TPU (tests run the
    kernel on CPU; the engine only dispatches it on real TPUs).
    """
    if q.shape[1] != 1:
        raise ValueError(f"the paged decode kernel takes one query token "
                         f"per row, got C={q.shape[1]} (prefill chunks go "
                         f"through paged_attention_xla)")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, _, h, dh = q.shape
    n_total, page, hkv, _ = k_pool.shape
    n = tables.shape[1]
    t = n * page

    def page_map(bi, j, tables_ref, pos_ref):
        # Clamp to the row's last used page: out-of-band steps re-fetch
        # an already-resident block (DMA elided) and pl.when skips them.
        last = pos_ref[bi] // page
        return (tables_ref[bi, jnp.minimum(j, last)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, n),
        in_specs=[
            pl.BlockSpec((1, h, dh), lambda bi, j, tr, pr: (bi, 0, 0)),
            pl.BlockSpec((1, page, hkv, dh), page_map),
            pl.BlockSpec((1, page, hkv, dh), page_map),
        ],
        out_specs=pl.BlockSpec((1, h, dh), lambda bi, j, tr, pr: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t, hkv * dh), k_pool.dtype),
            pltpu.VMEM((t, hkv * dh), v_pool.dtype),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, page=page, n_pages=n, hkv=hkv, dh=dh,
        window=window)
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, dh), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), positions.astype(jnp.int32),
      q[:, 0], k_pool, v_pool)
    return out[:, None]


def paged_attention(q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
                    tables: jax.Array, positions: jax.Array,
                    lengths: jax.Array, window: int | None = None,
                    impl: str = "auto") -> jax.Array:
    """Dispatch: the Pallas kernel for single-token decode on TPU, the
    XLA gather path everywhere else. ``impl``: "auto" | "xla" |
    "pallas". The kernel is decode-only (C == 1); multi-token prefill
    chunks take the gather path under EVERY impl — "pallas" forces the
    kernel for the decode steps (interpret mode off-TPU), it does not
    turn prefill into a kernel call."""
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown paged-attention impl {impl!r}; "
                         f"known: auto, xla, pallas")
    use_kernel = q.shape[1] == 1 and (
        impl == "pallas"
        or (impl == "auto" and jax.devices()[0].platform == "tpu"))
    if use_kernel:
        # Decode semantics: the one query token is the newest written
        # position, so the valid prefix is exactly positions + 1 — the
        # kernel derives lengths itself.
        return paged_attention_kernel(q, k_pool, v_pool, tables,
                                      positions[:, 0], window=window)
    return paged_attention_xla(q, k_pool, v_pool, tables, positions,
                               lengths, window=window)
