"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no attention at all (SURVEY.md §5 — pure-CNN workload), but
long-context is first-class for this framework. Two standard schemes, both
expressed as named-axis collectives so they compose with the ``data``/
``stage``/``model`` axes:

* **Ring attention** (`ring_attention`): Q stays put; (K, V) blocks rotate
  around the ``seq`` axis ring via ``ppermute`` while an online-softmax
  accumulator (running max / denominator / weighted values, à la
  Flash/blockwise attention) folds in one block per hop. Peak memory is one
  (K, V) block per device and comms ride the ICI ring — the long-context
  workhorse.
* **Ulysses** (`ulysses_attention`): ``all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs ordinary full attention on complete
  sequences for a subset of heads, and re-shards back. Cheaper compute
  plumbing when heads ≥ axis size; 2 all-to-alls per call.

Both must be called inside ``shard_map`` with ``axis_name`` bound, with
inputs sharded on the sequence dimension: q, k, v are the *local* shards
``[B, T_local, H, Dh]``.

Ring attention composes with the pallas flash kernels
(ops/pallas_attention.py): ``impl="auto"``/``"flash"`` runs each hop's
(Q_local, K_block) tile through the on-chip blocked kernel — the ring is the
*cross-chip* blocking, the kernel the *on-chip* blocking — merging hop
outputs via their logsumexp. Its backward is a second ring pass driving the
FlashAttention-2 dq/dkv kernels per hop, with dk/dv accumulators riding the
ring alongside their (K, V) blocks, so no [T_local, T_local] score tensor is
ever materialized in HBM in either direction. The ``"xla"`` block math
(which does materialize the per-hop local score tensor) remains for short
shards and non-TPU platforms; both paths accumulate in f32 regardless of
input dtype.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.ops.collectives import axis_size

_NEG = -1e30


def _block_attn(q, k, v, *, scale, q_pos, k_pos, causal):
    """Scores + masking for one (Q_local, K_block) pair, f32 accumulation.

    Returns (m, l, o): per-query running max, softmax denominator terms and
    value accumulator contributions for this block (all f32).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = k_pos[None, :] <= q_pos[:, None]        # [Tq, Tk]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                            # [B,H,Tq]
    # Guard fully-masked rows (exp(-inf - -inf)): zero them via finite max.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])                 # [B,H,Tq,Tk]
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)                            # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_safe, l, o                                # o [B,Tq,H,Dh] f32


def _ring_xla(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
              causal: bool) -> jax.Array:
    """The XLA block-math ring: materializes each hop's local score tensor
    (fine at short T_local); online-softmax state carried in f32."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = q.shape[-1] ** -0.5
    q_pos = idx * t_local + jnp.arange(t_local)

    # Online-softmax accumulators — f32 regardless of input dtype (bf16
    # running state would silently degrade vs the single-device kernel,
    # which accumulates f32).
    m_acc = jnp.full(q.shape[:1] + (q.shape[2], t_local), -jnp.inf,
                     jnp.float32)                       # [B,H,Tq]
    l_acc = jnp.zeros_like(m_acc)
    o_acc = jnp.zeros(q.shape, jnp.float32)

    def body(t, carry):
        m_acc, l_acc, o_acc, k_t, v_t = carry
        src = (idx - t) % n                             # origin of this block
        k_pos = src * t_local + jnp.arange(t_local)

        def compute():
            return _block_attn(q, k_t, v_t, scale=scale, q_pos=q_pos,
                               k_pos=k_pos, causal=causal)

        if causal:
            # Blocks entirely above the diagonal (src > idx) are fully
            # masked; skip their score matmuls at runtime. The (0, 0, 0)
            # stand-in is exactly what _block_attn returns for a fully
            # masked block (m_safe=0, l=0, o=0), so the merge below is
            # bit-identical — this halves the average per-hop compute,
            # the ring analog of the flash kernel's diagonal block skip.
            m_b, l_b, o_b = jax.lax.cond(
                src <= idx, compute,
                lambda: (jnp.zeros_like(m_acc), jnp.zeros_like(l_acc),
                         jnp.zeros_like(o_acc)))
        else:
            m_b, l_b, o_b = compute()
        m_new = jnp.maximum(m_acc, m_b)
        # Rescale old and new contributions onto the common max.
        a = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_new), 0.0)
        b = jnp.exp(m_b - m_new) * jnp.where(l_b > 0, 1.0, 0.0)
        l_new = a * l_acc + b * l_b
        o_new = (a[..., None].transpose(0, 2, 1, 3) * o_acc
                 + b[..., None].transpose(0, 2, 1, 3) * o_b)
        # Rotate (K, V) one hop around the ring.
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        return m_new, l_new, o_new, k_t, v_t

    carry = (m_acc, l_acc, o_acc, k, v)
    for t in range(n):   # static unroll: n is the mesh-axis size
        carry = body(t, carry)
    _, l_acc, o_acc, _, _ = carry
    denom = jnp.where(l_acc > 0, l_acc, 1.0)[..., None].transpose(0, 2, 1, 3)
    return (o_acc / denom).astype(q.dtype)


# ---------------------------------------------------------------------------
# kernel-in-ring: each hop runs the pallas flash kernel, outputs merged by lse
# ---------------------------------------------------------------------------

def _hop_is_full(idx, t):
    """At hop t, device idx holds block src = (idx - t) mod n; under causal
    masking the block contributes iff src <= idx, i.e. no ring wraparound."""
    return idx >= t


def _lse_to_bht(lse, b, h, t):
    """[B*H, T_pad] -> [B, H, T] (dropping causal padding rows)."""
    return lse.reshape(b, h, -1)[:, :, :t]


def _merge_by_lse(o_acc, lse_acc, o_b, lse_b):
    """Merge two normalized attention outputs via their logsumexp (all f32;
    o [B,T,H,D], lse [B,H,T]). A fully-masked side carries lse = -1e30 and
    drops out of the weights."""
    m = jnp.maximum(lse_acc, lse_b)
    w_a = jnp.exp(lse_acc - m)                          # [B,H,T]
    w_b = jnp.exp(lse_b - m)
    tot = w_a + w_b
    wa = (w_a / tot).transpose(0, 2, 1)[..., None]      # [B,T,H,1]
    wb = (w_b / tot).transpose(0, 2, 1)[..., None]
    return wa * o_acc + wb * o_b, m + jnp.log(tot)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash(q, k, v, axis_name, causal):
    o, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal)
    return o


def _ring_flash_fwd_impl(q, k, v, axis_name, causal):
    from distributed_model_parallel_tpu.ops.pallas_attention import (
        _flash_impl,
        default_blocks,
    )

    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t, h, _ = q.shape
    bq, bk = default_blocks()
    perm = [(i, (i + 1) % n) for i in range(n)]

    o_acc = jnp.zeros(q.shape, jnp.float32)
    lse_acc = jnp.full((b, h, t), _NEG, jnp.float32)
    k_t, v_t = k, v
    for hop in range(n):      # static unroll: n is the mesh-axis size
        def compute(k_t=k_t, v_t=v_t, hop_causal=(causal and hop == 0)):
            o_b, lse_b = _flash_impl(q, k_t, v_t, hop_causal, bq, bk, None)
            return o_b.astype(jnp.float32), _lse_to_bht(lse_b, b, h, t)

        if causal and hop > 0:
            # Blocks from above the diagonal (wrapped around the ring) are
            # fully masked: skip the kernel at runtime, merge a no-op.
            o_b, lse_b = jax.lax.cond(
                _hop_is_full(idx, hop), compute,
                lambda: (jnp.zeros(q.shape, jnp.float32),
                         jnp.full((b, h, t), _NEG, jnp.float32)))
        else:
            o_b, lse_b = compute()
        o_acc, lse_acc = _merge_by_lse(o_acc, lse_acc, o_b, lse_b)
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
    return o_acc.astype(q.dtype), lse_acc


def _ring_flash_fwd(q, k, v, axis_name, causal):
    o, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, causal, res, g):
    """Second ring pass driving the FlashAttention-2 backward kernels: each
    hop computes this device's (dq, dk, dv) tile against the visiting (K, V)
    block from the *global* saved (o, lse) — the hop tiles of the global
    softmax sum exactly to the full gradients — with the dk/dv accumulators
    rotating in lockstep with their blocks (home after n hops)."""
    from distributed_model_parallel_tpu.ops.pallas_attention import (
        _flash_bwd_impl,
        default_blocks,
        dispatch_entry,
    )

    q, k, v, o, lse = res
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t, h, _ = q.shape
    bq, bk = default_blocks()
    # Per-kernel measured dispatch tiles (ADVICE r4: the non-ring flash
    # path already uses them; without this the sp-ring backward left the
    # ~9% dq/dkv tile win on the table).
    entry = dispatch_entry() or {}
    dq_blocks = ((entry["dq_block_q"], entry["dq_block_k"])
                 if "dq_block_q" in entry else None)
    dkv_blocks = ((entry["dkv_block_q"], entry["dkv_block_k"])
                  if "dkv_block_q" in entry else None)
    perm = [(i, (i + 1) % n) for i in range(n)]
    # _flash_bwd_impl reads lse in its residual [B*H, T_pad] layout.
    lse_flat = lse.reshape(b * h, t)

    dq = jnp.zeros(q.shape, jnp.float32)
    dk_t = jnp.zeros(k.shape, jnp.float32)
    dv_t = jnp.zeros(v.shape, jnp.float32)
    k_t, v_t = k, v
    for hop in range(n):
        def compute(k_t=k_t, v_t=v_t, hop_causal=(causal and hop == 0)):
            dq_b, dk_b, dv_b = _flash_bwd_impl(
                q, k_t, v_t, o, lse_flat, g, hop_causal, bq, bk, None,
                dq_blocks=dq_blocks, dkv_blocks=dkv_blocks)
            return (dq_b.astype(jnp.float32), dk_b.astype(jnp.float32),
                    dv_b.astype(jnp.float32))

        if causal and hop > 0:
            dq_b, dk_b, dv_b = jax.lax.cond(
                _hop_is_full(idx, hop), compute,
                lambda: (jnp.zeros(q.shape, jnp.float32),
                         jnp.zeros(k.shape, jnp.float32),
                         jnp.zeros(v.shape, jnp.float32)))
        else:
            dq_b, dk_b, dv_b = compute()
        dq = dq + dq_b
        dk_t = dk_t + dk_b
        dv_t = dv_t + dv_b
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        dk_t = jax.lax.ppermute(dk_t, axis_name, perm)
        dv_t = jax.lax.ppermute(dv_t, axis_name, perm)
    return (dq.astype(q.dtype), dk_t.astype(k.dtype), dv_t.astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   *, causal: bool = True, impl: str = "auto") -> jax.Array:
    """Blockwise ring attention over ``axis_name``.

    q/k/v: local shards [B, T_local, H, Dh]; the global sequence is the
    concatenation of shards in axis-index order. Returns the local output
    shard [B, T_local, H, Dh].

    ``impl``: "flash" runs each hop through the pallas flash kernel
    (kernel-in-ring; on-chip blocked in both directions), "xla" uses the
    einsum block math (materializes the [Tq, Tk] hop tile), "auto" picks
    flash when the shared dispatch heuristic favors it for the *local*
    shard length (long-shard TPU runs) and the shard length tiles cleanly.
    """
    if impl not in ("auto", "flash", "xla"):
        raise ValueError(f"unknown ring impl {impl!r}; known: auto, flash, xla")
    use_flash = impl == "flash"
    if impl == "auto":
        from distributed_model_parallel_tpu.ops.pallas_attention import (
            should_use_flash,
        )

        use_flash = (q.shape[1] % 128 == 0
                     and should_use_flash(q.shape[1], causal=causal,
                                          head_dim=q.shape[-1],
                                          dtype=q.dtype))
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal)
    return _ring_xla(q, k, v, axis_name, causal)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, *, causal: bool = True,
                      impl: str = "auto") -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Re-shards [B, T/n, H, Dh] -> [B, T, H/n, Dh], runs full softmax attention
    over the complete sequence for the local head subset, then re-shards back.
    Requires H % axis_size == 0. ``impl`` is the flash-vs-XLA selector
    (``should_use_flash``): "auto" consults the measured dispatch table
    (bf16 and f32 both auto-select at their measured crossover, and a
    raised matmul-precision context auto-declines the kernel); "flash"
    forces the pallas kernel for dtypes/regimes the table excludes.
    """
    n = axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by axis size {n}")

    def seq_to_heads(x):   # [B, T/n, H, Dh] -> [B, T, H/n, Dh]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):   # [B, T, H/n, Dh] -> [B, T/n, H, Dh]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    t = qh.shape[1]
    # The local compute is ordinary attention over the complete sequence, so
    # the pallas flash kernel drops in where it wins (shared heuristic).
    from distributed_model_parallel_tpu.ops.pallas_attention import (
        flash_attention,
        should_use_flash,
    )
    if should_use_flash(t, causal=causal, impl=impl,
                        head_dim=qh.shape[-1], dtype=qh.dtype):
        return heads_to_seq(flash_attention(qh, kh, vh, causal=causal))
    scale = qh.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
    return heads_to_seq(o)


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   *, causal: bool = True) -> jax.Array:
    """Reference single-device attention ([B, T, H, Dh]) for parity tests and
    the non-sequence-parallel path."""
    t = q.shape[1]
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
