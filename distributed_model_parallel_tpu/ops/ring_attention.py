"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no attention at all (SURVEY.md §5 — pure-CNN workload), but
long-context is first-class for this framework. Two standard schemes, both
expressed as named-axis collectives so they compose with the ``data``/
``stage``/``model`` axes:

* **Ring attention** (`ring_attention`): Q stays put; (K, V) blocks rotate
  around the ``seq`` axis ring via ``ppermute`` while an online-softmax
  accumulator (running max / denominator / weighted values, à la
  Flash/blockwise attention) folds in one block per hop. Peak memory is one
  (K, V) block per device and comms ride the ICI ring — the long-context
  workhorse.
* **Ulysses** (`ulysses_attention`): ``all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs ordinary full attention on complete
  sequences for a subset of heads, and re-shards back. Cheaper compute
  plumbing when heads ≥ axis size; 2 all-to-alls per call.

Both must be called inside ``shard_map`` with ``axis_name`` bound, with
inputs sharded on the sequence dimension: q, k, v are the *local* shards
``[B, T_local, H, Dh]``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, *, scale, q_pos, k_pos, causal):
    """Scores + masking for one (Q_local, K_block) pair.

    Returns (m, l, o): per-query running max, softmax denominator terms and
    value accumulator contributions for this block.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = k_pos[None, :] <= q_pos[:, None]        # [Tq, Tk]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                            # [B,H,Tq]
    # Guard fully-masked rows (exp(-inf - -inf)): zero them via finite max.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])                 # [B,H,Tq,Tk]
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1)                            # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)            # [B,Tq,H,Dh]
    return m_safe, l, o


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   *, causal: bool = True) -> jax.Array:
    """Blockwise ring attention over ``axis_name``.

    q/k/v: local shards [B, T_local, H, Dh]; the global sequence is the
    concatenation of shards in axis-index order. Returns the local output
    shard [B, T_local, H, Dh].
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = q.shape[-1] ** -0.5
    q_pos = idx * t_local + jnp.arange(t_local)

    # Online-softmax accumulators.
    m_acc = jnp.full(q.shape[:1] + (q.shape[2], t_local), -jnp.inf,
                     q.dtype)                           # [B,H,Tq]
    l_acc = jnp.zeros_like(m_acc)
    o_acc = jnp.zeros_like(q)

    def body(t, carry):
        m_acc, l_acc, o_acc, k_t, v_t = carry
        src = (idx - t) % n                             # origin of this block
        k_pos = src * t_local + jnp.arange(t_local)

        def compute():
            return _block_attn(q, k_t, v_t, scale=scale, q_pos=q_pos,
                               k_pos=k_pos, causal=causal)

        if causal:
            # Blocks entirely above the diagonal (src > idx) are fully
            # masked; skip their score matmuls at runtime. The (0, 0, 0)
            # stand-in is exactly what _block_attn returns for a fully
            # masked block (m_safe=0, l=0, o=0), so the merge below is
            # bit-identical — this halves the average per-hop compute,
            # the ring analog of the flash kernel's diagonal block skip.
            m_b, l_b, o_b = jax.lax.cond(
                src <= idx, compute,
                lambda: (jnp.zeros_like(m_acc), jnp.zeros_like(l_acc),
                         jnp.zeros_like(o_acc)))
        else:
            m_b, l_b, o_b = compute()
        m_new = jnp.maximum(m_acc, m_b)
        # Rescale old and new contributions onto the common max.
        a = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_new), 0.0)
        b = jnp.exp(m_b - m_new) * jnp.where(l_b > 0, 1.0, 0.0)
        l_new = a * l_acc + b * l_b
        o_new = (a[..., None].transpose(0, 2, 1, 3) * o_acc
                 + b[..., None].transpose(0, 2, 1, 3) * o_b)
        # Rotate (K, V) one hop around the ring.
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        return m_new, l_new, o_new, k_t, v_t

    carry = (m_acc, l_acc, o_acc, k, v)
    for t in range(n):   # static unroll: n is the mesh-axis size
        carry = body(t, carry)
    _, l_acc, o_acc, _, _ = carry
    denom = jnp.where(l_acc > 0, l_acc, 1.0)[..., None].transpose(0, 2, 1, 3)
    return o_acc / denom


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, *, causal: bool = True,
                      impl: str = "auto") -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Re-shards [B, T/n, H, Dh] -> [B, T, H/n, Dh], runs full softmax attention
    over the complete sequence for the local head subset, then re-shards back.
    Requires H % axis_size == 0. ``impl`` is the flash-vs-XLA selector
    (``should_use_flash``): "auto" consults the measured dispatch table;
    "flash" forces the pallas kernel (the escape hatch for dtypes the table
    excludes, e.g. f32 long-context where XLA cannot materialize [T, T]).
    """
    n = jax.lax.axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by axis size {n}")

    def seq_to_heads(x):   # [B, T/n, H, Dh] -> [B, T, H/n, Dh]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):   # [B, T, H/n, Dh] -> [B, T/n, H, Dh]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    t = qh.shape[1]
    # The local compute is ordinary attention over the complete sequence, so
    # the pallas flash kernel drops in where it wins (shared heuristic).
    from distributed_model_parallel_tpu.ops.pallas_attention import (
        flash_attention,
        should_use_flash,
    )
    if should_use_flash(t, causal=causal, impl=impl,
                        head_dim=qh.shape[-1], dtype=qh.dtype):
        return heads_to_seq(flash_attention(qh, kh, vh, causal=causal))
    scale = qh.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
    return heads_to_seq(o)


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   *, causal: bool = True) -> jax.Array:
    """Reference single-device attention ([B, T, H, Dh]) for parity tests and
    the non-sequence-parallel path."""
    t = q.shape[1]
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
