"""Pallas flash attention for TPU — forward and backward kernels.

The hand-written-kernel tier of the stack (the reference's analog is the CUDA
kernels it consumes from PyTorch; SURVEY.md §2.2): blockwise online-softmax
causal attention that keeps the [T, T] score matrix out of HBM entirely —
scores live tile-by-tile in VMEM, the MXU does the matmuls, and only O([T, D])
touches HBM. Composes with ring attention (ops/ring_attention.py) which
handles the *cross-chip* blocking; this kernel is the *on-chip* blocking.

All three kernels stream K/V (or Q, for dk/dv) through VMEM one block per
grid step: the key/query sequence is a *grid dimension*, not a whole-sequence
VMEM block, so Mosaic double-buffers the next block's DMA against the current
block's MXU work and VMEM usage is O(block), independent of sequence length.
The online-softmax running state (m, l, acc) is carried across those grid
steps in f32 VMEM scratch — initialized on the first step of each row,
flushed to the output on the last. Causal (and windowed) programs clamp their
streaming index map to the diagonal band, so out-of-band grid steps fetch
nothing new and `pl.when` skips their compute entirely.

Backward is the FlashAttention-2 scheme: the forward also emits the per-row
logsumexp, and two kernels recompute score tiles from (q, k, lse) to produce
dq (grid over query blocks) and dk/dv (grid over key blocks) — so the
backward, like the forward, never materializes [T, T] in HBM. The
``bwd_impl="xla"`` escape hatch keeps the old recompute-with-XLA VJP.

Falls back to interpret mode off-TPU (tests run it on CPU), and pads the head
dim to the 128-lane tile when needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# ``pltpu.CompilerParams`` is the newer spelling; this container's pallas
# still names it ``TPUCompilerParams`` (same fields).
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

NEG_INF = -1e30


def band_keep(q_pos, k_pos, window):
    """Causal (and optionally banded) keep-mask — the single definition all
    three kernels share so forward and backward masking cannot diverge."""
    keep = k_pos <= q_pos
    if window is not None:
        keep = jnp.logical_and(keep, k_pos > q_pos - window)
    return keep


def _band_start_k(qi, bq, window, block_k):
    """First K block intersecting any band in q block qi (0 if unwindowed)."""
    if window is None:
        return 0
    return jnp.maximum(0, (qi * bq - window + 1) // block_k)


def _last_k_block(qi, bq, block_k):
    """Last K block at or below the diagonal for q block qi (causal)."""
    return ((qi + 1) * bq - 1) // block_k


def _block_interior(qi, j, bq, bk, window):
    """True when the (q block qi, k block j) tile lies strictly inside the
    causal band — every key <= every query, and (windowed) every key inside
    the window — so ``band_keep`` would be all-true and the kernels may
    take their mask-free step. The complement of ``band_keep`` at block
    granularity: keep the two definitions side by side so they cannot
    drift."""
    interior = (j + 1) * bk - 1 <= qi * bq
    if window is not None:
        interior = jnp.logical_and(
            interior, j * bk > qi * bq + bq - 1 - window)
    return interior


def _when_banded(in_band, interior, step):
    """Dispatch one grid step to ``step(masked: bool)``: mask-free for
    band-interior tiles, masked for diagonal/window-edge tiles, skipped
    outside the band. Shared by all three kernels (the fast path matters
    because the forward is VPU-bound — kernel_profile_r4.json)."""
    pl.when(jnp.logical_and(in_band, interior))(lambda: step(False))
    pl.when(jnp.logical_and(in_band, jnp.logical_not(interior)))(
        lambda: step(True))


def _kv_stream_map(causal, bq, bk, window):
    """Index map for K/V blocks streamed over the minor grid dim. Causal
    programs clamp j into the band [start, diag] so the out-of-band steps
    re-map to an already-resident block — Mosaic elides the repeat DMA —
    while `pl.when` in the kernel skips their compute."""
    if not causal:
        return lambda bh, i, j: (bh, j, 0)

    def index(bh, i, j):
        lo = _band_start_k(i, bq, window, bk)
        hi = _last_k_block(i, bq, bk)
        return (bh, jnp.clip(j, lo, hi), 0)

    return index


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                  *, num_k: int, causal: bool, scale: float,
                  window: int | None = None):
    """Grid: (batch*heads, num_q_blocks, num_k_blocks). Blocks: q/o [1, BQ, D];
    k/v [1, BK, D] (streamed over the minor grid dim); lse [1, 8, BQ] (per-row
    logsumexp of the scaled scores, for the backward, broadcast over 8
    sublanes for tile legality). Scratch: m/l [BQ, 128] f32 (sublane-major,
    lanes redundant), acc [BQ, D] f32 — the online-softmax carry across K
    steps. ``window`` (causal only): each
    query attends keys in (q_pos - window, q_pos]."""
    qi = pl.program_id(1)
    j = pl.program_id(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _step(masked: bool):
        q = q_ref[0] * scale                               # [BQ, D]
        k = k_ref[0]                                       # [BK, D]
        v = v_ref[0]
        # m/l ride sublane-major ([BQ, LW] with identical lanes) so every
        # step's broadcasts against [BQ, BK] tiles stay on the sublane axis
        # — no lane<->sublane relayout in the inner loop.
        m = m_scr[...]                                     # [BQ, LW]
        l = l_scr[...]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        keep = None
        if masked:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            keep = band_keep(q_pos, k_pos, window)
            s = jnp.where(keep, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1)[:, None])     # [BQ, LW]
        p = jnp.exp(s - m_new[:, :1])
        if masked and window is not None:
            # A row whose every key in this block is banded out while m is
            # still at the sentinel would get exp(NEG_INF - NEG_INF) = 1;
            # zero masked entries explicitly. Unreachable without a window
            # (the first processed block always holds each row's diagonal),
            # so the unwindowed hot path pays nothing.
            p = jnp.where(keep, p, 0.0)
        alpha = jnp.exp(m - m_new)                               # [BQ, LW]
        l_new = alpha * l + jnp.sum(p, axis=-1)[:, None]
        acc_scr[...] = alpha[:, :1] * acc_scr[...] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[...] = m_new
        l_scr[...] = l_new

    if causal:
        # Skip K blocks entirely outside the band: above the diagonal, and
        # (windowed) entirely left of the band. Their grid steps still run,
        # but fetch no new block (the index map clamps) and do no compute.
        # Blocks strictly inside the band (every key <= every query, no
        # window edge) take a mask-free step — the iota/compare/select VPU
        # passes run only on diagonal-crossing blocks, which matters
        # because the forward is VPU-bound (kernel_profile_r4.json).
        in_band = jnp.logical_and(j >= _band_start_k(qi, bq, window, bk),
                                  j <= _last_k_block(qi, bq, bk))
        _when_banded(in_band, _block_interior(qi, j, bq, bk, window), _step)
    else:
        _step(False)

    @pl.when(j == num_k - 1)
    def _finalize():
        l = l_scr[...]                                     # [BQ, LW]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, :1]).astype(o_ref.dtype)
        # lse rides in an (8, lane)-tiled layout: Mosaic requires the last
        # two block dims divisible by (8, 128), so the per-row vector is
        # broadcast over 8 sublanes (read back as row 0). The sublane->lane
        # relayout happens once per q row, not per K step.
        m_col, l_col = m_scr[:, 0], l_safe[:, 0]           # [BQ]
        lse = jnp.where(l_scr[:, 0] == 0, NEG_INF, m_col + jnp.log(l_col))
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, bq))


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2): recompute p from (q, k, lse)
# ---------------------------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, acc_scr, lse_scr, delta_scr, *, num_k: int,
                         causal: bool, scale: float,
                         window: int | None = None):
    """Grid: (batch*heads, num_q_blocks, num_k_blocks), K/V streamed over the
    minor dim. dq_i = scale * sum_j ds_ij k_j with ds = p * (dO·v^T - delta);
    delta = rowsum(dO * O). Scratch: the dq accumulator [BQ, D] f32, plus
    sublane-major copies of lse/delta ([BQ, LW]) transposed once per q row
    so the K loop broadcasts without lane<->sublane relayouts."""
    qi = pl.program_id(1)
    j = pl.program_id(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)
        lw = lse_scr.shape[1]
        lse_scr[...] = jnp.broadcast_to(lse_ref[0, 0][:, None], (bq, lw))
        delta_scr[...] = jnp.broadcast_to(delta_ref[0, 0][:, None], (bq, lw))

    def _step(masked: bool):
        q = q_ref[0]                                       # [BQ, D] (input
        do = do_ref[0]                                     # dtype for MXU)
        lse = lse_scr[:, :1]                               # [BQ, 1]
        delta = delta_scr[:, :1]
        k = k_ref[0]
        v = v_ref[0]
        s = scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse)                               # [BQ, BK] f32
        if masked:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            p = jnp.where(band_keep(q_pos, k_pos, window), p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        acc_scr[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    if causal:
        in_band = jnp.logical_and(j >= _band_start_k(qi, bq, window, bk),
                                  j <= _last_k_block(qi, bq, bk))
        _when_banded(in_band, _block_interior(qi, j, bq, bk, window), _step)
    else:
        _step(False)

    @pl.when(j == num_k - 1)
    def _finalize():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _q_bounds_for_k(ki, bk, bq, num_q, causal, window):
    """[start, end) of query blocks attending any key in key block ki."""
    if not causal:
        return 0, num_q
    start_q = (ki * bk) // bq
    if window is None:
        return start_q, num_q
    # Last query that can see any key in this block attends the block's
    # last key ((ki+1)*bk - 1) from window - 1 positions later.
    end_q = jnp.minimum(num_q, ((ki + 1) * bk - 1 + window - 1) // bq + 1)
    return start_q, end_q


def _q_stream_map(causal, bq, bk, num_q, window):
    """Index map for Q/dO (and lse/delta via ``lane_row``) blocks streamed
    over the dk/dv kernel's minor grid dim, clamped to the band like
    ``_kv_stream_map``."""
    if not causal:
        return lambda bh, ki, i: (bh, i, 0)

    def index(bh, ki, i):
        lo, hi = _q_bounds_for_k(ki, bk, bq, num_q, causal, window)
        return (bh, jnp.clip(i, lo, hi - 1), 0)

    return index


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *, num_q: int,
                          causal: bool, scale: float,
                          window: int | None = None):
    """Grid: (batch*heads, num_k_blocks, num_q_blocks), Q/dO/lse/delta
    streamed over the minor dim. dv_j = sum_i p_ij dO_i; dk_j = scale *
    sum_i ds_ij q_i. Scratch: dk/dv accumulators [BK, D] f32. Causal skips
    query blocks strictly above the diagonal (queries before this key block
    attend none of it); a window also skips query blocks past the band's
    lower edge."""
    ki = pl.program_id(1)
    i = pl.program_id(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    # The whole step works in transposed score space — s^T [BK, BQ], keys on
    # sublanes, queries on lanes — so the per-query lse/delta vectors (which
    # arrive lane-major) broadcast along sublanes for free, and dk/dv land
    # sublane-major [BK, D] straight from the MXU. No lane<->sublane
    # relayout anywhere in the Q loop.
    def _step(masked: bool):
        k = k_ref[0]                                       # [BK, D] (input
        v = v_ref[0]                                       # dtype for MXU)
        q = q_ref[0]                                       # [BQ, D]
        do = do_ref[0]
        lse = lse_ref[0, 0]                                # [BQ] lane-major
        delta = delta_ref[0, 0]
        contract_d = (((1,), (1,)), ((), ()))
        s_t = scale * jax.lax.dot_general(                 # [BK, BQ]
            k, q, contract_d, preferred_element_type=jnp.float32)
        p_t = jnp.exp(s_t - lse[None, :])                  # [BK, BQ] f32
        if masked:
            k_pos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bk, bq), 0)
            q_pos = i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bk, bq), 1)
            p_t = jnp.where(band_keep(q_pos, k_pos, window), p_t, 0.0)
        pc_t = p_t.astype(do.dtype)
        dv_scr[...] += jnp.dot(pc_t, do, preferred_element_type=jnp.float32)
        dp_t = jax.lax.dot_general(                        # [BK, BQ]
            v, do, contract_d, preferred_element_type=jnp.float32)
        ds_t = (p_t * (dp_t - delta[None, :]) * scale).astype(q.dtype)
        dk_scr[...] += jnp.dot(ds_t, q, preferred_element_type=jnp.float32)

    if causal:
        lo, hi = _q_bounds_for_k(ki, bk, bq, num_q, causal, window)
        in_band = jnp.logical_and(i >= lo, i < hi)
        _when_banded(in_band, _block_interior(i, ki, bq, bk, window), _step)
    else:
        _step(False)

    @pl.when(i == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# padding/layout plumbing shared by forward and backward
# ---------------------------------------------------------------------------

def _plan(t, d, causal, block_q, block_k, interpret):
    """Resolve (t_padded, d_padded, block_q, block_k, interpret)."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    t_pad = t
    if t % 128:
        if not causal and not interpret:
            raise ValueError(
                f"non-causal flash attention needs seq len divisible by 128 "
                f"on TPU (got {t}); pad inputs or use full_attention")
        if causal:
            t_pad = -(-t // 128) * 128

    def clamp(block: int) -> int:
        if not interpret:
            # On real TPUs the lse/delta tiles put the block on the lane
            # dim, so blocks must be multiples of 128 AND divide t_pad
            # (grid/loop counts floor silently otherwise). t_pad is a
            # multiple of 128 here, so search divisors in 128-lane units.
            m_units = t_pad // 128
            d_units = max(1, min(block // 128, m_units))
            while m_units % d_units:
                d_units -= 1
            return 128 * d_units
        # Interpret mode (tests): largest block <= requested that divides
        # t (halving preserves the power-of-two shape; bottoms out at 1).
        blk = min(block, t_pad)
        while t_pad % blk:
            blk //= 2
        return blk

    d_pad = max(128, d) if not interpret else d
    return t_pad, d_pad, clamp(block_q), clamp(block_k), interpret


def _pad_bhtd(x, t_pad, d_pad):
    """[B, T, H, D] -> [B*H, T_pad, D_pad]."""
    b, t, h, d = x.shape
    if t_pad != t or d_pad != d:
        x = jnp.pad(x, [(0, 0), (0, t_pad - t), (0, 0), (0, d_pad - d)])
    return x.transpose(0, 2, 1, 3).reshape(b * h, t_pad, d_pad)


def _unpad_bthd(x, b, h, t, d):
    """[B*H, T_pad, D_pad] -> [B, T, H, D]."""
    t_pad, d_pad = x.shape[1], x.shape[2]
    x = x.reshape(b, h, t_pad, d_pad).transpose(0, 2, 1, 3)
    return x[:, :t, :, :d]


_SEQ_SEMANTICS = ("parallel", "parallel", "arbitrary")
# Lane width of the sublane-major [BQ, _LANE_W] m/l/lse/delta scratch tiles
# (all 128 lanes carry the same per-row value; column 0 is read back).
_LANE_W = 128


def _flash_impl(q, k, v, causal, block_q, block_k, interpret, window=None):
    """Run the forward kernel; returns (o [B,T,H,D], lse [B*H, T_pad] f32)
    — lse stays in the padded flat layout for the backward (which re-tiles
    it to 8 sublanes alongside delta)."""
    b, t, h, d = q.shape
    t_pad, d_pad, bq, bk, interp = _plan(t, d, causal, block_q, block_k,
                                         interpret)
    scale = d ** -0.5
    num_k = t_pad // bk
    qf, kf, vf = (_pad_bhtd(x, t_pad, d_pad) for x in (q, k, v))
    kernel = functools.partial(_flash_kernel, num_k=num_k, causal=causal,
                               scale=scale, window=window)
    kv_map = _kv_stream_map(causal, bq, bk, window)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t_pad // bq, num_k),
        in_specs=[
            pl.BlockSpec((1, bq, d_pad), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d_pad), kv_map),
            pl.BlockSpec((1, bk, d_pad), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d_pad), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda bh, i, j: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_pad, d_pad), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, t_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANE_W), jnp.float32),
            pltpu.VMEM((bq, _LANE_W), jnp.float32),
            pltpu.VMEM((bq, d_pad), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=_SEQ_SEMANTICS),
        interpret=interp,
    )(qf, kf, vf)
    # Keep only sublane row 0 as the residual (the 8 rows are identical
    # copies written for tile legality) — 1x, not 8x, memory per layer.
    return _unpad_bthd(o, b, h, t, d), lse[:, 0, :]


def _bwd_prep(q, k, v, o, lse, g, t_pad, d_pad):
    """Shared backward preprocessing: delta = rowsum(dO * O) (tiny
    elementwise pass in plain XLA; padded rows get delta 0 and g 0, so
    they contribute nothing), lse padding for callers holding only the
    real-T lse, and the 8-sublane tiling both vectors need for Mosaic
    block-layout legality."""
    b, t, h, d = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = delta.transpose(0, 2, 1).reshape(b * h, t)
    if t_pad != t:
        delta = jnp.pad(delta, [(0, 0), (0, t_pad - t)])
    if lse.shape[1] != t_pad:
        # Padded rows have zero cotangents, so any finite lse keeps their
        # p finite and their contributions zero.
        lse = jnp.pad(lse, [(0, 0), (0, t_pad - lse.shape[1])])
    delta = jnp.broadcast_to(delta[:, None, :], (b * h, 8, t_pad))
    lse = jnp.broadcast_to(lse[:, None, :], (b * h, 8, t_pad))
    qf, kf, vf, gf = (_pad_bhtd(x, t_pad, d_pad) for x in (q, k, v, g))
    return qf, kf, vf, gf, lse, delta


def _bwd_dq_call(qf, kf, vf, gf, lse, delta, *, bq, bk, d_pad, causal, scale,
                 window, interp, out_dtype):
    """The dq kernel as one pallas_call (own block shape)."""
    bh_n, t_pad, _ = qf.shape
    num_q, num_k = t_pad // bq, t_pad // bk
    q_row_spec = pl.BlockSpec((1, bq, d_pad), lambda bh, i, j: (bh, i, 0))
    q_vec_spec = pl.BlockSpec((1, 8, bq), lambda bh, i, j: (bh, 0, i))
    kv_map = _kv_stream_map(causal, bq, bk, window)
    kv_spec = pl.BlockSpec((1, bk, d_pad), kv_map)
    return pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, num_k=num_k, causal=causal,
                          scale=scale, window=window),
        grid=(bh_n, num_q, num_k),
        in_specs=[
            q_row_spec, kv_spec, kv_spec,
            # dO is per-query-row: blocked like q.
            q_row_spec, q_vec_spec, q_vec_spec,
        ],
        out_specs=q_row_spec,
        out_shape=jax.ShapeDtypeStruct((bh_n, t_pad, d_pad), out_dtype),
        scratch_shapes=[pltpu.VMEM((bq, d_pad), jnp.float32),
                        pltpu.VMEM((bq, _LANE_W), jnp.float32),
                        pltpu.VMEM((bq, _LANE_W), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=_SEQ_SEMANTICS),
        interpret=interp,
    )(qf, kf, vf, gf, lse, delta)


def _bwd_dkv_call(qf, kf, vf, gf, lse, delta, *, bq, bk, d_pad, causal,
                  scale, window, interp, k_dtype, v_dtype):
    """The dk/dv kernel as one pallas_call (own block shape)."""
    bh_n, t_pad, _ = qf.shape
    num_q, num_k = t_pad // bq, t_pad // bk
    q_map = _q_stream_map(causal, bq, bk, num_q, window)
    q_stream_spec = pl.BlockSpec((1, bq, d_pad), q_map)
    vec_stream_spec = pl.BlockSpec(
        (1, 8, bq), lambda bh, ki, i: (bh, 0, q_map(bh, ki, i)[1]))
    k_blk_spec = pl.BlockSpec((1, bk, d_pad), lambda bh, ki, i: (bh, ki, 0))
    return pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, num_q=num_q, causal=causal,
                          scale=scale, window=window),
        grid=(bh_n, num_k, num_q),
        in_specs=[
            q_stream_spec, k_blk_spec, k_blk_spec,
            q_stream_spec, vec_stream_spec, vec_stream_spec,
        ],
        out_specs=[k_blk_spec, k_blk_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh_n, t_pad, d_pad), k_dtype),
            jax.ShapeDtypeStruct((bh_n, t_pad, d_pad), v_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d_pad), jnp.float32),
            pltpu.VMEM((bk, d_pad), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=_SEQ_SEMANTICS),
        interpret=interp,
    )(qf, kf, vf, gf, lse, delta)


def _flash_bwd_impl(q, k, v, o, lse, g, causal, block_q, block_k, interpret,
                    window=None, dq_blocks: tuple[int, int] | None = None,
                    dkv_blocks: tuple[int, int] | None = None):
    """Pallas backward: dq/dk/dv with [T, T] never in HBM.

    ``dq_blocks``/``dkv_blocks`` optionally give each backward kernel its
    own (q block, k block) tile shape — the two kernels have opposite
    residency (dq keeps queries resident and streams K/V; dk/dv the
    reverse), so their best tiles differ from the forward's and from each
    other (measured per-kernel sweep: benchmarks/kernel_profile_r4.json;
    both prefer 1024x1024 on v5e where the forward wants 512x1024).
    Unset, both inherit ``block_q``/``block_k``."""
    b, t, h, d = q.shape
    t_pad, d_pad, bq, bk, interp = _plan(t, d, causal, block_q, block_k,
                                         interpret)
    scale = d ** -0.5
    qf, kf, vf, gf, lse_t, delta = _bwd_prep(q, k, v, o, lse, g, t_pad, d_pad)

    def resolve(blocks):
        if blocks is None:
            return bq, bk
        _, _, rq, rk, _ = _plan(t, d, causal, blocks[0], blocks[1],
                                interpret)
        return rq, rk

    bq1, bk1 = resolve(dq_blocks)
    dq = _bwd_dq_call(qf, kf, vf, gf, lse_t, delta, bq=bq1, bk=bk1,
                      d_pad=d_pad, causal=causal, scale=scale, window=window,
                      interp=interp, out_dtype=q.dtype)

    bq2, bk2 = resolve(dkv_blocks)
    dk, dv = _bwd_dkv_call(qf, kf, vf, gf, lse_t, delta, bq=bq2, bk=bk2,
                           d_pad=d_pad, causal=causal, scale=scale,
                           window=window, interp=interp, k_dtype=k.dtype,
                           v_dtype=v.dtype)

    return (_unpad_bthd(dq, b, h, t, d), _unpad_bthd(dk, b, h, t, d),
            _unpad_bthd(dv, b, h, t, d))


# ---------------------------------------------------------------------------
# public differentiable entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, causal, block_q, block_k, interpret, bwd_impl, window,
           dq_blocks, dkv_blocks):
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret,
                       window)[0]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, bwd_impl, window,
               dq_blocks, dkv_blocks):
    o, lse = _flash_impl(q, k, v, causal, block_q, block_k, interpret, window)
    if bwd_impl == "xla":
        # The XLA-recompute backward reads only (q, k, v); don't hold the
        # output and lse in residual HBM for nothing.
        return o, (q, k, v, None, None)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, bwd_impl, window,
               dq_blocks, dkv_blocks, res, g):
    """Backward dispatch: the pallas FlashAttention-2 kernels by default
    (no [T, T] in HBM), or the XLA recompute formulation (``bwd_impl="xla"``,
    materializes scores — the pre-kernel behavior, kept as an escape hatch).
    Both are parity-pinned in tests/test_pallas_attention.py."""
    q, k, v, o, lse = res
    if bwd_impl == "xla":
        from distributed_model_parallel_tpu.ops.ring_attention import (
            full_attention,
        )

        _, vjp = jax.vjp(
            lambda q, k, v: full_attention(q, k, v, causal=causal), q, k, v)
        return vjp(g)
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, block_q, block_k,
                           interpret, window, dq_blocks=dq_blocks,
                           dkv_blocks=dkv_blocks)


_flash.defvjp(_flash_fwd, _flash_bwd)


# Flash-vs-XLA dispatch table, keyed by device_kind prefix. Values are
# measured, not guessed — benchmarks/dispatch_sweep.json holds the v5e
# sweep rows each entry was derived from (benchmarks/run_sweep.py across
# seq/dtype/head_dim). Unlisted TPU generations inherit the "tpu" row
# (same MXU/VMEM architecture; re-sweep to specialize); non-TPU platforms
# never auto-select flash — pallas interpret mode is orders of magnitude
# slower than XLA's fused attention.
#
# min_seq: crossover sequence length per compute dtype; None = never
#   auto-select for that dtype. bf16 crossover 1024 (streamed-K/V kernel,
#   r3 sweep: 0.17 vs 0.40 ms at hd 64, 0.16 vs 0.41 ms at hd 128; at 512
#   XLA still wins ~2x). float32 crossover 1024 too (r3 f32 sweeps,
#   dispatch_sweep_r3_f32.json / grad_sweep_r3_f32.json: fwd+bwd flash
#   wins 3.3x at 1024 and 4.5x at 4096, XLA wins at 512; XLA f32 cannot
#   run seq 8k at all). Precision footing is equal, not degraded: at
#   jax's DEFAULT matmul precision XLA's f32 attention also runs
#   single-pass MXU dots — measured max-abs error vs a float64 reference
#   on unit-scale inputs is 1.1e-2 (XLA f32) vs 7.6e-3 (flash f32), the
#   same bf16-pass class. Callers raising precision globally (e.g.
#   jax.default_matmul_precision('float32')) get true-f32 dots only from
#   XLA — the kernel does not consult that context — so should_use_flash
#   declines f32 auto-dispatch whenever the precision config is raised
#   (_matmul_precision_raised).
# block_q/block_k: fastest measured tile shape (clamped to seq at call
#   time).
# max_head_dim: the kernel keeps [block, D] tiles resident in VMEM; above
#   this, tiles spill and XLA wins regardless of seq.
_DISPATCH_TABLE: dict[str, dict] = {
    # bwd kernels carry their own measured tiles (dq_/dkv_block_*): both
    # backward kernels prefer 1024x1024 on v5e where the forward's best
    # is 512x1024 (benchmarks/kernel_profile_r4.json, seq-8k hd-128 sweep).
    "TPU v5 lite": {"min_seq": {"bfloat16": 1024, "float32": 1024},
                    "block_q": 512, "block_k": 1024, "max_head_dim": 256,
                    "dq_block_q": 1024, "dq_block_k": 1024,
                    "dkv_block_q": 1024, "dkv_block_k": 1024},
    "tpu": {"min_seq": {"bfloat16": 1024, "float32": 1024},
            "block_q": 512, "block_k": 1024, "max_head_dim": 256,
            "dq_block_q": 1024, "dq_block_k": 1024,
            "dkv_block_q": 1024, "dkv_block_k": 1024},
}


def dispatch_entry(device=None) -> dict | None:
    """The dispatch-table row for ``device`` (default ``jax.devices()[0]``);
    None on non-TPU platforms, the generic "tpu" row for unlisted TPUs."""
    from distributed_model_parallel_tpu.utils.profiling import (
        match_device_kind,
    )

    device = device if device is not None else jax.devices()[0]
    if device.platform != "tpu":
        return None
    specific = {k: v for k, v in _DISPATCH_TABLE.items() if k != "tpu"}
    return (match_device_kind(specific, device)
            or _DISPATCH_TABLE["tpu"])


def default_blocks(device=None) -> tuple[int, int]:
    """Per-platform (block_q, block_k) kernel tile defaults (the kernel
    itself clamps them to the actual sequence length)."""
    entry = dispatch_entry(device) or _DISPATCH_TABLE["tpu"]
    return entry["block_q"], entry["block_k"]


def _matmul_precision_raised() -> bool:
    """True when jax_default_matmul_precision is set above DEFAULT (e.g.
    'float32'/'highest'/'high'/'tensorfloat32') — the caller explicitly
    asked for more-than-single-pass MXU dots."""
    prec = jax.config.jax_default_matmul_precision
    return prec is not None and str(prec).lower() not in ("default", "fastest",
                                                          "bfloat16")


def should_use_flash(t: int, *, causal: bool = True, impl: str = "auto",
                     head_dim: int = 64, dtype=None,
                     device=None) -> bool:
    """Single home for the flash-vs-XLA dispatch heuristic (used by
    models/transformer and ops/ring_attention): "flash"/"xla" force an
    implementation; "auto" consults the per-platform dispatch table —
    sequence-length crossover by compute dtype, and a head-dim cap above
    which the kernel's VMEM tiles spill."""
    if impl == "flash":
        return True
    if impl == "xla":
        return False
    if impl != "auto":
        raise ValueError(f"unknown attn impl {impl!r}; known: auto, xla, flash")
    if not causal:
        return False
    entry = dispatch_entry(device)
    if entry is None:
        return False
    if head_dim > entry["max_head_dim"]:
        return False
    dtype_name = jnp.dtype(dtype).name if dtype is not None else "bfloat16"
    # Unlisted dtypes (e.g. float64 under x64) stay on XLA: the kernel
    # computes at bf16-input precision, so only dtypes with an explicit
    # measured entry may auto-select it.
    if dtype_name == "float32" and _matmul_precision_raised():
        # The f32 crossover was measured at jax's DEFAULT matmul precision,
        # where XLA's attention runs the same single-pass MXU dots as the
        # kernel. A caller who raised jax_default_matmul_precision asked
        # for true-f32 dots — which only XLA honors (the kernel does not
        # consult the precision context) — so auto must not route them to
        # the kernel's lower-precision math.
        return False
    min_seq = entry["min_seq"].get(dtype_name)
    if min_seq is None:
        return False
    return t >= min_seq


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool | None = None,
                    bwd_impl: str = "flash",
                    window: int | None = None,
                    dq_blocks: tuple[int, int] | None = None,
                    dkv_blocks: tuple[int, int] | None = None) -> jax.Array:
    """[B, T, H, D] -> [B, T, H, D] causal attention, pallas-blocked.

    ``interpret=None`` auto-selects interpret mode off-TPU. Default block
    sizes (``block_q``/``block_k`` = None) come from the per-platform
    dispatch table (``dispatch_entry``; blocks clamp to the sequence length
    for short inputs).

    K/V stream through VMEM one block per grid step (the sequence is a grid
    dimension, not a resident VMEM block), so per-program VMEM is O(block)
    and the sequence ceiling is set by HBM, not VMEM — seq 32k+ compiles
    and runs on a single v5e in both directions. Past the single-chip HBM
    budget, the long-context route is sequence parallelism over the ``seq``
    mesh axis (ops/ring_attention.py), which shards T before the kernel
    runs.

    ``window=W`` (causal only) restricts each query to the last W keys —
    sliding-window/local attention. Both directions skip blocks entirely
    outside the band (no DMA, no compute), so cost drops from O(T^2)
    toward O(T*W).

    Differentiable via a custom VJP: the FlashAttention-2 backward kernels
    recompute score tiles from the saved logsumexp, so neither direction
    puts [T, T] in HBM; ``bwd_impl="xla"`` selects the old
    recompute-with-XLA backward instead (full/causal only — it has no
    windowed reference formulation).
    """
    if bwd_impl not in ("flash", "xla"):
        raise ValueError(f"unknown bwd_impl {bwd_impl!r}; known: flash, xla")
    explicit_blocks = block_q is not None or block_k is not None
    if block_q is None or block_k is None:
        dq, dk = default_blocks()
        block_q = block_q if block_q is not None else dq
        block_k = block_k if block_k is not None else dk
    if not explicit_blocks:
        # Fully-defaulted callers get the measured per-kernel backward
        # tiles; a caller who tuned block_q/block_k (VMEM pressure, a
        # sweep) keeps control of BOTH directions — the table's backward
        # tiles were measured at head_dim 128 and must not override an
        # explicit choice.
        entry = dispatch_entry() or {}
        if dq_blocks is None and "dq_block_q" in entry:
            dq_blocks = (entry["dq_block_q"], entry["dq_block_k"])
        if dkv_blocks is None and "dkv_block_q" in entry:
            dkv_blocks = (entry["dkv_block_q"], entry["dkv_block_k"])
    if window is not None:
        if not causal:
            raise ValueError("window requires causal attention")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if bwd_impl == "xla":
            raise ValueError("window is only supported with bwd_impl='flash'")
    return _flash(q, k, v, causal, block_q, block_k, interpret, bwd_impl,
                  window, dq_blocks, dkv_blocks)
