"""Pallas flash attention for TPU.

The hand-written-kernel tier of the stack (the reference's analog is the CUDA
kernels it consumes from PyTorch; SURVEY.md §2.2): a blockwise
online-softmax causal attention kernel that keeps the [T, T] score matrix out
of HBM entirely — scores live tile-by-tile in VMEM, the MXU does the two
matmuls, and only O([T, Dh]) touches HBM. Composes with ring attention
(ops/ring_attention.py) which handles the *cross-chip* blocking; this kernel
is the *on-chip* blocking.

Falls back to interpret mode off-TPU (tests run it on CPU), and pads the head
dim to the 128-lane tile when needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_len: int,
                  causal: bool, scale: float):
    """Grid: (batch*heads, num_q_blocks). Blocks: q/o [1, BQ, D]; k/v [1, T, D]."""
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0] * scale                                   # [BQ, D]

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    num_k = seq_len // block_k

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :]   # [BK, D]
        v = v_ref[0, pl.dslice(j * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = alpha[:, None] * acc + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # Skip K blocks entirely above the diagonal: the last contributing
        # block covers query position (qi+1)*bq - 1.
        num_k_eff = ((qi + 1) * bq - 1) // block_k + 1
        m, l, acc = jax.lax.fori_loop(0, num_k_eff, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, num_k, body, (m0, l0, acc0))

    l = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    b, t, h, d = q.shape
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    # Ragged sequence lengths: for causal attention, zero-padding the
    # sequence END is exact — padded keys occupy future positions no real
    # query attends to, and padded query rows are sliced off below. This
    # keeps blocks >= the TPU tile (8x128) for any T. Non-causal padding
    # would need a key mask the kernel doesn't carry, so reject ragged T
    # there rather than hand Mosaic an illegal tile.
    t_orig = t
    if t % 128:
        if not causal and not interpret:
            raise ValueError(
                f"non-causal flash attention needs seq len divisible by 128 "
                f"on TPU (got {t}); pad inputs or use full_attention")
        if causal:
            t = -(-t // 128) * 128
            pad_t = [(0, 0), (0, t - t_orig), (0, 0), (0, 0)]
            q, k, v = (jnp.pad(x, pad_t) for x in (q, k, v))

    def clamp(block: int) -> int:
        # Largest block <= requested that divides t (halving preserves the
        # power-of-two shape the kernel tiles well with; bottoms out at 1).
        blk = min(block, t)
        while t % blk:
            blk //= 2
        return blk

    block_q = clamp(block_q)
    block_k = clamp(block_k)

    # Pad head dim to the TPU lane width so tiles are legal.
    d_pad = max(128, d) if not interpret else d
    scale = d ** -0.5
    if d_pad != d:
        pad = [(0, 0)] * 3 + [(0, d_pad - d)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))

    def bhtd(x):   # [B, T, H, D] -> [B*H, T, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d_pad)

    qf, kf, vf = bhtd(q), bhtd(k), bhtd(v)
    kernel = functools.partial(_flash_kernel, block_k=block_k, seq_len=t,
                               causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, t, d_pad), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, t, d_pad), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d_pad), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)

    out = out.reshape(b, h, t, d_pad).transpose(0, 2, 1, 3)
    return out[:, :t_orig, :, :d]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    """Backward: recompute attention with the XLA formulation and pull the
    cotangent through its VJP. Forward keeps flash's O(T) memory and speed;
    backward pays the materialized-scores cost (a dedicated flash backward
    kernel is the future upgrade). Mathematically identical to the kernel —
    parity pinned in tests/test_pallas_attention.py."""
    from distributed_model_parallel_tpu.ops.ring_attention import (
        full_attention,
    )

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: full_attention(q, k, v, causal=causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def should_use_flash(t: int, *, causal: bool = True,
                     impl: str = "auto") -> bool:
    """Single home for the flash-vs-XLA dispatch heuristic (used by
    models/transformer and ops/ring_attention): "flash"/"xla" force an
    implementation; "auto" picks flash on TPU for causal sequences >=
    2048, where the kernel's forward is 3-10x faster than XLA
    (benchmarks/run_sweep.py)."""
    if impl == "flash":
        return True
    if impl == "xla":
        return False
    if impl != "auto":
        raise ValueError(f"unknown attn impl {impl!r}; known: auto, xla, flash")
    return (causal and t >= 2048
            and jax.devices()[0].platform == "tpu")


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, block_q: int = 512,
                    block_k: int = 1024,
                    interpret: bool | None = None) -> jax.Array:
    """[B, T, H, D] -> [B, T, H, D] causal attention, pallas-blocked.

    ``interpret=None`` auto-selects interpret mode off-TPU. Default block
    sizes come from a v5e sweep with forced-sync timing (block 512x1024 is
    ~6x faster than 128x128 at seq 2-4k: 63 vs 9 TFLOPS at seq 2048;
    blocks clamp to the sequence length for short inputs). Beats plain XLA
    attention from seq ~2048 up, and still compiles at seq 8192 where the
    materialized T^2 score tensor makes XLA fail. Differentiable via a
    custom VJP (XLA-recompute backward, ``_flash_bwd``).
    """
    return _flash(q, k, v, causal, block_q, block_k, interpret)
