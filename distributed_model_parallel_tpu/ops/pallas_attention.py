"""Pallas flash attention for TPU — forward and backward kernels.

The hand-written-kernel tier of the stack (the reference's analog is the CUDA
kernels it consumes from PyTorch; SURVEY.md §2.2): blockwise online-softmax
causal attention that keeps the [T, T] score matrix out of HBM entirely —
scores live tile-by-tile in VMEM, the MXU does the matmuls, and only O([T, D])
touches HBM. Composes with ring attention (ops/ring_attention.py) which
handles the *cross-chip* blocking; this kernel is the *on-chip* blocking.

Backward is the FlashAttention-2 scheme: the forward also emits the per-row
logsumexp, and two kernels recompute score tiles from (q, k, lse) to produce
dq (grid over query blocks) and dk/dv (grid over key blocks) — so the
backward, like the forward, never materializes [T, T] in HBM. The
``bwd_impl="xla"`` escape hatch keeps the old recompute-with-XLA VJP.

Falls back to interpret mode off-TPU (tests run it on CPU), and pads the head
dim to the 128-lane tile when needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def band_keep(q_pos, k_pos, window):
    """Causal (and optionally banded) keep-mask — the single definition all
    three kernels share so forward and backward masking cannot diverge."""
    keep = k_pos <= q_pos
    if window is not None:
        keep = jnp.logical_and(keep, k_pos > q_pos - window)
    return keep


def _band_start_k(qi, bq, window, block_k):
    """First K block intersecting any band in q block qi (0 if unwindowed)."""
    if window is None:
        return 0
    return jnp.maximum(0, (qi * bq - window + 1) // block_k)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  seq_len: int, causal: bool, scale: float,
                  window: int | None = None):
    """Grid: (batch*heads, num_q_blocks). Blocks: q/o [1, BQ, D]; k/v [1, T, D];
    lse [1, BQ] (per-row logsumexp of the scaled scores, for the backward).
    ``window`` (causal only): each query attends keys in
    (q_pos - window, q_pos] — sliding-window/local attention, with K blocks
    entirely outside the band skipped."""
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0] * scale                                   # [BQ, D]

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    num_k = seq_len // block_k

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(j * block_k, block_k), :]   # [BK, D]
        v = v_ref[0, pl.dslice(j * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [BQ, BK]
        keep = None
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            keep = band_keep(q_pos, k_pos, window)
            s = jnp.where(keep, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal and window is not None:
            # A row whose every key in this block is banded out while m is
            # still at the sentinel would get exp(NEG_INF - NEG_INF) = 1;
            # zero masked entries explicitly. Unreachable without a window
            # (the first processed block always holds each row's diagonal),
            # so the unwindowed hot path pays nothing.
            p = jnp.where(keep, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = alpha[:, None] * acc + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # Skip K blocks entirely above the diagonal: the last contributing
        # block covers query position (qi+1)*bq - 1. A window also skips
        # blocks entirely left of the band.
        num_k_eff = ((qi + 1) * bq - 1) // block_k + 1
        start_k = _band_start_k(qi, bq, window, block_k)
        m, l, acc = jax.lax.fori_loop(start_k, num_k_eff, body,
                                      (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, num_k, body, (m0, l0, acc0))

    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse rides in an (8, lane)-tiled layout: Mosaic requires the last two
    # block dims divisible by (8, 128), so the per-row vector is broadcast
    # over 8 sublanes (read back as row 0).
    lse = jnp.where(l == 0, NEG_INF, m + jnp.log(l_safe))
    lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, bq))


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2): recompute p from (q, k, lse)
# ---------------------------------------------------------------------------

def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, seq_len: int, causal: bool,
                         scale: float, window: int | None = None):
    """Grid: (batch*heads, num_q_blocks). dq_i = scale * sum_j ds_ij k_j with
    ds = p * (dO·v^T - delta); delta = rowsum(dO * O)."""
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    q = q_ref[0]                                           # [BQ, D] (input
    do = do_ref[0]                                         # dtype for MXU)
    lse = lse_ref[0, 0]                                    # [BQ] (row 0 of
    delta = delta_ref[0, 0]                                # the 8-sublane tile)

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    def body(j, acc):
        k = k_ref[0, pl.dslice(j * block_k, block_k), :]
        v = v_ref[0, pl.dslice(j * block_k, block_k), :]
        s = scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse[:, None])                      # [BQ, BK] f32
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            p = jnp.where(band_keep(q_pos, k_pos, window), p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        return acc + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    num_k = seq_len // block_k
    if causal:
        num_k_eff = ((qi + 1) * bq - 1) // block_k + 1
        start_k = _band_start_k(qi, bq, window, block_k)
        acc = jax.lax.fori_loop(start_k, num_k_eff, body, acc0)
    else:
        acc = jax.lax.fori_loop(0, num_k, body, acc0)
    dq_ref[0] = acc.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, seq_len: int,
                          causal: bool, scale: float,
                          window: int | None = None):
    """Grid: (batch*heads, num_k_blocks). dv_j = sum_i p_ij dO_i;
    dk_j = scale * sum_i ds_ij q_i. Causal skips query blocks strictly above
    the diagonal (queries before this key block attend none of it); a
    window also skips query blocks past the band's lower edge."""
    ki = pl.program_id(1)
    bk = k_ref.shape[1]
    k = k_ref[0]                                           # [BK, D] (input
    v = v_ref[0]                                           # dtype for MXU)

    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
    d = k.shape[1]
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.dslice(i * block_q, block_q), :]
        do = do_ref[0, pl.dslice(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q)]
        delta = delta_ref[0, 0, pl.dslice(i * block_q, block_q)]
        s = scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        p = jnp.exp(s - lse[:, None])                      # [BQ, BK] f32
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            p = jnp.where(band_keep(q_pos, k_pos, window), p, 0.0)
        pc = p.astype(do.dtype)
        dv = dv + jnp.dot(pc.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(q.dtype)
        dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk, dv

    num_q = seq_len // block_q
    if causal:
        # First query block intersecting the diagonal for this key block.
        start_q = (ki * bk) // block_q
        if window is None:
            end_q = num_q
        else:
            # Last query that can see any key in this block attends the
            # block's last key ((ki+1)*bk - 1) from window - 1 positions
            # later.
            end_q = jnp.minimum(
                num_q, ((ki + 1) * bk - 1 + window - 1) // block_q + 1)
        dk, dv = jax.lax.fori_loop(start_q, end_q, body, (dk0, dv0))
    else:
        dk, dv = jax.lax.fori_loop(0, num_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# padding/layout plumbing shared by forward and backward
# ---------------------------------------------------------------------------

def _plan(t, d, causal, block_q, block_k, interpret):
    """Resolve (t_padded, d_padded, block_q, block_k, interpret)."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    t_pad = t
    if t % 128:
        if not causal and not interpret:
            raise ValueError(
                f"non-causal flash attention needs seq len divisible by 128 "
                f"on TPU (got {t}); pad inputs or use full_attention")
        if causal:
            t_pad = -(-t // 128) * 128

    def clamp(block: int) -> int:
        if not interpret:
            # On real TPUs the lse/delta tiles put the block on the lane
            # dim, so blocks must be multiples of 128 AND divide t_pad
            # (grid/loop counts floor silently otherwise). t_pad is a
            # multiple of 128 here, so search divisors in 128-lane units.
            m_units = t_pad // 128
            d_units = max(1, min(block // 128, m_units))
            while m_units % d_units:
                d_units -= 1
            return 128 * d_units
        # Interpret mode (tests): largest block <= requested that divides
        # t (halving preserves the power-of-two shape; bottoms out at 1).
        blk = min(block, t_pad)
        while t_pad % blk:
            blk //= 2
        return blk

    d_pad = max(128, d) if not interpret else d
    return t_pad, d_pad, clamp(block_q), clamp(block_k), interpret


def _pad_bhtd(x, t_pad, d_pad):
    """[B, T, H, D] -> [B*H, T_pad, D_pad]."""
    b, t, h, d = x.shape
    if t_pad != t or d_pad != d:
        x = jnp.pad(x, [(0, 0), (0, t_pad - t), (0, 0), (0, d_pad - d)])
    return x.transpose(0, 2, 1, 3).reshape(b * h, t_pad, d_pad)


def _unpad_bthd(x, b, h, t, d):
    """[B*H, T_pad, D_pad] -> [B, T, H, D]."""
    t_pad, d_pad = x.shape[1], x.shape[2]
    x = x.reshape(b, h, t_pad, d_pad).transpose(0, 2, 1, 3)
    return x[:, :t, :, :d]


def _flash_impl(q, k, v, causal, block_q, block_k, interpret, window=None):
    """Run the forward kernel; returns (o [B,T,H,D], lse [B*H, T_pad] f32)
    — lse stays in the padded flat layout for the backward (which re-tiles
    it to 8 sublanes alongside delta)."""
    b, t, h, d = q.shape
    t_pad, d_pad, bq, bk, interp = _plan(t, d, causal, block_q, block_k,
                                         interpret)
    scale = d ** -0.5
    qf, kf, vf = (_pad_bhtd(x, t_pad, d_pad) for x in (q, k, v))
    kernel = functools.partial(_flash_kernel, block_k=bk, seq_len=t_pad,
                               causal=causal, scale=scale, window=window)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t_pad // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d_pad), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, t_pad, d_pad), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, t_pad, d_pad), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d_pad), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda bh, i: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_pad, d_pad), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, t_pad), jnp.float32),
        ],
        interpret=interp,
    )(qf, kf, vf)
    # Keep only sublane row 0 as the residual (the 8 rows are identical
    # copies written for tile legality) — 1x, not 8x, memory per layer.
    return _unpad_bthd(o, b, h, t, d), lse[:, 0, :]


def _flash_bwd_impl(q, k, v, o, lse, g, causal, block_q, block_k, interpret,
                    window=None):
    """Pallas backward: dq/dk/dv with [T, T] never in HBM."""
    b, t, h, d = q.shape
    t_pad, d_pad, bq, bk, interp = _plan(t, d, causal, block_q, block_k,
                                         interpret)
    scale = d ** -0.5
    # delta = rowsum(dO * O) — tiny elementwise pass in plain XLA. Padded
    # rows get delta 0 and g 0, so they contribute nothing below. Tiled to
    # 8 sublanes like lse (Mosaic block-layout requirement).
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = delta.transpose(0, 2, 1).reshape(b * h, t)
    if t_pad != t:
        delta = jnp.pad(delta, [(0, 0), (0, t_pad - t)])
    delta = jnp.broadcast_to(delta[:, None, :], (b * h, 8, t_pad))
    lse = jnp.broadcast_to(lse[:, None, :], (b * h, 8, t_pad))
    qf, kf, vf, gf = (_pad_bhtd(x, t_pad, d_pad) for x in (q, k, v, g))

    common = dict(seq_len=t_pad, causal=causal, scale=scale, window=window)
    row_spec = pl.BlockSpec((1, t_pad, d_pad), lambda bh, i: (bh, 0, 0))
    vec_spec = pl.BlockSpec((1, 8, t_pad), lambda bh, i: (bh, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=bk, **common),
        grid=(b * h, t_pad // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d_pad), lambda bh, i: (bh, i, 0)),
            row_spec, row_spec,
            # dO is per-query-row: blocked like q, not full-T.
            pl.BlockSpec((1, bq, d_pad), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda bh, i: (bh, 0, i)),
            pl.BlockSpec((1, 8, bq), lambda bh, i: (bh, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, d_pad), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t_pad, d_pad), q.dtype),
        interpret=interp,
    )(qf, kf, vf, gf, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=bq, **common),
        grid=(b * h, t_pad // bk),
        in_specs=[
            row_spec,
            pl.BlockSpec((1, bk, d_pad), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, bk, d_pad), lambda bh, i: (bh, i, 0)),
            row_spec, vec_spec, vec_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d_pad), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, bk, d_pad), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_pad, d_pad), k.dtype),
            jax.ShapeDtypeStruct((b * h, t_pad, d_pad), v.dtype),
        ],
        interpret=interp,
    )(qf, kf, vf, gf, lse, delta)

    return (_unpad_bthd(dq, b, h, t, d), _unpad_bthd(dk, b, h, t, d),
            _unpad_bthd(dv, b, h, t, d))


# ---------------------------------------------------------------------------
# public differentiable entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, block_q, block_k, interpret, bwd_impl, window):
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret,
                       window)[0]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, bwd_impl, window):
    o, lse = _flash_impl(q, k, v, causal, block_q, block_k, interpret, window)
    if bwd_impl == "xla":
        # The XLA-recompute backward reads only (q, k, v); don't hold the
        # output and lse in residual HBM for nothing.
        return o, (q, k, v, None, None)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, bwd_impl, window, res, g):
    """Backward dispatch: the pallas FlashAttention-2 kernels by default
    (no [T, T] in HBM), or the XLA recompute formulation (``bwd_impl="xla"``,
    materializes scores — the pre-kernel behavior, kept as an escape hatch).
    Both are parity-pinned in tests/test_pallas_attention.py."""
    q, k, v, o, lse = res
    if bwd_impl == "xla":
        from distributed_model_parallel_tpu.ops.ring_attention import (
            full_attention,
        )

        _, vjp = jax.vjp(
            lambda q, k, v: full_attention(q, k, v, causal=causal), q, k, v)
        return vjp(g)
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, block_q, block_k,
                           interpret, window)


_flash.defvjp(_flash_fwd, _flash_bwd)


# Flash-vs-XLA dispatch table, keyed by device_kind prefix. Values are
# measured, not guessed — benchmarks/dispatch_sweep.json holds the v5e
# sweep rows each entry was derived from (benchmarks/run_sweep.py across
# seq/dtype/head_dim). Unlisted TPU generations inherit the "tpu" row
# (same MXU/VMEM architecture; re-sweep to specialize); non-TPU platforms
# never auto-select flash — pallas interpret mode is orders of magnitude
# slower than XLA's fused attention.
#
# min_seq: crossover sequence length per compute dtype; None = never
#   auto-select for that dtype. bf16 head-dim 64: flash wins from 2048
#   (3.4x) and 10x at 4096; head-dim 128 crosses earlier (1024) but 2048
#   is kept as the single safe threshold. float32 is None NOT for speed —
#   the kernel's MXU passes accumulate at bf16-input precision (measured
#   ~8e-3 abs error on unit-scale f32 inputs vs true-f32 XLA attention,
#   i.e. bf16-class), so auto-dispatch would silently degrade f32
#   attention; forcing attn_impl="flash" remains available and documented.
# block_q/block_k: fastest measured tile shape (clamped to seq at call
#   time; 512x1024 measured ~6x over 128x128 at seq 2-4k on v5e).
# max_head_dim: the kernel keeps [block, D] tiles resident in VMEM; above
#   this, tiles spill and XLA wins regardless of seq.
_DISPATCH_TABLE: dict[str, dict] = {
    "TPU v5 lite": {"min_seq": {"bfloat16": 2048, "float32": None},
                    "block_q": 512, "block_k": 1024, "max_head_dim": 256},
    "tpu": {"min_seq": {"bfloat16": 2048, "float32": None},
            "block_q": 512, "block_k": 1024, "max_head_dim": 256},
}


def dispatch_entry(device=None) -> dict | None:
    """The dispatch-table row for ``device`` (default ``jax.devices()[0]``);
    None on non-TPU platforms, the generic "tpu" row for unlisted TPUs."""
    from distributed_model_parallel_tpu.utils.profiling import (
        match_device_kind,
    )

    device = device if device is not None else jax.devices()[0]
    if device.platform != "tpu":
        return None
    specific = {k: v for k, v in _DISPATCH_TABLE.items() if k != "tpu"}
    return (match_device_kind(specific, device)
            or _DISPATCH_TABLE["tpu"])


def default_blocks(device=None) -> tuple[int, int]:
    """Per-platform (block_q, block_k) kernel tile defaults (the kernel
    itself clamps them to the actual sequence length)."""
    entry = dispatch_entry(device) or _DISPATCH_TABLE["tpu"]
    return entry["block_q"], entry["block_k"]


def should_use_flash(t: int, *, causal: bool = True, impl: str = "auto",
                     head_dim: int = 64, dtype=None,
                     device=None) -> bool:
    """Single home for the flash-vs-XLA dispatch heuristic (used by
    models/transformer and ops/ring_attention): "flash"/"xla" force an
    implementation; "auto" consults the per-platform dispatch table —
    sequence-length crossover by compute dtype, and a head-dim cap above
    which the kernel's VMEM tiles spill."""
    if impl == "flash":
        return True
    if impl == "xla":
        return False
    if impl != "auto":
        raise ValueError(f"unknown attn impl {impl!r}; known: auto, xla, flash")
    if not causal:
        return False
    entry = dispatch_entry(device)
    if entry is None:
        return False
    if head_dim > entry["max_head_dim"]:
        return False
    dtype_name = jnp.dtype(dtype).name if dtype is not None else "bfloat16"
    # Unlisted dtypes (e.g. float64 under x64) stay on XLA: the kernel
    # computes at bf16-input precision, so only dtypes with an explicit
    # measured entry may auto-select it.
    min_seq = entry["min_seq"].get(dtype_name)
    if min_seq is None:
        return False
    return t >= min_seq


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool | None = None,
                    bwd_impl: str = "flash",
                    window: int | None = None) -> jax.Array:
    """[B, T, H, D] -> [B, T, H, D] causal attention, pallas-blocked.

    ``interpret=None`` auto-selects interpret mode off-TPU. Default block
    sizes (``block_q``/``block_k`` = None) come from the per-platform
    dispatch table (``dispatch_entry``; on v5e 512x1024, measured ~6x
    faster than 128x128 at seq 2-4k: 63 vs 9 TFLOPS at seq 2048; blocks
    clamp to the sequence length for short inputs). Beats plain XLA
    attention from seq ~2048 up, and still compiles at seq 8192 where the
    materialized T^2 score tensor makes XLA fail.

    Single-chip sequence ceiling: the backward's dk/dv accumulators are
    held full-T in VMEM per (batch, head) program, which exceeds the v5e's
    16 MB scoped VMEM around T=16384 (measured: 19.5 MB requested). Longer
    sequences on one chip need the FlashAttention-2 k-block grid for dk/dv
    (one program per key block, looping query blocks — planned rework);
    today the supported long-context route past 8k is sequence parallelism
    over the ``seq`` mesh axis (ops/ring_attention.py), which shards T
    before the kernel runs.

    ``window=W`` (causal only) restricts each query to the last W keys —
    sliding-window/local attention. Both directions skip blocks entirely
    outside the band, so compute drops from O(T^2) toward O(T*W).

    Differentiable via a custom VJP: the FlashAttention-2 backward kernels
    recompute score tiles from the saved logsumexp, so neither direction
    puts [T, T] in HBM; ``bwd_impl="xla"`` selects the old
    recompute-with-XLA backward instead (full/causal only — it has no
    windowed reference formulation).
    """
    if bwd_impl not in ("flash", "xla"):
        raise ValueError(f"unknown bwd_impl {bwd_impl!r}; known: flash, xla")
    if block_q is None or block_k is None:
        dq, dk = default_blocks()
        block_q = block_q if block_q is not None else dq
        block_k = block_k if block_k is not None else dk
    if window is not None:
        if not causal:
            raise ValueError("window requires causal attention")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if bwd_impl == "xla":
            raise ValueError("window is only supported with bwd_impl='flash'")
    return _flash(q, k, v, causal, block_q, block_k, interpret, bwd_impl,
                  window)
