"""Fused SGD optimizer update as a single Pallas TPU kernel.

The optax path (train/optim.py) lowers the reference recipe —
``add_decayed_weights`` → momentum ``trace`` → ``scale_by_learning_rate``
— to a chain of per-leaf elementwise HLO ops: for a CNN with ~160
parameter leaves that is ~500 tiny kernels per step, each reading and
writing its operands through HBM. kernel_profile_r4.json shows the CNN
step is bandwidth-bound, so every avoided HBM round trip is wall time.

This module fuses the whole update into ONE elementwise Pallas kernel per
flat parameter bucket (``ops/collectives.plan_buckets`` — the same
reverse-leaf-order size-capped coalescing the DDP Reducer uses for its
allreduce): params, momentum and gradients stream through VMEM in
(rows, 128)-lane blocks, the VPU applies

    g'     = g + weight_decay * p
    m'     = momentum * m + g'
    delta  = -lr * (g' + momentum * m')   (nesterov)
           | -lr * m'                     (classic)

and each value makes exactly one HBM round trip. The momentum buffer
aliases its output (``input_output_aliases``) so it updates in place.

Exposed as an ``optax.GradientTransformation`` (``fused_sgd``) so it
drops into every trainer through ``make_optimizer`` — selectable via
``OptimizerConfig(fused=True)``. The LR schedule stays a host closure
over the on-device step count, so recovery-time lr_shrink rebuilds
(train/resilience.py) keep the opt_state structure, exactly like the
optax path. Off-TPU the same bucket math runs as pure XLA (fallback) —
and the kernel itself runs under the pallas interpreter for CPU parity
tests, the ``ops/pallas_attention.py`` idiom.

Parity: bit-identical to the optax chain for float32 trees on the
fallback path, and elementwise-equal within float32 rounding on the
kernel path (tests/test_pallas_optim.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_model_parallel_tpu.ops.collectives import plan_buckets

_LANES = 128            # TPU lane width: flat buckets reshape to (rows, 128)
_BLOCK_ROWS = 512       # rows per grid step: 512*128*4B = 256 KiB per operand


class FusedSGDState(NamedTuple):
    """Optimizer state: applied-update count (drives the LR schedule,
    like optax's ScaleByScheduleState) + the momentum buffer (params-like
    f32, like optax's TraceState; ``None`` when momentum is 0.0 — plain
    SGD carries no trace, matching the optax path's memory footprint)."""

    count: jnp.ndarray
    momentum: Any


def _fused_sgd_kernel(lr_ref, p_ref, m_ref, g_ref, d_ref, om_ref, *,
                      momentum: float, weight_decay: float, nesterov: bool):
    """One (BLOCK_ROWS, LANES) f32 tile of the fused update (momentum
    variant). Outputs: the update delta (added to params by
    ``optax.apply_updates``) and the new momentum (aliased over the old
    one, so it never leaves HBM twice)."""
    lr = lr_ref[0]
    g = g_ref[...]
    if weight_decay:
        g = g + weight_decay * p_ref[...]
    m = momentum * m_ref[...] + g
    om_ref[...] = m
    d = g + momentum * m if nesterov else m
    d_ref[...] = -lr * d


def _plain_sgd_kernel(lr_ref, p_ref, g_ref, d_ref, *,
                      weight_decay: float):
    """Momentum-free tile: no trace buffer exists at all (plain SGD
    carries no state beyond the count, like optax)."""
    g = g_ref[...]
    if weight_decay:
        g = g + weight_decay * p_ref[...]
    d_ref[...] = -lr_ref[0] * g


def _run_kernel(lr, p_flat, m_flat, g_flat, *, momentum, weight_decay,
                nesterov, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = p_flat.size
    # Pad the flat bucket so it reshapes to (rows, 128) with rows an exact
    # multiple of the block height (itself a multiple of the 8-sublane f32
    # tile) — no ragged last grid step.
    rows0 = -(-n // _LANES)
    block_rows = min(_BLOCK_ROWS, -(-rows0 // 8) * 8)
    rows = -(-rows0 // block_rows) * block_rows
    pad = rows * _LANES - n
    shape2d = (rows, _LANES)
    grid = (rows // block_rows,)

    def pad2d(x):
        return jnp.pad(x, (0, pad)).reshape(shape2d)

    block = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    scalar = pl.BlockSpec(memory_space=pltpu.SMEM)
    out2d = jax.ShapeDtypeStruct(shape2d, jnp.float32)
    lr_arr = jnp.asarray([lr], jnp.float32)
    if m_flat is None:
        delta = pl.pallas_call(
            partial(_plain_sgd_kernel, weight_decay=weight_decay),
            grid=grid,
            in_specs=[scalar, block, block],
            out_specs=block,
            out_shape=out2d,
            # the gradient buffer (dead after this kernel) aliases the
            # delta output.
            input_output_aliases={2: 0},
            interpret=interpret,
        )(lr_arr, pad2d(p_flat), pad2d(g_flat))
        return delta.reshape(-1)[:n], None
    out = pl.pallas_call(
        partial(_fused_sgd_kernel, momentum=momentum,
                weight_decay=weight_decay, nesterov=nesterov),
        grid=grid,
        in_specs=[scalar, block, block, block],
        out_specs=[block, block],
        out_shape=[out2d, out2d],
        # momentum-in aliases momentum-out; the gradient buffer (dead
        # after this kernel) aliases the delta.
        input_output_aliases={3: 0, 2: 1},
        interpret=interpret,
    )(lr_arr, pad2d(p_flat), pad2d(m_flat), pad2d(g_flat))
    delta, new_m = (x.reshape(-1)[:n] for x in out)
    return delta, new_m


def _run_xla(lr, p_flat, m_flat, g_flat, *, momentum, weight_decay,
             nesterov):
    """Pure-XLA fallback: the same flat-bucket math, same operation order
    as the kernel (and as the optax chain — bitwise parity on f32).
    ``m_flat`` is None iff momentum is 0.0 (no trace state)."""
    g = g_flat + weight_decay * p_flat if weight_decay else g_flat
    if m_flat is None:
        return -lr * g, None
    m = momentum * m_flat + g
    d = g + momentum * m if nesterov else m
    return -lr * d, m


def fused_sgd(learning_rate: Union[float, Callable], *,
              momentum: float = 0.0, weight_decay: float = 0.0,
              nesterov: bool = False,
              bucket_bytes: int = 64 * 1024 * 1024,
              use_pallas: bool | None = None
              ) -> optax.GradientTransformation:
    """SGD + momentum + weight decay + LR scaling as one fused kernel over
    flat parameter buckets — the drop-in equivalent of
    ``optax.chain(add_decayed_weights(wd), sgd(lr, momentum, nesterov))``.

    ``learning_rate`` may be a float or a schedule (called with the
    applied-update count, like optax). ``use_pallas``: None = auto (the
    kernel on TPU, the pure-XLA flat-bucket fallback elsewhere); True
    forces the kernel (interpret mode off-TPU — slow, for parity tests);
    False forces the fallback. Buckets are ``plan_buckets`` groups, so
    the coalescing matches the DDP bucketed allreduce's layout.

    Non-f32 leaves are updated in f32 and cast back to the leaf dtype on
    write-out (the f32-master-weights convention); the momentum buffer is
    always f32.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    interpret = jax.default_backend() != "tpu"

    has_momentum = bool(momentum)

    def init_fn(params):
        return FusedSGDState(
            count=jnp.zeros((), jnp.int32),
            # Plain SGD carries no trace — don't allocate (and round-trip
            # through HBM) a params-sized buffer that is always zero.
            momentum=(jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
                if has_momentum else None))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("fused_sgd needs params (weight decay + the "
                             "fused write-back read them)")
        lr = (learning_rate(state.count) if callable(learning_rate)
              else learning_rate)
        lr = jnp.asarray(lr, jnp.float32)
        g_leaves, treedef = jax.tree.flatten(updates)
        p_leaves = treedef.flatten_up_to(params)
        m_leaves = (treedef.flatten_up_to(state.momentum)
                    if has_momentum else None)
        out_d: list = [None] * len(g_leaves)
        out_m: list = [None] * len(g_leaves)
        run = (partial(_run_kernel, interpret=interpret) if use_pallas
               else _run_xla)
        for bucket in plan_buckets(updates, bucket_bytes):
            sizes = [g_leaves[i].size for i in bucket]
            p_flat = jnp.concatenate(
                [p_leaves[i].astype(jnp.float32).reshape(-1)
                 for i in bucket])
            m_flat = (jnp.concatenate(
                [m_leaves[i].reshape(-1) for i in bucket])
                if has_momentum else None)
            g_flat = jnp.concatenate(
                [g_leaves[i].astype(jnp.float32).reshape(-1)
                 for i in bucket])
            delta, new_m = run(lr, p_flat, m_flat, g_flat,
                               momentum=momentum,
                               weight_decay=weight_decay,
                               nesterov=nesterov)
            off = 0
            for i, size in zip(bucket, sizes):
                out_d[i] = delta[off:off + size].reshape(
                    g_leaves[i].shape).astype(p_leaves[i].dtype)
                if has_momentum:
                    out_m[i] = new_m[off:off + size].reshape(
                        g_leaves[i].shape)
                off += size
        return (jax.tree.unflatten(treedef, out_d),
                FusedSGDState(count=optax.safe_int32_increment(state.count),
                              momentum=(jax.tree.unflatten(treedef, out_m)
                                        if has_momentum else None)))

    return optax.GradientTransformation(init_fn, update_fn)
