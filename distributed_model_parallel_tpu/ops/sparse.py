"""Sparse-gradient embedding path (BASELINE.json config 5).

PyTorch's ``nn.Embedding(sparse=True)`` produces COO gradients that DDP
allreduces by exchanging (indices, values) pairs. JAX autodiff produces dense
gradients, and a dense allreduce of a large vocab table per step wastes HBM
bandwidth on rows no one touched. The TPU-native equivalent keeps the wire
format sparse with static shapes:

* the embedding grad for a batch of tokens IS (tokens, d_out) — no
  densification ever happens: ``embedding_grad_sparse`` just reshapes;
* cross-replica reduction = ``all_gather`` of the (ids, values) pairs over
  the data axis (exactly what DDP's sparse allreduce does — concatenation,
  not summation, with duplicates resolved at apply time);
* ``apply_sparse_grad`` folds the COO update into the table with one
  scatter-add (``.at[ids].add``), which XLA lowers to an efficient
  on-chip scatter; duplicate ids accumulate correctly.

All shapes are static (N = batch x seq tokens), so everything jits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def embedding_lookup(table: jax.Array, tokens: jax.Array) -> jax.Array:
    """[V, d] x [B, T] -> [B, T, d]."""
    return table[tokens]


def embedding_grad_sparse(tokens: jax.Array, d_out: jax.Array
                          ) -> tuple[jax.Array, jax.Array]:
    """COO gradient of ``embedding_lookup`` w.r.t. the table.

    tokens: [B, T] int ids; d_out: [B, T, d] cotangent.
    Returns (ids [N], values [N, d]) with N = B*T (duplicates kept).
    """
    ids = tokens.reshape(-1)
    vals = d_out.reshape(ids.shape[0], -1)
    return ids, vals


def sparse_allreduce(ids: jax.Array, vals: jax.Array, axis_name: str
                     ) -> tuple[jax.Array, jax.Array]:
    """DDP-style sparse gradient exchange: concatenate every replica's COO
    pairs (all_gather over the data axis). Values are pre-scaled by 1/world
    so the result is the mean gradient."""
    n = jax.lax.psum(1, axis_name)
    ids = jax.lax.all_gather(ids, axis_name, axis=0, tiled=True)
    vals = jax.lax.all_gather(vals / n, axis_name, axis=0, tiled=True)
    return ids, vals


def apply_sparse_grad(table: jax.Array, ids: jax.Array, vals: jax.Array,
                      scale: float | jax.Array = 1.0) -> jax.Array:
    """table <- table - scale * scatter_add(COO). One fused XLA scatter."""
    return table.at[ids].add(-scale * vals.astype(table.dtype))


def densify(ids: jax.Array, vals: jax.Array, num_rows: int) -> jax.Array:
    """COO -> dense [V, d] (for parity tests against dense autodiff)."""
    out = jnp.zeros((num_rows, vals.shape[-1]), vals.dtype)
    return out.at[ids].add(vals)
