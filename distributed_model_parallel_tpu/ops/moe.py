"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

Beyond the reference (SURVEY.md §2.3 lists EP as absent) but part of this
framework's first-class parallelism set: top-k token routing — top-1
(switch-style, raw gate) or top-2+ (GShard-style, gates normalized over the
selected experts) — with static capacity, experts sharded
one-per-device-group over the ``expert`` axis, and token exchange via
``all_to_all`` — the TPU-native form of expert dispatch (dense einsum
dispatch/combine against one-hot capacity masks, so everything is
static-shaped MXU work; dropped tokens pass through on the residual path).

Shapes (inside shard_map over the expert axis):
  x_local:        [B_local, T, d]   tokens on this device group
  expert params:  [E_local, ...]    experts owned by this group
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 4
    d_model: int = 64
    d_ff: int = 128
    capacity_factor: float = 2.0
    top_k: int = 1
    # Only consulted for top_k > 1: renormalize the selected experts' gates to
    # sum to 1 (GShard). top-1 always uses the raw softmax prob (Switch).
    normalize_gates: bool = True

    def __post_init__(self):
        if not (1 <= self.top_k <= self.num_experts):
            raise ValueError(
                f"top_k={self.top_k} must be in [1, num_experts="
                f"{self.num_experts}]")


def init_moe_params(rng: jax.Array, cfg: MoEConfig) -> dict:
    k1, k2, k3 = jax.random.split(rng, 3)
    E, d, f = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "router": jax.random.normal(k1, (d, E)) * (d ** -0.5),
        "w_in": jax.random.normal(k2, (E, d, f)) * (d ** -0.5),
        "w_out": jax.random.normal(k3, (E, f, d)) * (f ** -0.5),
    }


def _route(router, x, cfg: MoEConfig):
    """Top-k routing with per-expert capacity.

    Returns (dispatch [N, E, C] one-hot, combine [N, E, C] weighted,
    stats [3] f32) for N flattened tokens, where stats is

    * ``[0]`` load-balance loss (Switch/GShard first-choice form),
    * ``[1]`` router z-loss — mean squared logsumexp of the router
      logits, the logit-drift regularizer (ST-MoE); weighted into the
      training loss by ``TransformerConfig.moe_z_weight``,
    * ``[2]`` drop rate — the fraction of the N*k token-choices whose
      expert queue was already at capacity (``pos >= cap``); those
      choices ride the residual path. A metric, not a loss term: it is
      piecewise-constant in the params (zero gradient), and surfacing it
      is what turns silent capacity overflow into an observable.

    Choice j's queue positions are offset by all earlier choices'
    assignments (GShard ordering), so a token's second choice never
    collides with first-choice traffic.
    """
    n = x.shape[0]
    E = cfg.num_experts
    k = cfg.top_k
    # Capacity scales with k (GShard): each token makes k assignments, so
    # holding capacity_factor fixed keeps the drop rate constant across k.
    cap = max(1, int(cfg.capacity_factor * k * n / E))
    logits = x @ router                               # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gates, experts = jax.lax.top_k(probs, k)          # [N, k] each
    if k > 1 and cfg.normalize_gates:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)

    dispatch = jnp.zeros((n, E, cap), x.dtype)
    combine = jnp.zeros((n, E, cap), x.dtype)
    counts = jnp.zeros((E,), x.dtype)                 # queue heads per expert
    kept = jnp.zeros((), jnp.float32)
    for j in range(k):                                # k is static (config)
        onehot = jax.nn.one_hot(experts[:, j], E)     # [N, E]
        # Position of each token within its expert's queue, past all
        # choice-<j traffic.
        pos = (jnp.cumsum(onehot, axis=0) - 1.0 + counts) * onehot
        keep = (pos < cap) * onehot                   # drop overflow
        posk = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)   # [N]
        d_j = keep[:, :, None] * jax.nn.one_hot(posk, cap)[:, None, :]
        dispatch = dispatch + d_j
        combine = combine + d_j * gates[:, j][:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)
        kept = kept + jnp.sum(keep).astype(jnp.float32)

    # Load-balancing loss over first-choice assignment fractions
    # (Switch/GShard form).
    first_choice = jax.nn.one_hot(experts[:, 0], E)
    frac_tokens = jnp.mean(first_choice, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    balance = E * jnp.sum(frac_tokens * frac_probs)
    z = jnp.mean(jax.nn.logsumexp(logits.astype(jnp.float32),
                                  axis=-1) ** 2)
    drop_rate = 1.0 - kept / (n * k)
    stats = jnp.stack([balance.astype(jnp.float32), z,
                       jax.lax.stop_gradient(drop_rate)])
    return dispatch, combine, stats


def moe_ffn(params: dict, x: jax.Array, cfg: MoEConfig,
            ep_axis: str | None = None) -> tuple[jax.Array, jax.Array]:
    """MoE FFN on [B, T, d]. Returns ``(y, stats)`` where stats is the
    ``[balance_loss, z_loss, drop_rate]`` f32 vector from :func:`_route`.

    Without ``ep_axis``: all experts local (dense dispatch einsums).
    With ``ep_axis`` (inside shard_map): params arrive expert-sharded
    [E_local, ...]; expert inputs are exchanged with ``all_to_all`` so each
    device group runs only its own experts, then results return the same way.
    """
    b, t, d = x.shape
    xf = x.reshape(-1, d)                             # [N, d]
    dispatch, combine, aux = _route(params["router"], xf, cfg)

    # expert_in[e, c, :] = sum_n dispatch[n,e,c] * x[n]
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xf)

    if ep_axis is not None:
        ep = jax.lax.axis_size(ep_axis)
        e_local = params["w_in"].shape[0]             # E / ep
        # [E, C, d] -> exchange so this device holds its experts' tokens from
        # ALL groups (tiled: split expert axis by ep, concat source-major on
        # the capacity axis): -> [E_local, ep*C, d].
        expert_in = jax.lax.all_to_all(
            expert_in, ep_axis, split_axis=0, concat_axis=1, tiled=True)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"]))
        expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
        # Inverse exchange: [E_local, ep*C, d] -> [ep*E_local, C, d], chunks
        # source-major on axis 0 == global expert order.
        expert_out = jax.lax.all_to_all(
            expert_out, ep_axis, split_axis=1, concat_axis=0, tiled=True)
    else:
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"]))
        expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])

    y = jnp.einsum("nec,ecd->nd", combine, expert_out)
    # The one-hot routing masks are f32 (softmax-derived), which promotes
    # the combine einsum; cast back so a bf16 residual stream stays bf16
    # (a f32-promoted carry breaks the blocks lax.scan under mixed
    # precision — surfaced by the bf16 MoE bench).
    return y.reshape(b, t, d).astype(x.dtype), aux
