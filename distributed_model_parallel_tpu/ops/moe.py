"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

Beyond the reference (SURVEY.md §2.3 lists EP as absent) but part of this
framework's first-class parallelism set: top-k token routing — top-1
(switch-style, raw gate) or top-2+ (GShard-style, gates normalized over the
selected experts) — with static capacity, experts sharded
one-per-device-group over the ``expert`` axis, and token exchange via
``all_to_all`` — the TPU-native form of expert dispatch: static-shaped
scatter/gather against per-choice queue-slot indices (round 5; the one-hot
einsum masks used through round 4 cost N*E*C*d MAC per layer — orders of
magnitude more than the experts themselves at bench shapes). Dropped
tokens pass through on the residual path.

Shapes (inside shard_map over the expert axis):
  x_local:        [B_local, T, d]   tokens on this device group
  expert params:  [E_local, ...]    experts owned by this group
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 4
    d_model: int = 64
    d_ff: int = 128
    capacity_factor: float = 2.0
    top_k: int = 1
    # Only consulted for top_k > 1: renormalize the selected experts' gates to
    # sum to 1 (GShard). top-1 always uses the raw softmax prob (Switch).
    normalize_gates: bool = True

    def __post_init__(self):
        if not (1 <= self.top_k <= self.num_experts):
            raise ValueError(
                f"top_k={self.top_k} must be in [1, num_experts="
                f"{self.num_experts}]")


def init_moe_params(rng: jax.Array, cfg: MoEConfig) -> dict:
    k1, k2, k3 = jax.random.split(rng, 3)
    E, d, f = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "router": jax.random.normal(k1, (d, E)) * (d ** -0.5),
        "w_in": jax.random.normal(k2, (E, d, f)) * (d ** -0.5),
        "w_out": jax.random.normal(k3, (E, f, d)) * (f ** -0.5),
    }


def _route(router, x, cfg: MoEConfig):
    """Top-k routing with per-expert capacity, in INDEX form.

    Returns ``(experts [N,k] i32, gates [N,k], slot [N,k] i32,
    keep [N,k] bool, cap, stats [3] f32)`` for N flattened tokens:
    ``slot[n,j] = experts[n,j] * cap + queue position`` — each kept
    token-choice owns a unique slot in the [E*cap] expert-queue space,
    which is what lets dispatch/combine be gathers instead of the
    [N, E, C] one-hot einsums this module used through round 4 (those
    masks cost N*E*C*d MAC/layer — ~2 PFLOP at the bench shape, >100x
    the expert FFN math itself; the index form is pure data movement).

    ``stats`` is

    * ``[0]`` load-balance loss (Switch/GShard first-choice form),
    * ``[1]`` router z-loss — mean squared logsumexp of the router
      logits, the logit-drift regularizer (ST-MoE); weighted into the
      training loss by ``TransformerConfig.moe_z_weight``,
    * ``[2]`` drop rate — the fraction of the N*k token-choices whose
      expert queue was already at capacity (``pos >= cap``); those
      choices ride the residual path. A metric, not a loss term: it is
      piecewise-constant in the params (zero gradient), and surfacing it
      is what turns silent capacity overflow into an observable.

    Choice j's queue positions are offset by all earlier choices'
    assignments (GShard ordering), so a token's second choice never
    collides with first-choice traffic.
    """
    n = x.shape[0]
    E = cfg.num_experts
    k = cfg.top_k
    # Capacity scales with k (GShard): each token makes k assignments, so
    # holding capacity_factor fixed keeps the drop rate constant across k.
    cap = max(1, int(cfg.capacity_factor * k * n / E))
    logits = x @ router                               # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gates, experts = jax.lax.top_k(probs, k)          # [N, k] each
    if k > 1 and cfg.normalize_gates:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)

    counts = jnp.zeros((E,), jnp.int32)               # queue heads per expert
    slots, keeps = [], []
    for j in range(k):                                # k is static (config)
        e_j = experts[:, j]                           # [N]
        onehot = jax.nn.one_hot(e_j, E, dtype=jnp.int32)
        # Position of each token within its expert's queue, past all
        # choice-<j traffic.
        pos_all = jnp.cumsum(onehot, axis=0) - 1 + counts       # [N, E]
        pos = jnp.take_along_axis(pos_all, e_j[:, None], axis=1)[:, 0]
        keep = pos < cap
        slots.append(e_j * cap + jnp.minimum(pos, cap - 1))
        keeps.append(keep)
        counts = counts + jnp.sum(onehot, axis=0)
    slot = jnp.stack(slots, axis=1)                   # [N, k]
    keep = jnp.stack(keeps, axis=1)                   # [N, k]

    # Load-balancing loss over first-choice assignment fractions
    # (Switch/GShard form).
    first_choice = jax.nn.one_hot(experts[:, 0], E)
    frac_tokens = jnp.mean(first_choice, axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    balance = E * jnp.sum(frac_tokens * frac_probs)
    z = jnp.mean(jax.nn.logsumexp(logits.astype(jnp.float32),
                                  axis=-1) ** 2)
    drop_rate = 1.0 - jnp.sum(keep.astype(jnp.float32)) / (n * k)
    stats = jnp.stack([balance.astype(jnp.float32), z,
                       jax.lax.stop_gradient(drop_rate)])
    return experts, gates, slot, keep, cap, stats


def moe_ffn(params: dict, x: jax.Array, cfg: MoEConfig,
            ep_axis: str | None = None) -> tuple[jax.Array, jax.Array]:
    """MoE FFN on [B, T, d]. Returns ``(y, stats)`` where stats is the
    ``[balance_loss, z_loss, drop_rate]`` f32 vector from :func:`_route`.

    Without ``ep_axis``: all experts local (dense dispatch einsums).
    With ``ep_axis`` (inside shard_map): params arrive expert-sharded
    [E_local, ...]; expert inputs are exchanged with ``all_to_all`` so each
    device group runs only its own experts, then results return the same way.
    """
    b, t, d = x.shape
    n = b * t
    xf = x.reshape(-1, d)                             # [N, d]
    experts, gates, slot, keep, cap, aux = _route(params["router"], xf, cfg)
    E = cfg.num_experts

    # Dispatch as a scatter of token IDs into queue slots, then a gather:
    # kept slots are unique (queue positions), so .at[].set never collides;
    # dropped choices scatter to the out-of-bounds sentinel E*cap and are
    # dropped; unfilled slots keep token id N -> gather the zero pad row.
    token_ids = jnp.arange(n, dtype=jnp.int32)
    slot_token = jnp.full((E * cap,), n, jnp.int32)
    for j in range(cfg.top_k):
        idx = jnp.where(keep[:, j], slot[:, j], E * cap)
        slot_token = slot_token.at[idx].set(token_ids, mode="drop")
    xf_pad = jnp.concatenate([xf, jnp.zeros((1, d), xf.dtype)])
    expert_in = xf_pad[slot_token].reshape(E, cap, d)

    if ep_axis is not None:
        # [E, C, d] -> exchange so this device holds its experts' tokens from
        # ALL groups (tiled: split expert axis by ep, concat source-major on
        # the capacity axis): -> [E_local, ep*C, d].
        expert_in = jax.lax.all_to_all(
            expert_in, ep_axis, split_axis=0, concat_axis=1, tiled=True)
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"]))
        expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
        # Inverse exchange: [E_local, ep*C, d] -> [ep*E_local, C, d], chunks
        # source-major on axis 0 == global expert order.
        expert_out = jax.lax.all_to_all(
            expert_out, ep_axis, split_axis=1, concat_axis=0, tiled=True)
    else:
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"]))
        expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])

    # Combine: gather each kept choice's expert output back to its token,
    # weighted by the (differentiable) gate. Gate gradients flow exactly as
    # in the einsum form; the gathers transpose to scatter-adds under AD.
    out_flat = expert_out.reshape(E * cap, d)
    y = jnp.zeros((n, d), x.dtype)
    for j in range(cfg.top_k):
        w = jnp.where(keep[:, j], gates[:, j], 0).astype(x.dtype)
        y = y + w[:, None] * out_flat[slot[:, j]]
    # f32 expert params would promote the adds above; a bf16 residual
    # stream must come back bf16 (a promoted carry breaks the blocks
    # lax.scan under mixed precision).
    return y.reshape(b, t, d).astype(x.dtype), aux
