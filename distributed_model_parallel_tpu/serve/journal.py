"""Write-ahead request journal: crash-consistent serving state.

Every recovery path before this module rode a *graceful* drain — live
migration (PR 14) and cell kills (PR 17) both walk
``Engine.drain()`` and carry exported KV pages to the destination. A
hard crash (process gone, HBM gone) had only the ``EngineKilled``
contract: mark everything failed, lose the serving state with the
process. This journal is the serving tier's durability analogue of the
training tier's checkpoints, and the pinned determinism contract
(tokens = f(prompt, seed), asserted since PR 9) makes it nearly free:
durable *intent* plus a committed-token watermark reconstructs any
request bitwise — no KV export needed, the pages are recomputed by
re-prefilling prompt + committed tokens.

One JSONL journal per fleet, three record kinds (all carry ``ts``):

==========  ==========================================================
kind        payload keys
==========  ==========================================================
intent      rid, trace, prompt (token list), seed, max_new_tokens,
            priority, queue_budget_s, deadline_s, arrival_s — one per
            ACCEPTED request, written before the engine touches it
            and fsync'd (the durability boundary: an accepted request
            survives any later crash)
watermark   rid, tokens (committed token VALUES since the previous
            watermark), committed (running total) — flushed, not
            fsync'd: a lost tail only widens the deterministic replay,
            never loses a request
terminal    rid, outcome (completed | shed | failed) — exactly one per
            journaled request; recovery never re-serves a terminaled
            rid (exactly-once accounting, dedup by rid). Flushed at
            write, fsync'd in groups (``terminal_sync_every``, plus
            every intent fsync — fdatasync covers the whole file — and
            :meth:`~RequestJournal.close`)
==========  ==========================================================

Why terminals group-sync while intents fsync one by one: a flushed
record survives PROCESS death (it is in the page cache); only a host
crash can tear it off, and a terminal lost to a host crash is
reconstructed by the replay itself — the request re-completes with
bitwise-identical tokens and re-journals its terminal. The worst case
is duplicate delivery of an identical payload, never divergent
accounting, which is the standard group-commit trade (Postgres
``synchronous_commit=off``) made strictly safer by the determinism
contract. A lost INTENT, by contrast, silently cancels an accepted
request — that is why the admission path pays a per-record fsync and
the serve loop does not (the crashrecovery drill gates the serve-loop
journal overhead at < 3% of engine iteration time). Set
``terminal_sync_every=1`` for strict per-terminal fsync.

Rotation mirrors :class:`~..utils.telemetry.TelemetryRun` (live file
renamed to ``{stem}.N{ext}``); readers fold all parts through
``telemetry.read_records``, which skips a torn trailing line (a crash
mid-write) and counts it on ``telemetry_torn_lines`` — recovery
proceeds on the surviving prefix.

Reopening an existing journal path resumes its state (known intents,
terminals, committed counts) so dedup holds across a full fleet
restart, and :func:`fold` turns the on-disk records into the
:class:`JournalState` the recovery paths (``ServeFleet.crash_replica``
re-admission, ``ServeFleet.recover``) replay from.

A module-level :func:`install` registry (mirroring ``utils.health`` /
``utils.flightrec``) lets the crash flight recorder grab the installed
journal's tail for the postmortem bundle without plumbing.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

from distributed_model_parallel_tpu.utils.telemetry import (
    read_records,
    registry,
    stream_parts,
)

__all__ = [
    "JournalState",
    "RequestJournal",
    "TERMINAL_OUTCOMES",
    "fold",
    "install",
    "installed",
]

TERMINAL_OUTCOMES = ("completed", "shed", "failed")

# fsync-now boundary: an intent's loss silently cancels an accepted
# request, so it is the one kind that always pays a per-record fsync.
# Terminals group-sync (see the module docstring's trade-off note);
# watermarks are a replay optimization and may tear freely.
_DURABLE_KINDS = frozenset({"intent"})

# Intent fields copied verbatim from the Request at admission and back
# onto the reconstructed Request at recovery — the request identity,
# not its runtime state.
_INTENT_FIELDS = ("prompt", "seed", "max_new_tokens", "priority",
                  "queue_budget_s", "deadline_s", "arrival_s", "tenant")


@dataclasses.dataclass
class JournalState:
    """Folded view of a journal's records (:func:`fold`)."""

    intents: dict[str, dict]             # rid -> intent payload
    tokens: dict[str, list[int]]         # rid -> committed token values
    terminals: dict[str, str]            # rid -> outcome

    def pending(self) -> list[str]:
        """Rids recovery owes: journaled intent, no journaled terminal,
        in intent (acceptance) order — the deterministic replay order."""
        return [rid for rid in self.intents if rid not in self.terminals]


def fold(path: str) -> JournalState:
    """Fold a journal stream (all rotated parts, torn tail skipped via
    ``telemetry.read_records``) into a :class:`JournalState`."""
    state = JournalState(intents={}, tokens={}, terminals={})
    for rec in read_records(path):
        kind, rid = rec.get("kind"), rec.get("rid")
        if rid is None:
            continue
        if kind == "intent":
            state.intents.setdefault(rid, rec)
            state.tokens.setdefault(rid, [])
        elif kind == "watermark":
            toks = state.tokens.setdefault(rid, [])
            toks.extend(int(t) for t in rec.get("tokens", ()))
            want = rec.get("committed")
            if want is not None and len(toks) != int(want):
                raise ValueError(
                    f"journal {path}: watermark total for {rid!r} claims "
                    f"{want} committed tokens but the folded deltas give "
                    f"{len(toks)} — the stream is out of order or a "
                    f"NON-trailing record was lost")
        elif kind == "terminal":
            state.terminals.setdefault(rid, rec.get("outcome", "completed"))
    return state


class RequestJournal:
    """Append-only write-ahead journal for one serving fleet.

    ``watermark_every`` batches committed tokens: a request's watermark
    record is written once that many tokens accumulate since its last
    watermark (and on :meth:`flush_watermarks`). ``terminal_sync_every``
    group-commits terminal fsyncs (1 = strict per-terminal fsync; see
    the module docstring for why the default lag is safe). ``max_bytes``
    enables telemetry-style rotation. Reopening an existing path
    resumes its dedup state from disk.
    """

    def __init__(self, path: str, *, watermark_every: int = 8,
                 terminal_sync_every: int = 8,
                 max_bytes: int | None = None):
        if watermark_every < 1:
            raise ValueError(f"watermark_every must be >= 1, got "
                             f"{watermark_every}")
        if terminal_sync_every < 1:
            raise ValueError(f"terminal_sync_every must be >= 1, got "
                             f"{terminal_sync_every}")
        self.path = path
        self.watermark_every = int(watermark_every)
        self.terminal_sync_every = int(terminal_sync_every)
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._fh = None                 # persistent append handle
        self._intents: set[str] = set()
        self._terminals: set[str] = set()
        self._committed: dict[str, int] = {}    # rid -> journaled total
        self._pending: dict[str, list[int]] = {}  # rid -> unjournaled toks
        self._records = 0
        self._fsyncs = 0
        self._unsynced_terminals = 0
        # Cached metric handles: a registry lookup per record is
        # measurable on the serve loop's overhead budget.
        self._m_records = registry().counter("journal_records")
        self._m_fsyncs = registry().counter("journal_fsyncs")
        # Monotonic seconds spent inside record() — the overhead the
        # crashrecovery scenario gates at < 3% of serve iteration time.
        self.write_s = 0.0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if stream_parts(path):
            # fold FIRST (read_records counts a torn tail on
            # telemetry_torn_lines), then drop the torn partial line so
            # post-recovery appends start on a record boundary instead
            # of concatenating onto it.
            prior = fold(path)
            self._truncate_torn_tail()
            self._intents = set(prior.intents)
            self._terminals = set(prior.terminals)
            self._committed = {r: len(t) for r, t in prior.tokens.items()}

    def _truncate_torn_tail(self) -> None:
        """Truncate the live file back to its last complete line. Only
        the live file can tear mid-append (rotated parts are closed
        whole), and the torn record was never durable — dropping it is
        exactly what fold() already pretended happened."""
        try:
            with open(self.path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size == 0:
                    return
                f.seek(size - 1)
                if f.read(1) == b"\n":
                    return
                keep, pos, chunk = 0, size, 1 << 16
                while pos > 0:
                    step = min(chunk, pos)
                    f.seek(pos - step)
                    cut = f.read(step).rfind(b"\n")
                    if cut != -1:
                        keep = pos - step + cut + 1
                        break
                    pos -= step
                f.truncate(keep)
        except OSError:
            pass

    # -- writer -------------------------------------------------------------

    def record(self, kind: str, **payload) -> None:
        """Append one typed record; fsync-now for intents, group-sync
        for terminals, flush-only for watermarks."""
        t0 = time.monotonic()
        rec = {"ts": time.time(), "kind": kind, **payload}
        line = json.dumps(rec)
        synced = False
        with self._lock:
            self._maybe_rotate(len(line) + 1)
            # One persistent append handle (reopened across rotation):
            # an open() per record costs ~3x the fsync itself and blows
            # the < 3%-of-iteration-time overhead budget the
            # crashrecovery drill gates on.
            if self._fh is None:
                self._fh = open(self.path, "a")
            f = self._fh
            f.write(line + "\n")
            f.flush()
            if kind == "terminal":
                self._unsynced_terminals += 1
            if kind in _DURABLE_KINDS or (
                    self._unsynced_terminals >= self.terminal_sync_every):
                try:
                    # fdatasync: the data must be durable; the inode
                    # mtime may tear (cheaper on ext4, same recovery).
                    # One sync covers every earlier flushed record, so
                    # intent fsyncs retire pending terminals for free.
                    getattr(os, "fdatasync", os.fsync)(f.fileno())
                    self._fsyncs += 1
                    self._unsynced_terminals = 0
                    synced = True
                except OSError:
                    pass
            self._records += 1
        self._m_records.inc()
        if synced:
            self._m_fsyncs.inc()
        self.write_s += time.monotonic() - t0

    def _maybe_rotate(self, incoming: int) -> None:
        if self.max_bytes is None or not os.path.exists(self.path):
            return
        if os.path.getsize(self.path) + incoming <= self.max_bytes:
            return
        stem, ext = os.path.splitext(self.path)
        idx = len(stream_parts(self.path))   # live file -> next part index
        if self._fh is not None:
            # POSIX rename leaves an open fd pointing at the ROTATED
            # file; later appends must land in a fresh live file.
            self._fh.close()
            self._fh = None
        os.replace(self.path, f"{stem}.{idx}{ext}")

    def intent(self, req) -> bool:
        """Journal an accepted request's admission intent (durable).
        Dedups by rid — a recovery resubmission is a no-op — and
        returns whether a record was written."""
        if req.rid in self._intents:
            return False
        self._intents.add(req.rid)
        self._committed.setdefault(req.rid, 0)
        self.record("intent", rid=req.rid, trace=req.trace_id,
                    **{f: getattr(req, f, None) for f in _INTENT_FIELDS})
        return True

    def commit(self, rid: str, tokens) -> None:
        """Buffer committed token values for ``rid``; a watermark record
        is written once ``watermark_every`` accumulate. Only MODEL-
        COMMITTED tokens belong here (the engine calls this exactly
        where tokens enter ``req.generated`` — a speculative draft's
        rejected tail never reaches the journal)."""
        if rid not in self._intents or rid in self._terminals:
            return
        buf = self._pending.setdefault(rid, [])
        buf.extend(int(t) for t in tokens)
        if len(buf) >= self.watermark_every:
            self._flush_one(rid)

    def _flush_one(self, rid: str) -> None:
        buf = self._pending.pop(rid, None)
        if not buf:
            return
        total = self._committed.get(rid, 0) + len(buf)
        self._committed[rid] = total
        self.record("watermark", rid=rid, tokens=buf, committed=total)

    def flush_watermarks(self) -> None:
        """Write every buffered watermark (end-of-run / pre-restart
        tightening; never required for correctness — a lost buffer only
        widens the deterministic replay)."""
        for rid in list(self._pending):
            self._flush_one(rid)

    def sync(self) -> None:
        """fdatasync the live file now — retires any group-pending
        terminal syncs (a graceful-shutdown tightening; crash paths by
        definition never reach it)."""
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.flush()
                getattr(os, "fdatasync", os.fsync)(self._fh.fileno())
                self._fsyncs += 1
                self._unsynced_terminals = 0
            except OSError:
                return
        self._m_fsyncs.inc()

    def close(self) -> None:
        """Flush buffered watermarks (tightening, not required), sync,
        and release the append handle. The journal stays usable — the
        next record reopens the live file."""
        self.flush_watermarks()
        self.sync()
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def discard_pending(self, rid: str) -> None:
        """Drop ``rid``'s buffered (not-yet-journaled) tokens. Crash
        recovery truncates a request to its DISK watermark before
        replaying; the replayed decode re-commits the same token values,
        and without this reset the surviving in-process buffer would
        double-count them (fold's committed-total check would then
        fail). The journaled total (``_committed``) already matches the
        disk — only the buffer is stale."""
        self._pending.pop(rid, None)

    def terminal(self, rid: str, outcome: str) -> bool:
        """Journal a request's single terminal (durable). Silently drops
        rids with no journaled intent (never-accepted requests owe no
        terminal) and dedups by rid — exactly-once accounting even when
        a recovered request re-completes. Returns whether written."""
        if outcome not in TERMINAL_OUTCOMES:
            raise ValueError(f"unknown terminal outcome {outcome!r}; "
                             f"known: {TERMINAL_OUTCOMES}")
        if rid not in self._intents or rid in self._terminals:
            return False
        self._terminals.add(rid)
        self._flush_one(rid)        # terminal supersedes buffered tokens
        self.record("terminal", rid=rid, outcome=outcome)
        return True

    # -- introspection ------------------------------------------------------

    def is_terminal(self, rid: str) -> bool:
        return rid in self._terminals

    def position(self) -> dict:
        """Where the journal stands — stamped on crash-path failure
        records so a postmortem names the exact replay point."""
        try:
            nbytes = os.path.getsize(self.path)
        except OSError:
            nbytes = 0
        return {"records": self._records, "bytes": nbytes,
                "parts": len(stream_parts(self.path)),
                "fsyncs": self._fsyncs}

    def tail(self, n: int = 50) -> list[str]:
        """The last ``n`` raw journal lines (across rotation), torn tail
        included verbatim — the flight recorder's ``journal.json``
        payload."""
        lines: list[str] = []
        for part in stream_parts(self.path):
            try:
                with open(part) as f:
                    lines.extend(ln.rstrip("\n") for ln in f)
            except OSError:
                continue
        return lines[-n:]

    def state(self) -> JournalState:
        """Fold the on-disk records (plus nothing in-memory: buffered
        watermarks are by definition not yet journaled)."""
        return fold(self.path)

    def summary(self) -> dict:
        return {"records": self._records, "fsyncs": self._fsyncs,
                "intents": len(self._intents),
                "terminals": len(self._terminals),
                "write_s": self.write_s}


# ---------------------------------------------------------------------------
# Process-wide registry (flight-recorder integration, utils/flightrec.py)
# ---------------------------------------------------------------------------

_installed: RequestJournal | None = None


def install(journal: RequestJournal | None) -> None:
    """Register the process's live journal (``None`` uninstalls) so the
    crash flight recorder can bundle its tail without plumbing."""
    global _installed
    _installed = journal


def installed() -> RequestJournal | None:
    return _installed
