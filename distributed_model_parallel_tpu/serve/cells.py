"""Cell topology for the serving fleet: named groups of replicas.

A production fleet is not a flat replica list — replicas share racks,
power domains and rollout waves, and they fail in CORRELATED groups.
``CellDirectory`` gives :class:`~serve.fleet.ServeFleet` that structure:
replicas are partitioned into named cells (contiguous blocks, so each
cell's DevicePool slice is a contiguous id range), the router keys its
decisions on (cell, prefix, load) — a deterministic home-cell hash with
cell-local power-of-two-choices and cross-cell failover — and the
correlated fault kinds (``kill_cell`` / ``slow_cell`` / ``partition``,
utils/faults.py) target a cell as a unit.

The directory is pure, immutable bookkeeping: membership never changes
at runtime (a quarantined replica stays a MEMBER of its cell — it is
the fleet that tracks liveness), so the home-cell hash is stable across
quarantine→grow-back cycles and the router's assignment sequence stays
seed-deterministic through them (tests/test_cells.py pins it).

See docs/SERVING.md "Cell topology" for the operator view and
docs/RESILIENCE.md "Fault taxonomy" for the correlated fault kinds.
"""

from __future__ import annotations

import zlib

__all__ = ["CellDirectory", "home_cell"]


def home_cell(prompt: list[int], cells: tuple[str, ...],
              seed: int = 0) -> str:
    """Deterministic home cell for a prompt: a seeded crc32 over the
    prompt's leading tokens, mod the FULL configured cell list — never
    the live subset, so a cell going down does not reshuffle every
    other prompt's home (only the victims fail over)."""
    if not cells:
        raise ValueError("home_cell needs at least one cell")
    head = bytes(t % 256 for t in prompt[:32])
    h = zlib.crc32(head, seed & 0xFFFFFFFF)
    return cells[h % len(cells)]


class CellDirectory:
    """Immutable replica-name -> cell mapping (module docstring).

    Build either from an explicit ``{cell: [replica names]}`` mapping or
    via :meth:`partition` (``n_replicas`` into ``n_cells`` contiguous
    equal blocks — the scaled-down drill topology).
    """

    def __init__(self, members_by_cell: dict[str, list[str] | tuple]):
        if not members_by_cell:
            raise ValueError("CellDirectory needs at least one cell")
        self._members: dict[str, tuple[str, ...]] = {}
        self._cell_of: dict[str, str] = {}
        for cell, members in members_by_cell.items():
            members = tuple(members)
            if not members:
                raise ValueError(f"cell {cell!r} has no members")
            self._members[cell] = members
            for name in members:
                if name in self._cell_of:
                    raise ValueError(
                        f"replica {name!r} assigned to both "
                        f"{self._cell_of[name]!r} and {cell!r}")
                self._cell_of[name] = cell
        # Declaration order IS the hash order: stable, explicit.
        self.cells: tuple[str, ...] = tuple(self._members)

    @classmethod
    def partition(cls, names: list[str], n_cells: int) -> "CellDirectory":
        """Split ``names`` into ``n_cells`` contiguous blocks (first
        cells take the remainder) — contiguous, so each cell's device
        slice is a contiguous id range under the pool's lowest-ids-first
        assignment."""
        if n_cells < 1:
            raise ValueError(f"n_cells must be >= 1, got {n_cells}")
        if n_cells > len(names):
            raise ValueError(
                f"{n_cells} cells need >= 1 replica each; got "
                f"{len(names)} replicas")
        base, extra = divmod(len(names), n_cells)
        out, i = {}, 0
        for c in range(n_cells):
            take = base + (1 if c < extra else 0)
            out[f"c{c}"] = names[i:i + take]
            i += take
        return cls(out)

    def cell_of(self, name: str) -> str:
        try:
            return self._cell_of[name]
        except KeyError:
            raise KeyError(f"replica {name!r} is in no cell") from None

    def members(self, cell: str) -> tuple[str, ...]:
        try:
            return self._members[cell]
        except KeyError:
            raise KeyError(f"unknown cell {cell!r}; known: "
                           f"{list(self.cells)}") from None

    def home(self, prompt: list[int], seed: int = 0) -> str:
        return home_cell(prompt, self.cells, seed)

    def as_dict(self) -> dict[str, list[str]]:
        """JSON-ready membership view (statusz / summary payloads)."""
        return {c: list(m) for c, m in self._members.items()}

    def __contains__(self, cell: str) -> bool:
        return cell in self._members

    def __len__(self) -> int:
        return len(self._members)
