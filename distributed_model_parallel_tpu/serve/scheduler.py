"""Iteration-level (continuous) batching scheduler.

Orca-style inflight batching: the decode batch is a fixed set of slots,
and scheduling happens **per engine iteration**, not per batch — a
finishing sequence's slot and pages are handed to a waiting request
mid-batch, and long prompts prefill in chunks interleaved with decode
steps so they never stall the resident batch.

Admission policy: FIFO with head-of-line blocking, gated on the page
pool — a request is admitted only when a slot is free **and** the pool
holds pages for its whole worst case (``prompt + max_new_tokens``),
billed **post-sharing**: pages serving a cached prefix (the paged-KV
radix tree, serve/prefix_cache.py) are retained rather than allocated,
so a cache-hit request reserves only its uncached suffix and admits
where a cold twin queues, and tree-only pages count as reclaimable
(evicted LRU-leaf-first when the allocation needs the room).
Reservation *is* allocation: every page a request could ever touch is
taken at admission, so decode can never OOM mid-flight and nothing ever
needs preemption-by-page-pressure; the trade is earlier queuing, which
is exactly the backpressure the queue-wait histogram measures.
Head-of-line blocking (rather than skipping to a smaller request) keeps
admission deterministic and starvation-free.

``policy="static"`` is the baseline BENCH_serve compares against: the
same engine, but admission only refills when the **whole** batch has
drained — a finished sequence's slot idles until the last co-resident
request completes. The throughput gap between the two policies on the
same trace is the continuous-batching win.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from collections import deque
from typing import Any

from distributed_model_parallel_tpu.utils import tracing


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    COMPLETED = "completed"
    FAILED = "failed"


# Admission classes, in shed order: under overload ``batch`` requests
# wait behind (and are displaced by) ``interactive`` ones, so best-effort
# work sheds first (docs/SERVING.md "Overload and graceful degradation").
PRIORITIES = ("interactive", "batch")


def next_arrived_by_class(requests, now: float) -> "Request | None":
    """The next candidate among ``requests`` under the two-class order:
    an arrived interactive request jumps queued batch ones (batch
    waits, and therefore sheds, first), FIFO within a class. Shared by
    the engine scheduler's admission and the fleet's dispatch — one
    definition of the priority order."""
    batch_head = None
    for r in requests:
        if r.arrival_s > now:
            continue
        if r.priority != "batch":
            return r
        if batch_head is None:
            batch_head = r
    return batch_head


def overflow_victims(arrived: list["Request"],
                     bound: int) -> list["Request"]:
    """The requests to shed when ``arrived`` exceeds ``bound``, in shed
    order — batch first, newest first within a class, so the oldest
    interactive waiters keep their place. Shared by the engine
    scheduler's per-iteration trim and the fleet's per-round trim."""
    excess = len(arrived) - bound
    if excess <= 0:
        return []
    batch = [r for r in arrived if r.priority == "batch"]
    rest = [r for r in arrived if r.priority != "batch"]
    return (list(reversed(batch)) + list(reversed(rest)))[:excess]


def expiry_reason(req: "Request", now: float, *,
                  queue_budget_s: float | None = None,
                  deadline_s: float | None = None) -> str | None:
    """Typed shed reason for an arrived, still-queued request at clock
    ``now`` — ``total-deadline`` (the whole request can no longer matter)
    beats ``queue-deadline`` (it waited past its queue budget); ``None``
    while the request is still worth admitting. The per-request fields
    override the engine defaults passed in."""
    age = now - req.arrival_s
    dl = req.deadline_s if req.deadline_s is not None else deadline_s
    if dl is not None and age > dl:
        return "total-deadline"
    qb = (req.queue_budget_s if req.queue_budget_s is not None
          else queue_budget_s)
    if qb is not None and age > qb:
        return "queue-deadline"
    return None


@dataclasses.dataclass(eq=False)   # identity semantics: requests are live
class Request:                     # objects in slots/queues, not values
    """One generation request plus its lifecycle bookkeeping.

    ``arrival_s`` is seconds relative to the engine run's start (the
    open-loop load generator's clock); ``seed`` drives the per-request
    sampling stream (folded per position, so a request's tokens do not
    depend on who shares the batch).
    """

    rid: str
    prompt: list[int]
    max_new_tokens: int
    arrival_s: float = 0.0
    seed: int = 0
    # -- overload protection (docs/SERVING.md) --
    # Admission class: "interactive" jumps queued "batch" requests and
    # displaces them from a full submission queue — batch sheds first.
    priority: str = "interactive"
    # Queue-wait budget / total deadline (seconds from arrival_s); None
    # defers to the ServeConfig defaults. A queued request past either
    # is shed with a typed record instead of waiting forever; an
    # in-flight request past its total deadline is aborted and its
    # pages returned immediately.
    queue_budget_s: float | None = None
    deadline_s: float | None = None
    # Billing identity (utils/metering.py): which tenant's cost bucket
    # this request's chip-seconds and page-seconds land in. Rides the
    # traffic programs' ``tenant`` field (serve/traffic.py); None bills
    # to the "-" bucket.
    tenant: str | None = None

    # -- runtime state (engine-owned) --
    state: RequestState = RequestState.QUEUED
    generated: list[int] = dataclasses.field(default_factory=list)
    error: str | None = None
    prefill_cursor: int = 0          # prompt tokens already prefilled
    cached_prompt_tokens: int = 0    # prefix served from the radix tree
    slot: int | None = None
    t_admitted: float | None = None
    t_first_token: float | None = None
    t_done: float | None = None
    # Overload bookkeeping: why this request was shed (queue-deadline /
    # total-deadline / queue-full; None for a real failure or success),
    # and the pre-brownout-clamp max_new when level-3 brownout capped it.
    shed_reason: str | None = None
    max_new_requested: int | None = None
    # Live migration (serve/fleet.py): a drained request carries its
    # exported KV page contents here until the destination replica
    # admits it — admission then runs ``PagedKVCache.import_request``
    # instead of a cold allocation and the engine resumes the request
    # at its exact committed position.
    resume: dict | None = None
    migrations: int = 0              # times this request moved replicas
    # Request tracing (docs/TRACING.md "Request tracing"): the identity
    # stamped once at admission into the serving tier, and the
    # per-request causal sequence number ``utils.tracing.rtrace``
    # increments per record. The Request OBJECT migrates between
    # replicas, so the sequence stays monotonic across the hop — the
    # timeline joiner links the two stream segments by (trace, seq).
    trace_id: str | None = None
    trace_seq: int = 0
    # Dedup flag for the ``memory_stall`` rtrace event: set on the first
    # head-of-line page-pressure block, cleared when the request finally
    # admits — one event per stall episode, not one per iteration.
    mem_stalled: bool = False
    # Crash recovery (serve/journal.py): a replayed request re-prefills
    # prompt + journaled committed tokens instead of just the prompt —
    # the final prefill chunk re-samples the last committed token and
    # the engine asserts it bitwise against the journal (the same
    # determinism contract migration relies on), then clears the flag.
    replay: bool = False

    @property
    def prefill_tokens(self) -> list[int]:
        """What prefill must process before decode (re)starts: the
        prompt — plus, for a journal-replay request, every committed
        token except the last (re-sampled and asserted by the final
        prefill chunk)."""
        if self.replay and self.generated:
            return self.prompt + self.generated[:-1]
        return self.prompt

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def total_capacity(self) -> int:
        """Positions this request may ever write (prompt + generated)."""
        return self.prompt_len + self.max_new_tokens

    @property
    def done(self) -> bool:
        return self.state in (RequestState.COMPLETED, RequestState.FAILED)


def validate_request(req: Request, cache) -> None:
    """Shape/feasibility checks shared by per-engine submission and the
    fleet's router-time admission (serve/fleet.py) — every replica runs
    the same geometry, so one cache's limits speak for the fleet."""
    if req.prompt_len < 1:
        raise ValueError(f"request {req.rid!r}: empty prompt")
    if req.max_new_tokens < 1:
        raise ValueError(f"request {req.rid!r}: max_new_tokens must "
                         f"be >= 1, got {req.max_new_tokens}")
    if req.total_capacity > cache.max_seq_len:
        raise ValueError(
            f"request {req.rid!r}: prompt ({req.prompt_len}) + "
            f"max_new_tokens ({req.max_new_tokens}) exceeds the "
            f"engine's max_seq_len {cache.max_seq_len}")
    if cache.pages_needed(req.total_capacity) > cache.pool.n_pages:
        raise ValueError(
            f"request {req.rid!r} needs "
            f"{cache.pages_needed(req.total_capacity)} pages but "
            f"the whole pool holds {cache.pool.n_pages}; it can "
            f"never be admitted")
    if req.priority not in PRIORITIES:
        raise ValueError(f"request {req.rid!r}: unknown priority "
                         f"{req.priority!r}; known: {PRIORITIES}")
    for name, v in (("queue_budget_s", req.queue_budget_s),
                    ("deadline_s", req.deadline_s)):
        if v is not None and v <= 0:
            raise ValueError(f"request {req.rid!r}: {name} must be > 0, "
                             f"got {v}")


class Scheduler:
    """Slot + queue bookkeeping; the engine drives it once per iteration.

    Owns no device state — admission consults the :class:`PagedKVCache`
    pool the engine passes in, so the page-accounting invariants
    (no double allocation, every page returned) live in one place.
    """

    def __init__(self, cache, n_slots: int, *, policy: str = "continuous",
                 prefill_chunks_per_iter: int = 1,
                 queue_budget_s: float | None = None,
                 deadline_s: float | None = None,
                 max_queue: int | None = None):
        if policy not in ("continuous", "static"):
            raise ValueError(f"unknown policy {policy!r}; known: "
                             f"continuous, static")
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if prefill_chunks_per_iter < 1:
            raise ValueError(f"prefill_chunks_per_iter must be >= 1, got "
                             f"{prefill_chunks_per_iter}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.cache = cache
        self.n_slots = n_slots
        self.policy = policy
        self.prefill_chunks_per_iter = prefill_chunks_per_iter
        # Engine-wide deadline defaults (per-request fields override) and
        # the submission-queue bound — the overload-protection knobs
        # (docs/SERVING.md "Overload and graceful degradation").
        self.queue_budget_s = queue_budget_s
        self.deadline_s = deadline_s
        self.max_queue = max_queue
        self.queue: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * n_slots
        self._ids: set[str] = set()
        # Request-trace sink: the engine points this at its telemetry
        # stream so admission's per-request ``rtrace`` records land even
        # when the scheduler runs outside a ``tracing.sink_scope``; a
        # fleet replica's engine also sets ``trace_fields`` to tag every
        # record with its origin (``{"replica": name}``) — the joiner
        # links migration hops by origin change (utils/telemetry.py).
        self.sink = None
        self.trace_fields: dict = {}

    # -- submission ---------------------------------------------------------

    @property
    def full(self) -> bool:
        """The submission queue is at its bound — the caller must reject
        with a typed record, not enqueue. (In fleet mode every queued
        request has already arrived — the fleet gates arrivals — so the
        raw count IS the live backlog; open-loop standalone engines
        bound the *arrived* backlog instead, via
        :meth:`arrived_backlog` + the engine's per-iteration trim.)"""
        return (self.max_queue is not None
                and len(self.queue) >= self.max_queue)

    def arrived_backlog(self, now: float) -> int:
        """Queued requests that have actually arrived by ``now`` — the
        backlog the queue bound applies to (future-dated open-loop trace
        entries are pre-registrations, not load)."""
        return sum(1 for r in self.queue if r.arrival_s <= now)

    def overflow(self, now: float) -> list[Request]:
        """Arrived requests beyond ``max_queue``, in shed order
        (:func:`overflow_victims`). The engine sheds these with typed
        ``queue-full`` records each iteration, so the live backlog
        stays bounded no matter how fast submissions arrive. Migrated
        requests (``resume`` payload) are exempt — rescued load is not
        new demand, the same contract that lets their force-enqueue
        bypass the bound — so they neither count against it nor get
        trimmed."""
        if self.max_queue is None:
            return []
        arrived = [r for r in self.queue
                   if r.arrival_s <= now and r.resume is None]
        victims = overflow_victims(arrived, self.max_queue)
        if not victims:
            return []
        gone = {id(r) for r in victims}
        self.queue = deque(r for r in self.queue if id(r) not in gone)
        return victims

    def submit(self, req: Request) -> None:
        if req.rid in self._ids:
            raise ValueError(f"duplicate request id {req.rid!r}")
        validate_request(req, self.cache)
        self._ids.add(req.rid)
        self.queue.append(req)

    # -- shedding -----------------------------------------------------------

    def expire(self, now: float) -> list[tuple[Request, str]]:
        """Remove arrived queued requests whose queue budget or total
        deadline has passed; returns ``(request, reason)`` pairs for the
        engine to shed with typed records. Queued requests hold no page
        reservation (reservation happens at admission), so removal is
        pure bookkeeping; their rids stay burned (a shed request is
        terminal, not resubmittable)."""
        out: list[tuple[Request, str]] = []
        keep: deque[Request] = deque()
        for r in self.queue:
            reason = (expiry_reason(r, now,
                                    queue_budget_s=self.queue_budget_s,
                                    deadline_s=self.deadline_s)
                      if r.arrival_s <= now else None)
            if reason is None:
                keep.append(r)
            else:
                out.append((r, reason))
        if out:
            self.queue = keep
        return out

    # -- admission ----------------------------------------------------------

    def admit(self, now: float) -> list[Request]:
        """Move arrived queue-head requests into free slots (continuous),
        or refill the whole batch once it has fully drained (static).
        Allocates every admitted request's full page reservation. An
        admission pass with a live queue records a span (utils/tracing.py)
        so the page-table writes show up on the engine timeline; empty
        passes stay span-free (one per idle engine iteration would drown
        the trace), and per-request attribution rides on the ``rtrace``
        plane — one ``admitted`` record per placed request, plus a
        deduplicated ``memory_stall`` when the queue head blocks on
        page pressure (docs/TRACING.md "Request tracing")."""
        if self.policy == "static" and any(
                r is not None for r in self.slots):
            return []
        if not self.queue:
            return []
        admitted: list[Request] = []
        with tracing.span("admit") as sp:
            for slot in range(self.n_slots):
                if self.slots[slot] is not None:
                    continue
                req = self._next_admittable(now)
                if req is None:
                    break
                if req.resume is not None:
                    # A migrated-in request: its exported KV is
                    # authoritative, so the reservation is all fresh pages
                    # (no prefix sharing on arrival) with the payload's
                    # page contents written back in — same backpressure
                    # contract as a cold admission (False -> keep queuing,
                    # no side effects).
                    if not self.cache.import_request(
                            req.rid, req.resume["k"], req.resume["v"],
                            req.total_capacity, req=req, sink=self.sink,
                            trace_fields=self.trace_fields):
                        self._note_memory_stall(req)
                        break              # head-of-line: wait for pages
                else:
                    # One-pass fit check + admission (try_admit peeks the
                    # POST-SHARING bill — a cached prefix's pages are
                    # retained, not allocated, and tree-only pages count
                    # as reclaimable — and only when it fits performs the
                    # reservation; no second radix match / evictable walk
                    # on the hot path). A cold request on a warm pool
                    # queues exactly when its full reservation exceeds
                    # free + evictable (tests/test_prefix_cache.py pins
                    # the regression).
                    # (A journal-replay request admits over prompt +
                    # committed tokens — prefill_tokens — so its pages
                    # cover the whole replayed prefix.)
                    got = self.cache.try_admit(req.rid, req.prefill_tokens,
                                               req.total_capacity)
                    if got is None:
                        self._note_memory_stall(req)
                        break              # head-of-line: wait for pages
                    req.cached_prompt_tokens = got
                self.queue.remove(req)
                req.slot = slot
                req.state = RequestState.PREFILL
                if req.t_admitted is None:
                    # First admission only: a migrated request keeps its
                    # original admission stamp — queue-wait and the
                    # pre/post-kill TTFT split in BENCH_serve fleet mode
                    # both mean "when did this request first get a slot",
                    # not "when did it land on its latest replica".
                    req.t_admitted = now
                self.slots[slot] = req
                admitted.append(req)
                req.mem_stalled = False    # stall episode (if any) ended
                tracing.rtrace(
                    req, "admitted", sink=self.sink, slot=slot,
                    cached_tokens=req.cached_prompt_tokens,
                    resumed=req.resume is not None, **self.trace_fields)
            sp.annotate(n=len(admitted))
        return admitted

    def _note_memory_stall(self, req: Request) -> None:
        """One ``memory_stall`` rtrace per stall episode: emitted when the
        queue-head request first blocks on page pressure, re-armed only
        after it admits — attribution for latency that is memory, not
        compute (ISSUE 16 memory-pressure telemetry)."""
        if req.mem_stalled:
            return
        req.mem_stalled = True
        tracing.rtrace(req, "memory_stall", sink=self.sink,
                       free_pages=self.cache.pool.free_pages,
                       need_capacity=req.total_capacity,
                       **self.trace_fields)

    def _next_admittable(self, now: float) -> Request | None:
        """The next admission candidate (:func:`next_arrived_by_class`).
        Head-of-line blocking applies to the CHOSEN candidate: when it
        does not fit, admission waits rather than skipping deeper
        (deterministic, starvation-free within class)."""
        return next_arrived_by_class(self.queue, now)

    # -- iteration views ----------------------------------------------------

    def prefilling(self) -> list[Request]:
        """Up to ``prefill_chunks_per_iter`` prefill candidates this
        iteration, in slot order (deterministic interleave)."""
        todo = [r for r in self.slots
                if r is not None and r.state is RequestState.PREFILL]
        return list(itertools.islice(todo, self.prefill_chunks_per_iter))

    def decoding(self) -> list[Request]:
        return [r for r in self.slots
                if r is not None and r.state is RequestState.DECODE]

    def active(self) -> list[Request]:
        return [r for r in self.slots if r is not None]

    def evict(self, req: Request) -> None:
        """Release a finished/failed request's slot and pages — the
        mid-batch half of continuous batching."""
        if req.slot is None or self.slots[req.slot] is not req:
            raise ValueError(f"request {req.rid!r} is not resident")
        self.cache.release(req.rid)
        self.slots[req.slot] = None
        req.slot = None

    def withdraw(self, req: Request) -> None:
        """Remove a LIVE request from this scheduler entirely (the drain
        half of migration, serve/fleet.py): a resident request gives up
        its slot and pages, a queued one leaves the queue, and the rid
        leaves the id set — the request will be resubmitted to a peer
        replica's scheduler, and may even return here after a
        quarantine/reinstate cycle."""
        if req.slot is not None:
            self.evict(req)
        else:
            if not any(q is req for q in self.queue):
                raise ValueError(f"request {req.rid!r} is not queued here")
            self.queue = deque(q for q in self.queue if q is not req)
        self._ids.discard(req.rid)

    def pending(self, now: float | None = None) -> int:
        """Queued requests (optionally only those already arrived)."""
        if now is None:
            return len(self.queue)
        return sum(1 for r in self.queue if r.arrival_s <= now)

    def next_arrival(self) -> float | None:
        return min((r.arrival_s for r in self.queue), default=None)

    def idle(self) -> bool:
        return not self.queue and all(r is None for r in self.slots)


def summarize(values: list[float]) -> dict[str, Any]:
    """p50/p99/mean/max over a host-side sample list (exact, sorted —
    the SLO numbers BENCH_serve publishes; registry histograms carry the
    same samples as bucketed estimates for the telemetry stream)."""
    if not values:
        return {"count": 0}
    ys = sorted(values)

    def pct(q: float) -> float:
        if len(ys) == 1:
            return ys[0]
        pos = q / 100.0 * (len(ys) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(ys) - 1)
        return ys[lo] + (pos - lo) * (ys[hi] - ys[lo])

    return {"count": len(ys), "mean": sum(ys) / len(ys),
            "p50": pct(50), "p99": pct(99), "max": ys[-1]}
