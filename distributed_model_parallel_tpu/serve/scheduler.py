"""Iteration-level (continuous) batching scheduler.

Orca-style inflight batching: the decode batch is a fixed set of slots,
and scheduling happens **per engine iteration**, not per batch — a
finishing sequence's slot and pages are handed to a waiting request
mid-batch, and long prompts prefill in chunks interleaved with decode
steps so they never stall the resident batch.

Admission policy: FIFO with head-of-line blocking, gated on the page
pool — a request is admitted only when a slot is free **and** the pool
holds pages for its whole worst case (``prompt + max_new_tokens``),
billed **post-sharing**: pages serving a cached prefix (the paged-KV
radix tree, serve/prefix_cache.py) are retained rather than allocated,
so a cache-hit request reserves only its uncached suffix and admits
where a cold twin queues, and tree-only pages count as reclaimable
(evicted LRU-leaf-first when the allocation needs the room).
Reservation *is* allocation: every page a request could ever touch is
taken at admission, so decode can never OOM mid-flight and nothing ever
needs preemption-by-page-pressure; the trade is earlier queuing, which
is exactly the backpressure the queue-wait histogram measures.
Head-of-line blocking (rather than skipping to a smaller request) keeps
admission deterministic and starvation-free.

``policy="static"`` is the baseline BENCH_serve compares against: the
same engine, but admission only refills when the **whole** batch has
drained — a finished sequence's slot idles until the last co-resident
request completes. The throughput gap between the two policies on the
same trace is the continuous-batching win.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import time
from collections import deque
from typing import Any

from distributed_model_parallel_tpu.utils import tracing


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    COMPLETED = "completed"
    FAILED = "failed"


@dataclasses.dataclass(eq=False)   # identity semantics: requests are live
class Request:                     # objects in slots/queues, not values
    """One generation request plus its lifecycle bookkeeping.

    ``arrival_s`` is seconds relative to the engine run's start (the
    open-loop load generator's clock); ``seed`` drives the per-request
    sampling stream (folded per position, so a request's tokens do not
    depend on who shares the batch).
    """

    rid: str
    prompt: list[int]
    max_new_tokens: int
    arrival_s: float = 0.0
    seed: int = 0

    # -- runtime state (engine-owned) --
    state: RequestState = RequestState.QUEUED
    generated: list[int] = dataclasses.field(default_factory=list)
    error: str | None = None
    prefill_cursor: int = 0          # prompt tokens already prefilled
    cached_prompt_tokens: int = 0    # prefix served from the radix tree
    slot: int | None = None
    t_admitted: float | None = None
    t_first_token: float | None = None
    t_done: float | None = None
    # Live migration (serve/fleet.py): a drained request carries its
    # exported KV page contents here until the destination replica
    # admits it — admission then runs ``PagedKVCache.import_request``
    # instead of a cold allocation and the engine resumes the request
    # at its exact committed position.
    resume: dict | None = None
    migrations: int = 0              # times this request moved replicas

    @property
    def prompt_len(self) -> int:
        return len(self.prompt)

    @property
    def total_capacity(self) -> int:
        """Positions this request may ever write (prompt + generated)."""
        return self.prompt_len + self.max_new_tokens

    @property
    def done(self) -> bool:
        return self.state in (RequestState.COMPLETED, RequestState.FAILED)


def validate_request(req: Request, cache) -> None:
    """Shape/feasibility checks shared by per-engine submission and the
    fleet's router-time admission (serve/fleet.py) — every replica runs
    the same geometry, so one cache's limits speak for the fleet."""
    if req.prompt_len < 1:
        raise ValueError(f"request {req.rid!r}: empty prompt")
    if req.max_new_tokens < 1:
        raise ValueError(f"request {req.rid!r}: max_new_tokens must "
                         f"be >= 1, got {req.max_new_tokens}")
    if req.total_capacity > cache.max_seq_len:
        raise ValueError(
            f"request {req.rid!r}: prompt ({req.prompt_len}) + "
            f"max_new_tokens ({req.max_new_tokens}) exceeds the "
            f"engine's max_seq_len {cache.max_seq_len}")
    if cache.pages_needed(req.total_capacity) > cache.pool.n_pages:
        raise ValueError(
            f"request {req.rid!r} needs "
            f"{cache.pages_needed(req.total_capacity)} pages but "
            f"the whole pool holds {cache.pool.n_pages}; it can "
            f"never be admitted")


class Scheduler:
    """Slot + queue bookkeeping; the engine drives it once per iteration.

    Owns no device state — admission consults the :class:`PagedKVCache`
    pool the engine passes in, so the page-accounting invariants
    (no double allocation, every page returned) live in one place.
    """

    def __init__(self, cache, n_slots: int, *, policy: str = "continuous",
                 prefill_chunks_per_iter: int = 1):
        if policy not in ("continuous", "static"):
            raise ValueError(f"unknown policy {policy!r}; known: "
                             f"continuous, static")
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if prefill_chunks_per_iter < 1:
            raise ValueError(f"prefill_chunks_per_iter must be >= 1, got "
                             f"{prefill_chunks_per_iter}")
        self.cache = cache
        self.n_slots = n_slots
        self.policy = policy
        self.prefill_chunks_per_iter = prefill_chunks_per_iter
        self.queue: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * n_slots
        self._ids: set[str] = set()

    # -- submission ---------------------------------------------------------

    def submit(self, req: Request) -> None:
        if req.rid in self._ids:
            raise ValueError(f"duplicate request id {req.rid!r}")
        validate_request(req, self.cache)
        self._ids.add(req.rid)
        self.queue.append(req)

    # -- admission ----------------------------------------------------------

    def admit(self, now: float) -> list[Request]:
        """Move arrived queue-head requests into free slots (continuous),
        or refill the whole batch once it has fully drained (static).
        Allocates every admitted request's full page reservation. An
        admission that placed someone records a span (utils/tracing.py)
        so the page-table writes show up on the engine timeline; idle
        passes stay span-free (one per engine iteration would drown the
        trace)."""
        if self.policy == "static" and any(
                r is not None for r in self.slots):
            return []
        # Clock reads only when a span could actually be recorded — this
        # runs once per engine iteration, and the tracing-off contract is
        # "no clock call" (utils/tracing.py).
        trace = tracing.installed() is not None and tracing.enabled()
        if trace:
            t0m = time.monotonic()
            t0w = time.time()
        admitted: list[Request] = []
        for slot in range(self.n_slots):
            if self.slots[slot] is not None:
                continue
            if not self.queue or self.queue[0].arrival_s > now:
                break
            req = self.queue[0]
            if req.resume is not None:
                # A migrated-in request: its exported KV is
                # authoritative, so the reservation is all fresh pages
                # (no prefix sharing on arrival) with the payload's page
                # contents written back in — same backpressure contract
                # as a cold admission (False -> keep queuing, no side
                # effects).
                if not self.cache.import_request(
                        req.rid, req.resume["k"], req.resume["v"],
                        req.total_capacity):
                    break                  # head-of-line: wait for pages
            else:
                # One-pass fit check + admission (try_admit peeks the
                # POST-SHARING bill — a cached prefix's pages are
                # retained, not allocated, and tree-only pages count as
                # reclaimable — and only when it fits performs the
                # reservation; no second radix match / evictable walk on
                # the hot path). A cold request on a warm pool queues
                # exactly when its full reservation exceeds free +
                # evictable (tests/test_prefix_cache.py pins the
                # regression).
                got = self.cache.try_admit(req.rid, req.prompt,
                                           req.total_capacity)
                if got is None:
                    break                  # head-of-line: wait for pages
                req.cached_prompt_tokens = got
            self.queue.popleft()
            req.slot = slot
            req.state = RequestState.PREFILL
            if req.t_admitted is None:
                # First admission only: a migrated request keeps its
                # original admission stamp — queue-wait and the
                # pre/post-kill TTFT split in BENCH_serve fleet mode
                # both mean "when did this request first get a slot",
                # not "when did it land on its latest replica".
                req.t_admitted = now
            self.slots[slot] = req
            admitted.append(req)
        if admitted and trace:
            tracing.record_span(
                "admit", time.monotonic() - t0m, t0=t0w, n=len(admitted),
                requests=",".join(r.rid for r in admitted))
        return admitted

    # -- iteration views ----------------------------------------------------

    def prefilling(self) -> list[Request]:
        """Up to ``prefill_chunks_per_iter`` prefill candidates this
        iteration, in slot order (deterministic interleave)."""
        todo = [r for r in self.slots
                if r is not None and r.state is RequestState.PREFILL]
        return list(itertools.islice(todo, self.prefill_chunks_per_iter))

    def decoding(self) -> list[Request]:
        return [r for r in self.slots
                if r is not None and r.state is RequestState.DECODE]

    def active(self) -> list[Request]:
        return [r for r in self.slots if r is not None]

    def evict(self, req: Request) -> None:
        """Release a finished/failed request's slot and pages — the
        mid-batch half of continuous batching."""
        if req.slot is None or self.slots[req.slot] is not req:
            raise ValueError(f"request {req.rid!r} is not resident")
        self.cache.release(req.rid)
        self.slots[req.slot] = None
        req.slot = None

    def withdraw(self, req: Request) -> None:
        """Remove a LIVE request from this scheduler entirely (the drain
        half of migration, serve/fleet.py): a resident request gives up
        its slot and pages, a queued one leaves the queue, and the rid
        leaves the id set — the request will be resubmitted to a peer
        replica's scheduler, and may even return here after a
        quarantine/reinstate cycle."""
        if req.slot is not None:
            self.evict(req)
        else:
            if not any(q is req for q in self.queue):
                raise ValueError(f"request {req.rid!r} is not queued here")
            self.queue = deque(q for q in self.queue if q is not req)
        self._ids.discard(req.rid)

    def pending(self, now: float | None = None) -> int:
        """Queued requests (optionally only those already arrived)."""
        if now is None:
            return len(self.queue)
        return sum(1 for r in self.queue if r.arrival_s <= now)

    def next_arrival(self) -> float | None:
        return min((r.arrival_s for r in self.queue), default=None)

    def idle(self) -> bool:
        return not self.queue and all(r is None for r in self.slots)


def summarize(values: list[float]) -> dict[str, Any]:
    """p50/p99/mean/max over a host-side sample list (exact, sorted —
    the SLO numbers BENCH_serve publishes; registry histograms carry the
    same samples as bucketed estimates for the telemetry stream)."""
    if not values:
        return {"count": 0}
    ys = sorted(values)

    def pct(q: float) -> float:
        if len(ys) == 1:
            return ys[0]
        pos = q / 100.0 * (len(ys) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(ys) - 1)
        return ys[lo] + (pos - lo) * (ys[hi] - ys[lo])

    return {"count": len(ys), "mean": sum(ys) / len(ys),
            "p50": pct(50), "p99": pct(99), "max": ys[-1]}
