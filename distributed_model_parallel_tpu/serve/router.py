"""SLO-aware request router for a multi-replica serving fleet.

One fleet-level admission decision per request: which replica gets it.
The policy is **power-of-two-choices** over live load — sample two
distinct live replicas (seeded rng, deterministic) and take the less
loaded — with a **prefix-affinity** override: when some replica's radix
tree already holds a usable prefix of the prompt (PR 13's prefix cache
is per-replica), sending the request there converts prefill work into a
page-table share, so affinity wins unless that replica is materially
busier than the least-loaded one (``affinity_slack``).

Load is the signal the SLOs actually feel: queued requests + resident
requests + page-pool occupancy (the fraction term breaks ties between
otherwise-equal replicas toward the emptier pool). Power-of-two-choices
gives near-best-of-all balancing at O(1) cost and — unlike
least-loaded-of-all — does not herd every burst onto one replica between
load refreshes (the classic Mitzenmacher result).

Cell topology (serve/cells.py): on a celled fleet the key becomes
**(cell, prefix, load)** — prefix affinity still wins outright (the KV
pages live where they live), otherwise the prompt's deterministic home
cell (a seeded hash over the FULL configured cell list, so a down cell
never reshuffles other prompts' homes) confines p2c to the home cell's
candidates (reason ``cell-local``); only when the home cell offers no
admitting candidate — killed, partitioned, breakers open — does p2c
widen to the remaining cells (reason ``failover``).

Determinism: the rng is seeded, sampling order is submission order, and
load is pure bookkeeping — the same trace through the same fleet yields
the same assignment sequence, before, during and after a
quarantine→reinstate cycle (tests/test_fleet.py and tests/test_cells.py
pin it). Migration re-admissions bypass p2c and go least-loaded: a
drain dumps a burst of requests at once, and spreading them by load is
the point.
"""

from __future__ import annotations

import random

from distributed_model_parallel_tpu.utils import tracing

__all__ = ["Router"]


class Router:
    """Deterministic SLO-aware replica picker (see module docstring).

    The fleet (serve/fleet.py) owns replica lifecycle; the router is
    pure policy — it reads live queue/slot/page state off the candidate
    engines at decision time and keeps only its own assignment
    bookkeeping.
    """

    def __init__(self, seed: int = 0, *, affinity_slack: float = 2.0,
                 affinity_min_tokens: int = 1, cells=None):
        if affinity_slack < 0:
            raise ValueError(f"affinity_slack must be >= 0, got "
                             f"{affinity_slack}")
        self._seed = int(seed)
        self._rng = random.Random(seed)
        self.affinity_slack = affinity_slack
        self.affinity_min_tokens = affinity_min_tokens
        # Cell topology (serve/cells.py CellDirectory, or None for the
        # flat PR 14 fleet): home-cell hashing + cell-local p2c with
        # cross-cell failover.
        self.cells = cells
        # name -> requests routed there (statusz + the fleet summary)
        self.assignments: dict[str, int] = {}
        self.affinity_hits = 0
        self.failovers = 0

    @staticmethod
    def load(replica) -> float:
        """A replica's live load: queued + resident requests, plus page
        occupancy as the fractional tie-break toward the emptier pool."""
        eng = replica.engine
        return (len(eng.sched.queue)
                + sum(1 for s in eng.sched.slots if s is not None)
                + eng.cache.occupancy)

    def pick(self, prompt: list[int], replicas: list, *,
             migrate: bool = False, commit: bool = True,
             request=None, sink=None) -> tuple[object, str, dict]:
        """Choose a live replica for ``prompt``. Returns ``(replica,
        reason, loads)`` where reason is ``affinity`` (prefix-cache
        match won), ``p2c`` (power-of-two-choices), ``cell-local``
        (p2c confined to the prompt's home cell), ``failover`` (home
        cell unreachable — p2c over the other cells), ``only`` (one
        candidate), or ``migrate`` (least-loaded drain placement).
        ``loads`` maps replica name -> load at decision time (the typed
        ``router`` record's payload). ``commit=False`` defers the
        assignment bookkeeping to an explicit :meth:`commit` — the
        fleet's dispatch path, where an admission can still be refused
        (bounded queue, circuit breaker, injected chaos) and a refused
        pick must not inflate the assignment counts. A traced
        ``request``/``sink`` puts the landed decision on the request
        timeline as a ``route`` rtrace record — emitted at commit time,
        so a refused pick never fakes a hop."""
        if not replicas:
            raise ValueError("no live replica to route to")
        loads = {r.name: self.load(r) for r in replicas}
        if len(replicas) == 1:
            chosen, reason = replicas[0], "only"
        elif migrate:
            # Drain placement: the exported KV rides with the request
            # (no prefix to exploit), and a whole replica's worth of
            # requests arrives at once — spread strictly by load.
            chosen = min(replicas, key=lambda r: (loads[r.name], r.name))
            reason = "migrate"
        else:
            chosen, reason = self._pick_new(prompt, replicas, loads)
        if commit:
            self.commit(chosen.name, reason, request=request, sink=sink,
                        loads=loads)
        return chosen, reason, loads

    def commit(self, name: str, reason: str, *, request=None, sink=None,
               loads: dict | None = None) -> None:
        """Count an assignment that actually LANDED (the engine accepted
        the request); a traced ``request`` gets its ``route`` rtrace
        record here."""
        self.assignments[name] = self.assignments.get(name, 0) + 1
        if reason == "affinity":
            self.affinity_hits += 1
        if reason == "failover":
            self.failovers += 1
        if request is not None:
            fields = {"loads": loads} if loads is not None else {}
            tracing.rtrace(request, "route", sink=sink, replica=name,
                           reason=reason, **fields)

    def _pick_new(self, prompt, replicas, loads):
        best_aff, aff_rep = 0, None
        for r in replicas:
            cached = r.engine.cache.cached_prefix_tokens(prompt)
            if cached > best_aff:
                best_aff, aff_rep = cached, r
        min_load = min(loads.values())
        if (aff_rep is not None and best_aff >= self.affinity_min_tokens
                and loads[aff_rep.name] <= min_load + self.affinity_slack):
            return aff_rep, "affinity"
        if self.cells is not None:
            home = self.cells.home(prompt, self._seed)
            local = [r for r in replicas
                     if self.cells.cell_of(r.name) == home]
            if local:
                return self._p2c(local, loads), "cell-local"
            # Home cell killed/partitioned/breaker-open: fail over
            # across whatever the other cells offer.
            return self._p2c(replicas, loads), "failover"
        return self._p2c(replicas, loads), "p2c"

    def _p2c(self, replicas, loads):
        # Power-of-two-choices: two distinct seeded samples, less loaded
        # wins. Exact ties go to the FIRST sampled — the sample order is
        # itself seeded-random, so idle replicas share ties instead of
        # herding onto a fixed favorite (a (load, name) tie-break would
        # send a lightly-loaded fleet's whole trace to one replica).
        if len(replicas) == 1:
            return replicas[0]
        a, b = self._rng.sample(range(len(replicas)), 2)
        ra, rb = replicas[a], replicas[b]
        return ra if loads[ra.name] <= loads[rb.name] else rb
