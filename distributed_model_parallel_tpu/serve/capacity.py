"""Capacity observatory: cost tables, headroom, billing invariants.

The analysis half of the resource-metering plane (utils/metering.py
emits; this module reads). Input is a telemetry stream — the typed
``meter`` records (per-residency bills), ``utilization`` records
(per-replica duty ledgers), ``rtrace`` terminals and the fleet's
``serve`` summary — and the output is the capacity report
``scripts/dmp_capacity.py`` renders and ``dmp_report``'s
``== capacity ==`` section embeds:

* **per-tenant cost table** — chip-seconds, page-seconds, resident
  time, tokens and sheds per tenant, straight from the terminal + hop
  meter records (a migrated request's residencies sum across replicas;
  nothing is double-billed because each record bills exactly one
  residency);
* **per-replica utilization** — each replica's duty-cycle ledger
  (busy / stalled / brownout / idle / quarantined fractions of its
  wall), plus the derived **sustainable tokens/s** (observed rate
  scaled to a fully-busy duty cycle) and **headroom** (sustainable
  minus observed);
* **what-if planning** (:func:`what_if`) — project fleet capacity at
  replicas ± N from the measured per-replica sustainable rate, pricing
  per-iteration dispatch-launch overhead with the autotune cost model's
  ``alpha_s`` coefficient (autotune/cost_model.py) so a shrink-the-
  fleet projection does not pretend launch overhead amortizes away;
* **billing invariants** (:func:`check_invariants`) — the
  ``dmp_capacity --gate`` contract:

  1. every ``utilization`` record's duty buckets partition its wall
     within the tolerance (default 1%);
  2. billed chip-seconds never exceed the fleet's iterated wall —
     the sum over replicas of (wall − quarantined) seconds, i.e.
     wall × live replicas in ledger form (a meter that over-billed
     physical chip time would fail here);
  3. every trace's terminal ``rtrace`` events pair 1:1 with terminal
     ``meter`` records — exactly one bill closes per terminal, none
     without one (hop records are residency splits, not terminals,
     and are excluded on both sides).

See docs/OBSERVABILITY.md "Capacity & cost" for the report tour.
"""

from __future__ import annotations

from distributed_model_parallel_tpu.utils.metering import (
    LEDGER_BUCKETS,
    METER_TERMINAL_EVENTS,
)
from distributed_model_parallel_tpu.utils.telemetry import (
    RTRACE_TERMINAL_EVENTS,
)

__all__ = [
    "build_capacity",
    "check_invariants",
    "tenant_costs",
    "utilization_by_replica",
    "what_if",
]


def _meter_records(records) -> list[dict]:
    return [r for r in records if r.get("kind") == "meter"]


def _utilization_records(records) -> list[dict]:
    return [r for r in records if r.get("kind") == "utilization"]


def _last_serve_summary(records) -> dict | None:
    """The run's final ``serve`` summary — fleet-policy preferred (it
    carries replica counts); a single-engine summary works for the
    degenerate one-replica capacity view."""
    fleet = None
    any_summary = None
    for r in records:
        if r.get("kind") == "serve" and r.get("event") == "summary":
            any_summary = r
            if r.get("policy") == "fleet":
                fleet = r
    return fleet if fleet is not None else any_summary


def tenant_costs(records) -> dict[str, dict]:
    """Per-tenant cost table from the meter records. Hop records add
    cost figures only; terminal records also count the request, its
    tokens and (for shed/expired) the shed."""
    out: dict[str, dict] = {}
    for r in _meter_records(records):
        row = out.setdefault(
            r.get("tenant") or "-",
            {"requests": 0, "chip_s": 0.0, "page_s": 0.0,
             "resident_s": 0.0, "tokens": 0, "sheds": 0, "hops": 0})
        row["chip_s"] += float(r.get("chip_s") or 0.0)
        row["page_s"] += float(r.get("page_s") or 0.0)
        row["resident_s"] += float(r.get("resident_s") or 0.0)
        ev = r.get("event")
        if ev in METER_TERMINAL_EVENTS:
            row["requests"] += 1
            row["tokens"] += int(r.get("tokens") or 0)
            if ev in ("shed", "expired"):
                row["sheds"] += 1
        elif ev == "hop":
            row["hops"] += 1
    for row in out.values():
        for k in ("chip_s", "page_s", "resident_s"):
            row[k] = round(row[k], 6)
    return dict(sorted(out.items()))


def utilization_by_replica(records) -> dict[str, dict]:
    """Per-replica duty ledger, summed across that replica's
    ``utilization`` records (a hard-crashed predecessor's archived
    meter emits under the same replica name — its duty history folds
    in, exactly like the fleet summary's rollup)."""
    out: dict[str, dict] = {}
    for r in _utilization_records(records):
        name = str(r.get("replica") or "-")
        row = out.setdefault(
            name, {**{f"{b}_s": 0.0 for b in LEDGER_BUCKETS},
                   "wall_s": 0.0, "iterations": 0,
                   "meter_write_s": 0.0, "cell": r.get("cell")})
        for b in LEDGER_BUCKETS:
            row[f"{b}_s"] += float(r.get(f"{b}_s") or 0.0)
        row["wall_s"] += float(r.get("wall_s") or 0.0)
        row["iterations"] += int(r.get("iterations") or 0)
        row["meter_write_s"] += float(r.get("meter_write_s") or 0.0)
    return dict(sorted(out.items()))


def _duty_fractions(row: dict) -> dict:
    wall = row.get("wall_s") or 0.0
    if wall <= 0:
        return {b: 0.0 for b in LEDGER_BUCKETS}
    return {b: row[f"{b}_s"] / wall for b in LEDGER_BUCKETS}


def build_capacity(records) -> dict:
    """The full capacity report over one (merged) telemetry stream.

    Sustainable tokens/s scales the observed completion rate to a
    fully-busy duty cycle: a replica 40% busy that moved its share of
    tokens could move ~2.5x that before saturating (brownout time
    counts as busy — it IS serving, degraded). Fleet tokens apportion
    to replicas by their busy-second share (the meter bills chips, not
    tokens, so the stream has no per-replica token count)."""
    summary = _last_serve_summary(records)
    util = utilization_by_replica(records)
    tenants = tenant_costs(records)
    meters = _meter_records(records)
    chip_s = sum(float(r.get("chip_s") or 0.0) for r in meters)
    page_s = sum(float(r.get("page_s") or 0.0) for r in meters)

    wall_s = float((summary or {}).get("wall_s") or 0.0)
    tokens = int((summary or {}).get("tokens_generated") or 0)
    observed_tps = tokens / wall_s if wall_s > 0 else 0.0
    goodput_tps = (summary or {}).get("goodput_tokens_per_s") or 0.0

    served_s = {n: row["busy_s"] + row["brownout_s"]
                for n, row in util.items()}
    total_served = sum(served_s.values())
    replicas: dict[str, dict] = {}
    for name, row in util.items():
        frac = _duty_fractions(row)
        busy_frac = frac["busy"] + frac["brownout"]
        # This replica's share of the fleet's tokens, by busy-time
        # share — then scaled to a 100% duty cycle.
        share = (served_s[name] / total_served if total_served > 0
                 else 0.0)
        rep_tps = observed_tps * share
        sustainable = rep_tps / busy_frac if busy_frac > 0 else 0.0
        replicas[name] = {
            **{f"{b}_s": round(row[f"{b}_s"], 6)
               for b in LEDGER_BUCKETS},
            "wall_s": round(row["wall_s"], 6),
            "iterations": row["iterations"],
            "cell": row.get("cell"),
            "duty": {b: round(f, 4) for b, f in frac.items()},
            "tokens_per_s": round(rep_tps, 3),
            "sustainable_tokens_per_s": round(sustainable, 3),
            "headroom_tokens_per_s": round(
                max(0.0, sustainable - rep_tps), 3),
            "meter_write_s": round(row["meter_write_s"], 6),
        }
    fleet_sustainable = sum(r["sustainable_tokens_per_s"]
                            for r in replicas.values())
    iter_wall = sum(row["wall_s"] - row["quarantined_s"]
                    for row in util.values())
    write_s = sum(row["meter_write_s"] for row in util.values())
    return {
        "wall_s": round(wall_s, 6),
        "n_replicas": (summary or {}).get("n_replicas") or len(util),
        "live_replicas": (summary or {}).get("live_replicas"),
        "tokens": tokens,
        "tokens_per_s": round(observed_tps, 3),
        "goodput_tokens_per_s": (round(float(goodput_tps), 3)
                                 if goodput_tps else 0.0),
        "billed_chip_s": round(chip_s, 6),
        "billed_page_s": round(page_s, 6),
        "meter_records": len(meters),
        "tenants": tenants,
        "replicas": replicas,
        "sustainable_tokens_per_s": round(fleet_sustainable, 3),
        "headroom_tokens_per_s": round(
            max(0.0, fleet_sustainable - observed_tps), 3),
        "headroom_fraction": (
            round(max(0.0, 1.0 - observed_tps / fleet_sustainable), 4)
            if fleet_sustainable > 0 else None),
        "metering_overhead": {
            "meter_write_s": round(write_s, 6),
            "iteration_wall_s": round(iter_wall, 6),
            "fraction": (round(write_s / iter_wall, 6)
                         if iter_wall > 0 else 0.0),
        },
    }


def what_if(cap: dict, delta: int, coeffs=None) -> dict:
    """Project fleet capacity at ``n_replicas + delta``.

    The projection takes each replica as interchangeable at the
    measured mean sustainable rate, then prices per-iteration dispatch
    launch overhead with the autotune cost model's ``alpha_s``
    (autotune/cost_model.py): every engine iteration pays a fixed
    launch cost, so the same offered load on fewer replicas runs
    proportionally more iterations per replica and the overhead term
    does NOT amortize away — a shrink projection that ignored it would
    flatter small fleets."""
    if coeffs is None:
        from distributed_model_parallel_tpu.autotune.cost_model import (
            default_coefficients,
        )

        coeffs = default_coefficients()
    replicas = cap.get("replicas") or {}
    n = len(replicas) or int(cap.get("n_replicas") or 1)
    n2 = max(1, n + int(delta))
    per_replica = (cap.get("sustainable_tokens_per_s", 0.0) / n
                   if n else 0.0)
    # Launch-overhead fraction at the CURRENT duty: iterations per
    # iterated-wall second × alpha_s. Scaling the fleet by n/n2 scales
    # each survivor's iteration rate by the same factor at fixed
    # offered load.
    iters = sum(r.get("iterations") or 0 for r in replicas.values())
    iter_wall = sum((r.get("wall_s") or 0.0)
                    - (r.get("quarantined_s") or 0.0)
                    for r in replicas.values())
    iter_rate = iters / iter_wall if iter_wall > 0 else 0.0
    overhead_now = min(0.9, coeffs.alpha_s * iter_rate)
    overhead_then = min(0.9, overhead_now * (n / n2))
    capacity_tps = (per_replica * n2
                    * (1.0 - overhead_then) / (1.0 - overhead_now)
                    if overhead_now < 1.0 else per_replica * n2)
    observed = cap.get("tokens_per_s", 0.0)
    return {
        "replicas": n2,
        "delta": int(delta),
        "capacity_tokens_per_s": round(capacity_tps, 3),
        "offered_tokens_per_s": round(observed, 3),
        "projected_utilization": (round(observed / capacity_tps, 4)
                                  if capacity_tps > 0 else None),
        "headroom_tokens_per_s": round(
            max(0.0, capacity_tps - observed), 3),
        "saturated": bool(capacity_tps > 0
                          and observed > capacity_tps),
        "alpha_s": coeffs.alpha_s,
        "launch_overhead_fraction": round(overhead_then, 6),
    }


def check_invariants(records, *, tolerance: float = 0.01) -> list[str]:
    """The ``dmp_capacity --gate`` billing invariants (module
    docstring). Returns human-readable failure strings; empty means
    the stream's billing is sound."""
    failures: list[str] = []
    utils = _utilization_records(records)
    meters = _meter_records(records)

    # 1. Duty buckets partition each utilization record's wall.
    for r in utils:
        wall = float(r.get("wall_s") or 0.0)
        total = sum(float(r.get(f"{b}_s") or 0.0)
                    for b in LEDGER_BUCKETS)
        if wall <= 1e-9:
            if total > 1e-9:
                failures.append(
                    f"utilization record for {r.get('replica')}: "
                    f"buckets sum to {total:.6f}s on zero wall")
            continue
        err = abs(total - wall) / wall
        if err > tolerance:
            failures.append(
                f"duty buckets do not partition wall on "
                f"{r.get('replica')}: |{total:.6f} - {wall:.6f}| "
                f"= {err:.2%} > {tolerance:.0%}")

    # 2. Billed chip-seconds bounded by iterated wall (= wall x live
    # replicas in ledger form: quarantined time never iterates).
    chip_s = sum(float(r.get("chip_s") or 0.0) for r in meters)
    if utils:
        budget = sum(float(r.get("wall_s") or 0.0)
                     - float(r.get("quarantined_s") or 0.0)
                     for r in utils)
        source = "iterated wall (utilization ledger)"
    else:
        summary = _last_serve_summary(records)
        if summary is None:
            failures.append("no utilization records and no serve "
                            "summary: cannot bound billed chip time")
            budget = None
            source = None
        else:
            budget = (float(summary.get("wall_s") or 0.0)
                      * int(summary.get("n_replicas") or 1))
            source = "summary wall x n_replicas"
    if budget is not None and chip_s > budget * (1.0 + tolerance):
        failures.append(
            f"billed chip-seconds exceed {source}: "
            f"{chip_s:.6f}s > {budget:.6f}s")

    # 3. Terminal rtrace events pair 1:1 with terminal meter records.
    rtrace_terms: dict[str, int] = {}
    for r in records:
        if (r.get("kind") == "rtrace" and r.get("trace") is not None
                and r.get("event") in RTRACE_TERMINAL_EVENTS):
            t = str(r["trace"])
            rtrace_terms[t] = rtrace_terms.get(t, 0) + 1
    meter_terms: dict[str, int] = {}
    for r in meters:
        if (r.get("trace") is not None
                and r.get("event") in METER_TERMINAL_EVENTS):
            t = str(r["trace"])
            meter_terms[t] = meter_terms.get(t, 0) + 1
    for t, n in rtrace_terms.items():
        m = meter_terms.get(t, 0)
        if m != n:
            failures.append(
                f"trace {t}: {n} terminal rtrace event(s) but {m} "
                f"terminal meter record(s)")
    for t, m in meter_terms.items():
        if t not in rtrace_terms:
            failures.append(
                f"trace {t}: {m} terminal meter record(s) with no "
                f"terminal rtrace event")
    return failures
