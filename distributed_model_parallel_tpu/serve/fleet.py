"""Self-healing multi-replica serving fleet.

``ServeFleet`` runs N independent :class:`~serve.engine.Engine` replicas
— one model copy and one paged KV pool each, on a disjoint
:class:`~orchestrator.scheduler.DevicePool` slice — behind a
:class:`~serve.router.Router` that admits requests with SLO-aware
balancing (power-of-two-choices over live queue depth + page occupancy,
with a prefix-affinity bonus toward the replica whose radix tree already
holds the prompt). One fleet round = one router dispatch pass + one
engine iteration per live replica, all on a shared monotonic clock, so
the whole fleet replays deterministically for a fixed trace.

Self-healing: the fleet is a tenant of the PR 7 device-health sentinel
(``utils/health.DeviceHealthMonitor``). Each replica's per-round wall
time feeds the monitor as a ``serve`` signal on its device slice; when
the monitor quarantines a replica's devices (or an operator/chaos drill
calls :meth:`kill_replica`), the replica is **drained, not killed**:

1. every live request's committed tokens + written KV pages are
   serialized out of the paged cache (``PagedKVCache.export_request`` —
   values, never page ids, so nothing references the dying replica);
2. the replica's prefix tree is dropped and every page verified back on
   the free list (``Engine.clear_cache``);
3. its devices leave the pool (``DevicePool.quarantine`` + release);
4. each drained request is re-admitted on the least-loaded peer at the
   exact committed position (``PagedKVCache.import_request`` + the
   engine's resume path) — a typed ``migration`` record per move.

Because a request's tokens are a pure function of (prompt, seed) — the
engine's pinned determinism contract — a migrated request's remaining
tokens are **bitwise identical** to an unmigrated run, and the chaos
drill (tests/test_fleet.py, BENCH_serve fleet mode) asserts exactly
that. Once the sentinel reinstates the devices (or ``revive_after``
rounds pass in drill mode), the replica **grows back**: it re-claims its
exact device slice (``DevicePool.assign_ids``) and the router resumes
sending it traffic.

See docs/SERVING.md "Fleet serving" for the operator recipe.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax

from distributed_model_parallel_tpu.serve.cells import CellDirectory
from distributed_model_parallel_tpu.serve.engine import (
    Engine,
    EngineKilled,
    ServeConfig,
)
from distributed_model_parallel_tpu.serve.overload import CircuitBreaker
from distributed_model_parallel_tpu.serve.router import Router
from distributed_model_parallel_tpu.serve.scheduler import (
    Request,
    RequestState,
    expiry_reason,
    next_arrived_by_class,
    overflow_victims,
    summarize,
    validate_request,
)
from distributed_model_parallel_tpu.utils import health as health_mod
from distributed_model_parallel_tpu.utils import tracing
from distributed_model_parallel_tpu.utils.faults import FaultInjector
from distributed_model_parallel_tpu.utils.metering import (
    LEDGER_BUCKETS,
    emit_meter,
)
from distributed_model_parallel_tpu.utils.telemetry import registry

__all__ = ["Replica", "ServeFleet"]

LIVE = "live"
QUARANTINED = "quarantined"


@dataclasses.dataclass
class Replica:
    """One serving replica: an engine plus its device slice."""

    name: str
    engine: Engine
    device_ids: tuple[int, ...]
    state: str = LIVE
    quarantined_round: int | None = None
    kills: int = 0                   # quarantine cycles survived
    cell: str | None = None          # cell membership (serve/cells.py)
    crashes: int = 0                 # hard crashes (no-drain) survived


class ServeFleet:
    """N engine replicas behind an SLO-aware router (module docstring).

    ``pool`` defaults to a fresh :class:`DevicePool` over
    ``jax.devices()``; pass the orchestrator's pool to co-schedule the
    serving tier with training tenants (replicas hold their slices under
    ``serve-{name}``). ``health`` wires the device-health sentinel in;
    without it, :meth:`kill_replica` + ``revive_after`` drive the same
    quarantine/grow-back machinery (the chaos-drill mode).
    ``step_hook(round)`` runs once per fleet round — the drill's kill
    trigger, like the engine's per-iteration hook.
    """

    def __init__(self, params: dict, cfg, serve: ServeConfig,
                 n_replicas: int, *, pool=None, devices=None,
                 health=None, telemetry=None, router_seed: int = 0,
                 affinity_slack: float = 2.0, revive_after: int | None = None,
                 step_hook=None, slo_metrics: bool = True,
                 breaker: CircuitBreaker | None = None,
                 faults=(), fault_replica: str | None = None,
                 cells=None, fault_cell: str | None = None,
                 cell_sick_threshold: float = 0.5, clock=None,
                 journal=None, meter: bool = True):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if not 0.0 < cell_sick_threshold <= 1.0:
            raise ValueError(f"cell_sick_threshold must be in (0, 1], "
                             f"got {cell_sick_threshold}")
        if serve.policy != "continuous":
            raise ValueError(
                "the fleet runs continuous-batching replicas; the static "
                "baseline exists for single-engine BENCH_serve comparisons")
        if pool is None:
            from distributed_model_parallel_tpu.orchestrator.scheduler import (
                DevicePool,
            )

            pool = DevicePool(devices if devices is not None
                              else jax.devices())
        self.pool = pool
        per = pool.n_free // n_replicas
        if per < 1:
            raise ValueError(
                f"{n_replicas} replicas need >= 1 free device each; the "
                f"pool has {pool.n_free} free")
        self.serve = serve
        self.telemetry = telemetry
        self.health = health
        self.revive_after = revive_after
        self.step_hook = step_hook
        self._slo_metrics = slo_metrics
        # Resource metering (utils/metering.py): off switches the whole
        # billing plane — engine meters AND the fleet's own zero-cost
        # terminals — so the soak drill can A/B the schedule digest.
        self._meter = meter
        # Pluggable clock (serve/traffic.SimClock for the deterministic
        # chaos scenarios; the real monotonic clock otherwise). Virtual
        # mode advances one fixed dt per fleet round and skips idle gaps
        # to the next arrival, so every TTFT/deadline/goodput number is
        # a pure function of the trace + seed.
        self._virtual = clock is not None
        self._clock = clock if clock is not None else time.monotonic
        self._engine_clock = clock       # fresh post-crash engines reuse it
        # Write-ahead request journal (serve/journal.py): intent at
        # acceptance, committed-token watermarks from the engines,
        # exactly one terminal per trace. None = journal off — byte-
        # identical scheduling to a journal-less fleet. install() makes
        # it visible to the crash flight recorder's bundle.
        self.journal = journal
        if journal is not None:
            from distributed_model_parallel_tpu.serve import (
                journal as journal_mod,
            )

            journal_mod.install(journal)
        self.replicas: list[Replica] = []
        for i in range(n_replicas):
            name = f"r{i}"
            devs = pool.assign(f"serve-{name}", per)
            eng = Engine(params, cfg, serve, telemetry=telemetry,
                         slo_metrics=slo_metrics, replica=name,
                         clock=clock, journal=journal, meter=meter)
            self.replicas.append(Replica(
                name=name, engine=eng,
                device_ids=tuple(d.id for d in devs)))
        # Cell topology (serve/cells.py): an int partitions the replicas
        # into that many contiguous cells; a dict gives explicit
        # membership; a CellDirectory passes through; None keeps the
        # flat PR 14 fleet. Contiguous blocks + the pool's
        # lowest-ids-first assignment make each cell's device slice a
        # contiguous id range.
        if cells is None:
            self.cells = None
        elif isinstance(cells, CellDirectory):
            self.cells = cells
        elif isinstance(cells, int):
            self.cells = CellDirectory.partition(
                [r.name for r in self.replicas], cells)
        else:
            self.cells = CellDirectory(cells)
        if self.cells is not None:
            known = {r.name for r in self.replicas}
            for c in self.cells.cells:
                missing = [n for n in self.cells.members(c)
                           if n not in known]
                if missing:
                    raise ValueError(f"cell {c!r} names unknown replicas "
                                     f"{missing}")
            for rep in self.replicas:
                rep.cell = self.cells.cell_of(rep.name)
        # Stamp each replica's meter with its cell so utilization
        # records roll up per cell (utils/metering.py).
        for rep in self.replicas:
            if rep.engine.meter is not None:
                rep.engine.meter.cell = rep.cell
        self.cell_sick_threshold = cell_sick_threshold
        self.router = Router(router_seed, affinity_slack=affinity_slack,
                             cells=self.cells)
        # Router-level admission circuit breaker (serve/overload.py):
        # repeated admission failures — a replica's bounded queue
        # staying full, or injected admission chaos — take the replica
        # out of the routing set until a half-open probe lands.
        # Distinct from health quarantine: an open breaker's replica
        # keeps serving its residents.
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        # Serve-side chaos (utils/faults.py): slow_replica sleeps inside
        # the victim replica's timed round, admission_fail refuses its
        # admissions for a bounded run of attempts.
        self.injector = FaultInjector(faults) if faults else None
        for spec in (self.injector.plan if self.injector else ()):
            if spec.site not in ("serve", "admit", "cell"):
                raise ValueError(
                    f"fleet fault plans serve only the serve/admit/cell "
                    f"sites; {spec.kind!r} fires at {spec.site!r} "
                    f"(train-side faults belong on trainer "
                    f"RecoveryConfig plans)")
            if spec.site == "cell" and self.cells is None:
                raise ValueError(
                    f"{spec.kind!r} targets a cell, but the fleet has "
                    f"no cell topology (pass cells=)")
        self._fault_replica = fault_replica or self.replicas[-1].name
        if not any(r.name == self._fault_replica for r in self.replicas):
            raise ValueError(f"unknown fault_replica "
                             f"{self._fault_replica!r}")
        # The correlated-fault victim cell (kill_cell / slow_cell /
        # partition): default the LAST cell — disjoint from the c0
        # home-heavy head of the hash range often enough to keep drills
        # interesting, and symmetric with fault_replica's default.
        if self.cells is not None:
            self._fault_cell = fault_cell or self.cells.cells[-1]
            if self._fault_cell not in self.cells:
                raise ValueError(f"unknown fault_cell "
                                 f"{self._fault_cell!r}; known: "
                                 f"{list(self.cells.cells)}")
        elif fault_cell is not None:
            raise ValueError("fault_cell needs a cell topology "
                             "(pass cells=)")
        else:
            self._fault_cell = None
        # Correlated-fault runtime state: cells the router currently
        # cannot reach (partition), the active slow_cell period, cells
        # taken down whole (for the grow-back record), and the resident
        # requests caught inside an active partition (the drain-on-heal
        # accounting).
        self._partitioned: set[str] = set()
        self._slow_period: int | None = None
        self._cells_down: set[str] = set()
        self._partition_caught: list = []
        self._cell_kills = 0
        # Bounded fleet admission: beyond max_queue * n_replicas the
        # fleet REJECTS (typed, reason queue-full) instead of growing an
        # unbounded host-side list — batch sheds first: an arriving
        # interactive request displaces the newest queued batch one.
        self._max_pending = (serve.max_queue * n_replicas
                            if serve.max_queue is not None else None)
        self._pending: deque[Request] = deque()
        self._requests: list[Request] = []
        self._ids: set[str] = set()
        self._shed_by_reason: dict[str, int] = {}
        # Metering state the engines cannot see: per-tenant counts of
        # queue-only sheds (the request never reached an engine meter),
        # and the archived meters of hard-crashed engines — their
        # closed per-tenant rollups and duty history must survive the
        # engine object (crash_replica) or the cost table under-counts.
        self._tenant_sheds: dict[str, int] = {}
        self._dead_meters: list = []
        self._rejected = 0
        self._auto_rid = 0
        self._rounds = 0
        self._now = 0.0
        self._wall_s = 0.0
        self._migrations = 0
        self._kills = 0
        # Hard-crash accounting (serve/journal.py crash recovery):
        # crashes fired, requests re-admitted from the journal, and the
        # cumulative monotonic recovery-pass duration — the
        # ``recovery_time_s`` BENCH_serve crash drills gate on
        # (utils/baseline.py GATE_METRICS, lower-better).
        self._crashes = 0
        self._crash_recovered = 0
        self.recovery_time_s = 0.0
        self.kill_times: dict[str, float] = {}
        self.revive_times: dict[str, float] = {}
        if slo_metrics:
            from distributed_model_parallel_tpu.utils import statusz

            statusz.maybe_serve(serve.statusz_port)
            statusz.register("serve-fleet", self._status)
            self._set_live_gauge()

    # -- views ---------------------------------------------------------------

    def _live(self) -> list[Replica]:
        return [r for r in self.replicas if r.state == LIVE]

    def _cell_members(self, cell: str) -> list[Replica]:
        return [r for r in self.replicas if r.cell == cell]

    def _live_cells(self) -> list[str]:
        """Cells with at least one live, reachable replica — the
        router's actual dispatch surface."""
        if self.cells is None:
            return []
        return [c for c in self.cells.cells
                if c not in self._partitioned
                and any(r.state == LIVE for r in self._cell_members(c))]

    def _holder(self, rep: Replica) -> str:
        return f"serve-{rep.name}"

    def _set_live_gauge(self) -> None:
        if self._slo_metrics:
            registry().gauge("serve_live_replicas").set(len(self._live()))
            if self.cells is not None:
                registry().gauge("serve_live_cells").set(
                    len(self._live_cells()))

    def _meters(self, *, cell: str | None = None) -> list:
        """Every meter in scope: the current engines' plus the archived
        meters of hard-crashed predecessors (``crash_replica`` swaps the
        engine object out, but its billed history must keep counting).
        ``cell`` narrows to one cell's members."""
        out = [r.engine.meter for r in self.replicas
               if r.engine.meter is not None
               and (cell is None or r.cell == cell)]
        out += [m for m in self._dead_meters
                if cell is None or m.cell == cell]
        return out

    @staticmethod
    def _merged_utilization(meters) -> dict | None:
        """Summed duty-cycle ledger across ``meters`` — the fleet and
        per-cell rollups for /statusz and the summary. Buckets keep
        partitioning wall exactly: sums of exact partitions."""
        if not meters:
            return None
        out = {b: 0.0 for b in LEDGER_BUCKETS}
        for m in meters:
            for bucket, s in m.ledger.items():
                out[bucket] += s
        return {**{f"{b}_s": round(s, 6) for b, s in out.items()},
                "wall_s": round(sum(out.values()), 6)}

    def _set_engine_gauges(self) -> None:
        """The fleet owns the process-global engine gauges: replica
        engines skip their own writes — N replicas flapping one
        unlabeled gauge would report whichever iterated last
        (per-replica numbers live on the /statusz providers).
        ``serve_page_occupancy`` is the MAX across live replicas (what
        the page-pool saturation alert wants to see),
        ``serve_shared_pages`` the fleet-wide sum, and the
        hit/accept-rate gauges pool the replicas' raw token counts (a
        per-replica mean would weight an idle replica like a busy
        one)."""
        live = self._live()
        if not (self._slo_metrics and live):
            return
        reg = registry()
        reg.gauge("serve_page_occupancy").set(
            max(r.engine.cache.occupancy for r in live))
        if self.serve.prefix_cache:
            reg.gauge("serve_shared_pages").set(
                sum(r.engine.cache.shared_pages for r in live))
            prompts = sum(r.engine._prompt_tokens for r in live)
            if prompts:
                reg.gauge("serve_cache_hit_rate").set(
                    sum(r.engine._cached_tokens for r in live) / prompts)
        if self.serve.spec_k:
            proposed = sum(r.engine._draft_proposed for r in live)
            if proposed:
                reg.gauge("serve_draft_accept_rate").set(
                    sum(r.engine._draft_accepted for r in live)
                    / proposed)
        if self.serve.brownout:
            # Worst (deepest) live replica level — the saturation view,
            # like the occupancy max above.
            reg.gauge("serve_brownout_level").set(
                max(r.engine.brownout.level for r in live))
        # Fleet duty-cycle gauges (utils/metering.py): each bucket's
        # fraction of the fleet's cumulative iteration wall, across ALL
        # replicas — a quarantined replica's dead time is the point.
        u = self._merged_utilization(self._meters())
        if u is not None and u["wall_s"] > 0:
            wall = u["wall_s"]
            reg.gauge("serve_utilization_busy").set(u["busy_s"] / wall)
            reg.gauge("serve_utilization_stalled").set(
                u["stalled_s"] / wall)
            reg.gauge("serve_utilization_brownout").set(
                u["brownout_s"] / wall)
            reg.gauge("serve_utilization_idle").set(u["idle_s"] / wall)
            reg.gauge("serve_utilization_quarantined").set(
                u["quarantined_s"] / wall)

    def _status(self) -> dict:
        """The fleet's /statusz provider: replica table + router state."""
        return {
            "workload": "serve-fleet",
            "n_replicas": len(self.replicas),
            "live": [r.name for r in self._live()],
            "pending": len(self._pending),
            "pending_bound": self._max_pending,
            "requests_shed": (
                sum(self._shed_by_reason.values())
                + sum(sum(r.engine._shed_by_reason.values())
                      for r in self.replicas)),
            "requests_rejected": (
                self._rejected
                + sum(r.engine._rejected for r in self.replicas)),
            "rounds": self._rounds,
            "migrations": self._migrations,
            "replica_kills": self._kills,
            "router": {"assignments": dict(self.router.assignments),
                       "affinity_hits": self.router.affinity_hits,
                       "failovers": self.router.failovers},
            "replicas": {
                r.name: {
                    "state": r.state,
                    "cell": r.cell,
                    "devices": list(r.device_ids),
                    "queue_depth": len(r.engine.sched.queue),
                    "active_requests": len(r.engine.sched.active()),
                    "page_occupancy": r.engine.cache.occupancy,
                    "assignments": self.router.assignments.get(r.name, 0),
                    "breaker": self.breaker.state(r.name),
                    "brownout_level": (r.engine.brownout.level
                                       if r.engine.brownout is not None
                                       else None),
                } for r in self.replicas},
            "cells": self._cell_status(),
            "utilization": self._merged_utilization(self._meters()),
            "healthy": bool(self._live()),
        }

    def _cell_status(self) -> dict | None:
        """Per-cell rollup for /statusz and the fleet summary: member
        liveness, reachability, aggregated breaker state, and (when the
        health sentinel is wired) the quarantined fraction of the
        cell's device slice."""
        if self.cells is None:
            return None
        out = {}
        for c in self.cells.cells:
            members = self._cell_members(c)
            devices = [d for r in members for d in r.device_ids]
            out[c] = {
                "members": [r.name for r in members],
                "live": [r.name for r in members if r.state == LIVE],
                "partitioned": c in self._partitioned,
                "breaker": self.breaker.group_state(
                    [r.name for r in members]),
                "assignments": sum(
                    self.router.assignments.get(r.name, 0)
                    for r in members),
                "utilization": self._merged_utilization(
                    self._meters(cell=c)),
                **({"device_quarantined_fraction": round(
                        self.health.quarantined_fraction(devices), 3)}
                   if self.health is not None else {}),
            }
        return out

    def results(self) -> list[Request]:
        return list(self._requests)

    def close(self) -> None:
        """Unregister the fleet's /statusz presence (the fleet provider
        plus every replica engine's). A discarded drill fleet must not
        keep feeding stale replica state — including ``healthy: false``
        from an all-quarantined end state — into /statusz and /healthz,
        or pin N engines' params in the exporter's provider table (the
        same teardown PR 12 added for reaped orchestrator tenants).
        Results stay readable; the fleet just leaves the exporter."""
        if self._slo_metrics:
            from distributed_model_parallel_tpu.utils import statusz

            statusz.unregister("serve-fleet")
            for rep in self.replicas:
                statusz.unregister(rep.engine._provider)
        if self.journal is not None:
            from distributed_model_parallel_tpu.serve import (
                journal as journal_mod,
            )

            # Un-install only OUR journal: a crashed-and-recovered
            # successor fleet may have installed its own by now, and a
            # discarded fleet must not blind the flight recorder to it.
            if journal_mod.installed() is self.journal:
                journal_mod.install(None)

    # -- submission ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *, rid: str | None = None,
               arrival_s: float = 0.0, seed: int = 0,
               priority: str = "interactive",
               queue_budget_s: float | None = None,
               deadline_s: float | None = None,
               tenant: str | None = None) -> Request:
        """Queue a request at fleet level; the router assigns it to a
        replica when it arrives (open loop), so placement sees the load
        at arrival time, not submission time. A full fleet queue
        (``ServeConfig.max_queue`` × replicas) REJECTS with a typed
        record (reason ``queue-full``) — batch first: an interactive
        arrival displaces the newest queued batch request instead of
        being turned away itself. Callers check ``req.done``."""
        prompt = [int(t) for t in prompt]
        if rid is None:
            rid = f"req-{self._auto_rid}"
            self._auto_rid += 1
        if rid in self._ids:
            raise ValueError(f"duplicate request id {rid!r}")
        req = Request(rid=rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      arrival_s=float(arrival_s), seed=int(seed),
                      priority=priority, queue_budget_s=queue_budget_s,
                      deadline_s=deadline_s, tenant=tenant)
        # Geometry is fleet-uniform: any replica's cache speaks for all.
        ref = self.replicas[0].engine
        validate_request(req, ref.cache)
        bad = [t for t in prompt if not (0 <= t < ref.cfg.vocab_size)]
        if bad:
            raise ValueError(f"prompt tokens {bad} outside vocab "
                             f"[0, {ref.cfg.vocab_size})")
        self._ids.add(rid)
        self._requests.append(req)
        # Stamp the request trace at fleet admission — the identity that
        # survives routing, migration between replicas, and brownout
        # clamps (docs/TRACING.md "Request tracing"). Fleet-level rtrace
        # records carry no ``replica`` field: their origin IS the fleet.
        if self.telemetry is not None:
            req.trace_id = tracing.new_trace_id()
            tracing.rtrace(req, "submitted", sink=self.telemetry,
                           prompt_tokens=req.prompt_len,
                           max_new_tokens=req.max_new_tokens,
                           priority=req.priority)
        # Write-ahead intent (serve/journal.py): durable BEFORE any
        # engine touches the request, so an accepted request survives
        # any later crash. Every terminal path journals its matching
        # single terminal — including the queue-full shed just below.
        if self.journal is not None:
            self.journal.intent(req)
        # The bound rejects ALREADY-ARRIVED submissions against the live
        # arrived backlog (the runaway-client case); future-dated
        # open-loop trace entries enqueue and the per-round trim
        # (``_bound_pending``) sheds overflow once they arrive.
        if (self._max_pending is not None
                and req.arrival_s <= self._now
                and sum(1 for r in self._pending
                        if r.arrival_s <= self._now) >= self._max_pending):
            if req.priority == "batch":
                self._shed_request(req, "queue-full")
                return req
            victim = next((r for r in reversed(self._pending)
                           if r.priority == "batch"
                           and r.arrival_s <= self._now), None)
            if victim is None:
                self._shed_request(req, "queue-full")
                return req
            # Batch sheds first: the newest queued batch request gives
            # its place to the interactive arrival.
            self._pending.remove(victim)
            self._shed_request(victim, "queue-full")
        self._pending.append(req)
        return req

    def _bound_pending(self, now: float) -> None:
        """Per-round queue bound: shed arrived fleet-queue overflow
        beyond ``max_queue`` × replicas with typed ``queue-full``
        records — batch first, newest-arrival first within a class, so
        the oldest interactive waiters keep their place and the live
        backlog stays bounded no matter the offered load."""
        if self._max_pending is None:
            return
        arrived = [r for r in self._pending if r.arrival_s <= now]
        victims = overflow_victims(arrived, self._max_pending)
        if not victims:
            return
        gone = {id(r) for r in victims}
        self._pending = deque(r for r in self._pending
                              if id(r) not in gone)
        for req in victims:
            self._shed_request(req, "queue-full",
                               waited_s=max(0.0, now - req.arrival_s))

    def _shed_request(self, req: Request, reason: str, *,
                      waited_s: float | None = None) -> None:
        """Typed fleet-level shed: queue-full rejection/displacement or
        a fleet-queue deadline expiry — terminal, counted, recorded."""
        req.state = RequestState.FAILED
        req.shed_reason = reason
        req.error = f"shed: {reason}"
        if self.journal is not None:
            self.journal.terminal(req.rid, "shed")
        tracing.rtrace(req,
                       "expired" if reason in ("total-deadline",
                                               "queue-deadline")
                       else "shed",
                       sink=self.telemetry, reason=reason, state="queued",
                       **({"waited_s": round(waited_s, 4)}
                          if waited_s is not None else {}))
        self._shed_by_reason[reason] = self._shed_by_reason.get(reason, 0) + 1
        # Exactly one terminal meter record per terminal trace: a
        # queue-only request never reached an engine meter, so the
        # fleet bills its zero-cost terminal here (utils/metering.py)
        # and counts the shed against its tenant for the SLO rollup.
        if self._meter:
            emit_meter(self.telemetry, req,
                       "expired" if reason in ("total-deadline",
                                               "queue-deadline")
                       else "shed", replica="fleet")
        t = req.tenant or "-"
        self._tenant_sheds[t] = self._tenant_sheds.get(t, 0) + 1
        if reason == "queue-full":
            self._rejected += 1
        if self._slo_metrics:
            reg = registry()
            reg.counter("serve_shed_total").inc()
            if reason == "queue-full":
                reg.counter("serve_rejected_total").inc()
        if self.telemetry is not None:
            self.telemetry.record(
                "shed", request=req.rid, reason=reason,
                priority=req.priority, state="queued", policy="fleet",
                prompt_tokens=req.prompt_len,
                new_tokens=len(req.generated),
                **({"waited_s": round(waited_s, 4)}
                   if waited_s is not None else {}))

    def warmup(self) -> None:
        """Compile every program once (engine builders are memoized per
        geometry, so warming one replica warms them all)."""
        self.replicas[0].engine.warmup()

    # -- the control loop ----------------------------------------------------

    def run(self, *, max_rounds: int | None = None,
            record_summary: bool = True) -> dict:
        """Drive the fleet until every submitted request is terminal (or
        ``max_rounds``). Same contract as ``Engine.run``: a death marks
        every live request failed (typed) before :class:`EngineKilled`
        propagates."""
        t0 = self._clock()
        try:
            with tracing.sink_scope(self.telemetry):
                while not self._idle():
                    if max_rounds is not None and self._rounds >= max_rounds:
                        break
                    now = self._clock() - t0
                    self._now = now
                    if self.step_hook is not None:
                        self.step_hook(self._rounds)
                    self._rounds += 1
                    self._poll_cell_faults()
                    self._expire_pending(now)
                    progress = self._dispatch(now)
                    # Queue-bound trim AFTER dispatch (work-conserving:
                    # requests the replicas just absorbed must not count
                    # against the bound).
                    self._bound_pending(now)
                    q0 = time.monotonic()
                    for rep in self.replicas:
                        if rep.state != LIVE:
                            continue
                        if (self._slow_period is not None
                                and rep.cell == self._fault_cell
                                and self._rounds % self._slow_period):
                            # slow_cell: the victim cell's replicas run
                            # an engine iteration only every period-th
                            # round — lockstep cell-wide slowdown, no
                            # wall-clock sleep (virtual replays stay
                            # exact). Residents decode slower; SLOs sag.
                            continue
                        w0 = time.monotonic()
                        if (self.injector is not None
                                and rep.name == self._fault_replica):
                            # slow_replica sleeps HERE, inside the timed
                            # window, so the health sentinel's serve
                            # signal observes it like a real throttle;
                            # crash_replica fires the hard-crash path on
                            # the same victim.
                            for spec in self.injector.poll("serve"):
                                if spec.kind == "crash_replica":
                                    self.crash_replica(rep.name)
                            if rep.state != LIVE:
                                continue     # crashed this round
                        stepped = rep.engine.step_once(now, t0)
                        if stepped:
                            # Only WORKING rounds feed the sentinel: an
                            # idle round's microsecond wall time would
                            # seed the warmup-min baseline so low that
                            # the first busy round reads as an outlier
                            # and a healthy replica gets quarantined.
                            self._observe(rep, time.monotonic() - w0)
                        progress = progress or stepped
                    # Quarantined duty: while its peers stepped, a
                    # quarantined replica's chips sat out the whole
                    # round — that wall lands in its ledger's
                    # ``quarantined`` bucket, same real-monotonic
                    # clock as the live replicas' iteration samples
                    # (utils/metering.py).
                    qdt = time.monotonic() - q0
                    for rep in self.replicas:
                        if (rep.state == QUARANTINED
                                and rep.engine.meter is not None):
                            rep.engine.meter.add_quarantined(qdt)
                    self._set_engine_gauges()
                    self._apply_health()
                    self._maybe_revive()
                    if (self._pending and not self._live()
                            and self.revive_after is None
                            and self.health is None):
                        # No live peer and no revive path (no sentinel,
                        # no drill timer): queued requests can never
                        # dispatch — fail them typed instead of spinning
                        # forever, like _migrate's no-live-peer branch.
                        self._fail_pending(
                            "all replicas quarantined with no revive "
                            "path")
                        continue
                    if self._virtual:
                        # One round = one dt of virtual time; an idle
                        # fleet skips straight to the next arrival.
                        self._clock.tick()
                        if not progress:
                            nxt = min((r.arrival_s for r in self._pending),
                                      default=None)
                            if nxt is not None:
                                self._clock.advance_to(t0 + nxt)
                    elif not progress:
                        nxt = min((r.arrival_s for r in self._pending),
                                  default=None)
                        if nxt is not None:
                            time.sleep(max(0.0, min(nxt - now, 0.05)))
        except BaseException as e:
            self._fail_fleet(f"{type(e).__name__}: {e}")
            self._wall_s += self._clock() - t0
            if self.telemetry is not None:
                self.telemetry.failure(
                    "fleet-killed", detail=f"{type(e).__name__}: {e}",
                    round=self._rounds)
            from distributed_model_parallel_tpu.utils import flightrec

            flightrec.dump("fleet-killed", telemetry_run=self.telemetry,
                           error=e)
            if not isinstance(e, Exception):
                raise
            raise EngineKilled(
                f"fleet died at round {self._rounds}; in-flight requests "
                f"marked failed") from e
        self._wall_s += self._clock() - t0
        return self.summary(record=record_summary)

    def _idle(self) -> bool:
        return not self._pending and all(r.engine.sched.idle()
                                         for r in self.replicas)

    def _expire_pending(self, now: float) -> None:
        """Shed arrived fleet-queue requests past their queue budget or
        total deadline — under sustained overload most shedding happens
        HERE, before any replica spends a page on the request."""
        expired = [
            (r, reason) for r in self._pending if r.arrival_s <= now
            and (reason := expiry_reason(
                r, now, queue_budget_s=self.serve.queue_budget_s,
                deadline_s=self.serve.deadline_s)) is not None]
        if not expired:
            return
        gone = {id(r) for r, _ in expired}
        self._pending = deque(r for r in self._pending
                              if id(r) not in gone)
        for req, reason in expired:
            self._shed_request(req, reason,
                               waited_s=max(0.0, now - req.arrival_s))

    def _next_pending(self, now: float) -> Request | None:
        """Next arrived fleet-queue request — the engine scheduler's
        two-class order, one shared definition
        (:func:`~serve.scheduler.next_arrived_by_class`)."""
        return next_arrived_by_class(self._pending, now)

    def _try_admit(self, rep: Replica, req: Request) -> bool:
        """One admission attempt: the injected ``admission_fail`` chaos
        (victim replica only) or a full bounded submission queue refuses
        it — the refusal feeds the circuit breaker."""
        if (self.injector is not None and rep.name == self._fault_replica):
            self.injector.poll("admit")
            if self.injector.admission_blocked():
                return False
        return rep.engine.try_enqueue(req)

    def _emit_breaker_records(self) -> None:
        for tr in self.breaker.drain_transitions():
            if self.telemetry is not None:
                self.telemetry.record("breaker", **tr)

    def _dispatch(self, now: float) -> bool:
        """Route every arrived fleet-queue request to a live replica
        whose circuit breaker admits traffic. A refused admission
        (bounded queue, chaos) feeds the breaker and leaves the request
        on the fleet queue for the next round — bounded-queue
        backpressure, never a drop."""
        progress = False
        while True:
            req = self._next_pending(now)
            if req is None:
                break
            live = self._live()
            if not live:
                break                 # all quarantined: wait for grow-back
            candidates = [r for r in live
                          if r.cell not in self._partitioned
                          and self.breaker.allows(r.name, self._rounds)]
            self._emit_breaker_records()   # half-open transitions
            if not candidates:
                break    # every breaker open / cell unreachable: wait
            placed = None
            while candidates:
                rep, reason, loads = self.router.pick(
                    req.prompt, candidates, commit=False)
                ok = self._try_admit(rep, req)
                self.breaker.note(rep.name, ok, self._rounds)
                self._emit_breaker_records()
                if ok:
                    placed = (rep, reason, loads)
                    break
                candidates = [r for r in candidates if r is not rep]
            if placed is None:
                break                 # nobody would take it: next round
            rep, reason, loads = placed
            self.router.commit(
                rep.name, reason, request=req, sink=self.telemetry,
                loads={k: round(v, 3) for k, v in sorted(loads.items())})
            self._pending.remove(req)
            if self._slo_metrics:
                registry().counter("serve_router_assignments").inc()
            if self.telemetry is not None:
                self.telemetry.record(
                    "router", request=req.rid, replica=rep.name,
                    reason=reason, round=self._rounds,
                    loads={k: round(v, 3) for k, v in sorted(loads.items())})
            progress = True
        return progress

    def _poll_cell_faults(self) -> None:
        """Once-per-round poll of the ``cell`` fault site (utils/faults):
        ``kill_cell`` fires the REAL quarantine→drain→migrate path for
        every member of the victim cell at once; ``partition`` flips the
        router's reachability for the victim cell (typed ``cell``
        records on both edges, with the drain-on-heal accounting of the
        residents caught inside); ``slow_cell`` sets the step-skip
        period the round loop honors. No sleeps, no randomness — the
        scenario replays bit-for-bit."""
        if self.injector is None or self._fault_cell is None:
            return
        for spec in self.injector.poll("cell"):
            if spec.kind == "kill_cell":
                self.kill_cell(self._fault_cell)
        self._slow_period = self.injector.cell_slow_period()
        active = self.injector.partition_active()
        if active and self._fault_cell not in self._partitioned:
            self._partitioned.add(self._fault_cell)
            # Residents caught inside the partition: they keep decoding
            # (the cell is unreachable, not dead) and the heal record
            # reports how many drained out in the meantime.
            self._partition_caught = [
                req for rep in self._cell_members(self._fault_cell)
                if rep.state == LIVE
                for req in rep.engine.sched.active()]
            if self.telemetry is not None:
                self.telemetry.record(
                    "cell", event="partition", cell=self._fault_cell,
                    round=self._rounds,
                    residents=len(self._partition_caught))
            self._set_live_gauge()
        elif not active and self._fault_cell in self._partitioned:
            self._partitioned.discard(self._fault_cell)
            drained = sum(1 for r in self._partition_caught if r.done)
            if self.telemetry is not None:
                self.telemetry.record(
                    "cell", event="heal", cell=self._fault_cell,
                    round=self._rounds,
                    residents=len(self._partition_caught),
                    drained=drained)
            self._partition_caught = []
            self._set_live_gauge()

    def _observe(self, rep: Replica, seconds: float) -> None:
        """Feed the replica's round wall time to the health sentinel as
        a ``serve`` signal on its device slice (the fleet's own monitor,
        else whatever the orchestrator installed process-wide)."""
        if self.health is not None:
            self.health.observe("serve", rep.device_ids, seconds)
        else:
            health_mod.observe_serve(rep.device_ids, seconds)

    # -- self-healing --------------------------------------------------------

    def _apply_health(self) -> None:
        """Consume the sentinel's transitions: quarantine events drain
        the hit replicas to their peers; reinstate events grow them
        back (typed ``health`` records on the fleet's stream, like the
        orchestrator's control loop)."""
        if self.health is None:
            return
        events = self.health.tick()
        quarantined: list[int] = []
        reinstated: list[int] = []
        for ev in events:
            if self.telemetry is not None:
                self.telemetry.record("health", round=self._rounds, **ev)
            if ev["event"] == "quarantine":
                quarantined += ev["devices"]
            elif ev["event"] == "reinstate":
                reinstated += ev["devices"]
        if quarantined:
            bad = set(quarantined)
            fresh = []
            for rep in self.replicas:
                if rep.state == LIVE and bad & set(rep.device_ids):
                    self._quarantine_replica(rep, reason="device-degraded")
                    fresh.append(rep)
            self._cell_sweep(fresh)
        if reinstated:
            back = set(reinstated)
            still_bad = set(self.health.quarantined_ids)
            for rep in self.replicas:
                if (rep.state == QUARANTINED
                        and back & set(rep.device_ids)
                        and not still_bad & set(rep.device_ids)):
                    self._revive(rep)

    def _maybe_revive(self) -> None:
        """Drill-mode grow-back: a killed replica revives after
        ``revive_after`` quarantined rounds. On a health-wired fleet
        this covers operator/drill kills the MONITOR never saw (no
        reinstate event will ever arrive for them) — but a replica
        whose devices the sentinel itself still quarantines stays down
        until probation heals them (the sentinel's verdict wins)."""
        if self.revive_after is None:
            return
        for rep in self.replicas:
            if (rep.state != QUARANTINED
                    or self._rounds - rep.quarantined_round
                    < self.revive_after):
                continue
            if (self.health is not None
                    and set(rep.device_ids)
                    & set(self.health.quarantined_ids)):
                continue
            self._revive(rep)

    def kill_replica(self, name: str, *, reason: str = "killed") -> int:
        """Chaos-drill entry point: quarantine + drain replica ``name``
        mid-stream (idempotent per cycle — killing an already
        quarantined replica raises). Returns requests migrated."""
        for rep in self.replicas:
            if rep.name == name:
                if rep.state != LIVE:
                    raise ValueError(f"replica {name!r} is {rep.state}")
                migrated = self._quarantine_replica(rep, reason=reason)
                self._cell_sweep([rep])
                return migrated
        raise KeyError(f"unknown replica {name!r}")

    def crash_replica(self, name: str, *,
                      reason: str = "injected-crash") -> int:
        """Hard-crash drill entry point (serve/journal.py): replica
        ``name``'s engine object, page pool and prefix tree are
        DISCARDED with no drain — nothing is exported, exactly what a
        process death leaves behind. A recovery pass then reconstructs
        every journaled non-terminal request the dead replica held from
        the write-ahead journal and re-admits it on a live peer at its
        disk watermark; the destination's replay prefill re-derives the
        committed prefix bitwise (the determinism contract) and asserts
        it against the journal. Returns requests re-admitted."""
        if self.journal is None:
            raise ValueError(
                "crash_replica needs a write-ahead journal (pass "
                "journal=RequestJournal(...)); without one a hard crash "
                "can only lose requests — kill_replica is the graceful "
                "drain path")
        rep = next((r for r in self.replicas if r.name == name), None)
        if rep is None:
            raise KeyError(f"unknown replica {name!r}")
        if rep.state != LIVE:
            raise ValueError(f"replica {name!r} is {rep.state}")
        t0 = time.monotonic()
        lost = [r for r in rep.engine._requests if not r.done]
        params, cfg = rep.engine.params, rep.engine.cfg
        rep.engine.kill(reason=reason)
        if rep.engine.meter is not None:
            # The dead engine's meter outlives it: closed per-tenant
            # rollups and duty history keep counting in the fleet
            # summary. Its OPEN bills die unbilled — the residents'
            # chip time since their last terminal/hop is lost, which is
            # the safe direction for the capacity gate (billed chip-
            # seconds can only under-shoot wall × live replicas).
            self._dead_meters.append(rep.engine.meter)
        # The crash: the old engine (scheduler, page pool, prefix tree)
        # is dropped on the floor — no drain, no clear_cache invariant
        # to satisfy, its pages die with it. A FRESH engine takes the
        # slot so the standard grow-back path revives the replica cold,
        # like a restarted process; its statusz provider re-registers
        # under the same name, replacing the dead engine's entry.
        rep.engine = Engine(params, cfg, self.serve,
                            telemetry=self.telemetry,
                            slo_metrics=self._slo_metrics,
                            replica=rep.name, clock=self._engine_clock,
                            journal=self.journal, meter=self._meter)
        if rep.engine.meter is not None:
            rep.engine.meter.cell = rep.cell
        rep.state = QUARANTINED
        rep.quarantined_round = self._rounds
        rep.kills += 1
        rep.crashes += 1
        self._kills += 1
        self._crashes += 1
        self.kill_times[rep.name] = self._now
        self.pool.quarantine(rep.device_ids)
        self.pool.release(self._holder(rep))
        self._set_live_gauge()
        if self.telemetry is not None:
            self.telemetry.record(
                "event", message=f"fleet crash: replica {rep.name} "
                                 f"({reason}) devices {rep.device_ids} "
                                 f"hard-crashed, {len(lost)} requests to "
                                 f"recover from the journal")
        self._cell_sweep([rep])
        recovered = self._recover_lost(lost, rep)
        self.recovery_time_s += time.monotonic() - t0
        return recovered

    def _recover_lost(self, lost: list[Request], rep: Replica) -> int:
        """Journal-driven replay re-admission after a hard crash: every
        non-terminal request the dead replica held is reset to its DISK
        watermark (buffered watermarks died with the process) and
        re-admitted on a live peer, exactly-once by terminal dedup."""
        st = self.journal.state()
        recovered = 0
        for req in lost:
            if self.journal.is_terminal(req.rid):
                continue
            toks = st.tokens.get(req.rid, [])
            self.journal.discard_pending(req.rid)
            # Reset to the journaled state: committed prefix from the
            # disk watermark, every runtime-local field (slot, cursors,
            # resume payload) cleared — the peer admits it cold and the
            # replay prefill rebuilds the KV from token values.
            req.generated = list(toks)
            req.state = RequestState.QUEUED
            req.slot = None
            req.prefill_cursor = 0
            req.cached_prompt_tokens = 0
            req.resume = None
            req.mem_stalled = False
            req.replay = bool(toks)
            tracing.rtrace(req, "recovered", sink=self.telemetry,
                           from_replica=rep.name, committed=len(toks))
            live = [r for r in self._live()
                    if r.cell not in self._partitioned]
            if not live:
                # Same contract as _migrate's dead end: typed failure,
                # never a silent drop — and a journaled terminal, so a
                # later fleet restart does not resurrect it.
                req.state = RequestState.FAILED
                req.error = (f"fleet-killed: replica {rep.name} crashed "
                             f"with no reachable live peer")
                self.journal.terminal(req.rid, "failed")
                tracing.rtrace(req, "failed", sink=self.telemetry,
                               error="no-live-replica")
                if self._meter:
                    emit_meter(self.telemetry, req, "failed",
                               replica="fleet")
                if self._slo_metrics:
                    registry().counter("serve_requests_failed").inc()
                if self.telemetry is not None:
                    self.telemetry.record(
                        "serve", event="failed", request=req.rid,
                        policy="fleet", error="no-live-replica",
                        detail=req.error, prompt_tokens=req.prompt_len,
                        new_tokens=len(req.generated))
                continue
            candidates = [r for r in live
                          if self.breaker.allows(r.name, self._rounds)
                          ] or live
            self._emit_breaker_records()
            target, reason, loads = self.router.pick(
                req.prompt, candidates, migrate=True, request=req,
                sink=self.telemetry)
            target.engine.enqueue(req, force=True)
            recovered += 1
            self._crash_recovered += 1
            if self._slo_metrics:
                registry().counter("serve_router_assignments").inc()
            if self.telemetry is not None:
                self.telemetry.record(
                    "router", request=req.rid, replica=target.name,
                    reason=reason, round=self._rounds,
                    loads={k: round(v, 3)
                           for k, v in sorted(loads.items())})
                # The recovery ledger entry pairing the kill's failure
                # record — dmp_report folds these like migrations.
                self.telemetry.record(
                    "recovery", action="replay-readmit", request=req.rid,
                    from_replica=rep.name, to_replica=target.name,
                    committed=len(toks), round=self._rounds)
        return recovered

    def kill_cell(self, cell: str, *, reason: str = "cell-killed") -> int:
        """Correlated-failure entry point: quarantine + drain EVERY live
        member of ``cell`` at once (a rack power event, a cell-wide
        rollout gone bad). Every member is drained BEFORE anyone is
        re-placed, so no request ever migrates onto a sibling that is
        about to die in the same event — placements go cross-cell by
        construction. Returns requests migrated."""
        if self.cells is None:
            raise ValueError("kill_cell needs a cell topology "
                             "(pass cells=)")
        if cell not in self.cells:
            raise KeyError(f"unknown cell {cell!r}; known: "
                           f"{list(self.cells.cells)}")
        victims = [r for r in self._cell_members(cell) if r.state == LIVE]
        if not victims:
            raise ValueError(f"cell {cell!r} has no live replica to kill")
        drained: list[tuple[Request, Replica]] = []
        for rep in victims:
            for req in self._drain_out(rep, reason=reason):
                drained.append((req, rep))
        self._cells_down.add(cell)
        self._cell_kills += 1
        if self.telemetry is not None:
            self.telemetry.record(
                "cell", event="kill", cell=cell, round=self._rounds,
                replicas=[r.name for r in victims], reason=reason,
                requests_draining=len(drained))
        migrated = 0
        for req, rep in drained:
            migrated += self._migrate(req, rep)
        return migrated

    def _cell_sweep(self, fresh: list[Replica]) -> None:
        """Cell-sick aggregation: when MORE than ``cell_sick_threshold``
        of a cell's members are quarantined, the stragglers are presumed
        to share the correlated cause (rack power, bad rollout wave) and
        are quarantined too — the cell fails as a unit, exactly as it
        grows back as one. Only FRESH quarantines trigger the sweep, so
        a cell growing back member-by-member is never re-condemned for
        still being mostly down."""
        if self.cells is None:
            return
        for cell in sorted({r.cell for r in fresh if r.cell is not None}):
            members = self._cell_members(cell)
            down = sum(1 for r in members if r.state == QUARANTINED)
            if down / len(members) <= self.cell_sick_threshold:
                continue
            rest = [r for r in members if r.state == LIVE]
            if not rest:
                continue
            if self.telemetry is not None:
                self.telemetry.record(
                    "cell", event="sick", cell=cell, round=self._rounds,
                    quarantined=down, members=len(members),
                    swept=[r.name for r in rest])
            self._cells_down.add(cell)
            for rep in rest:
                if rep.state == LIVE:
                    self._quarantine_replica(rep, reason="cell-sick")

    def _quarantine_replica(self, rep: Replica, *, reason: str) -> int:
        migrated = 0
        for req in self._drain_out(rep, reason=reason):
            migrated += self._migrate(req, rep)
        return migrated

    def _drain_out(self, rep: Replica, *, reason: str) -> list[Request]:
        """Take ``rep`` out of service and return its drained requests
        (committed tokens + KV pages serialized by value) WITHOUT
        re-placing them — ``kill_cell`` drains a whole cell before any
        migration, single-replica paths migrate immediately."""
        drained = rep.engine.drain()
        rep.engine.clear_cache()     # raises if any page is still held
        rep.state = QUARANTINED
        rep.quarantined_round = self._rounds
        rep.kills += 1
        self._kills += 1
        self.kill_times[rep.name] = self._now
        self.pool.quarantine(rep.device_ids)
        self.pool.release(self._holder(rep))
        self._set_live_gauge()
        if self.telemetry is not None:
            self.telemetry.record(
                "event", message=f"fleet quarantine: replica {rep.name} "
                                 f"({reason}) devices {rep.device_ids} out "
                                 f"of service, {len(drained)} requests "
                                 f"draining")
        return drained

    def _migrate(self, req: Request, source: Replica) -> int:
        # A partitioned cell's replicas are unreachable for placements
        # too: the router cannot hand existing load to a cell it cannot
        # talk to (its residents keep decoding — they just get no new
        # neighbors until the heal).
        live = [r for r in self._live()
                if r.cell not in self._partitioned]
        if not live:
            # Nowhere to drain to: the request fails typed, exactly like
            # an engine kill — never silently dropped.
            req.state = RequestState.FAILED
            req.error = (f"fleet-killed: replica {source.name} quarantined "
                         f"with no reachable live peer")
            req.resume = None
            if self.journal is not None:
                self.journal.terminal(req.rid, "failed")
            tracing.rtrace(req, "failed", sink=self.telemetry,
                           error="no-live-replica")
            # The source engine's drain already closed its hop bill;
            # this terminal is the zero-cost fleet-side record that
            # pairs the rtrace terminal (utils/metering.py).
            if self._meter:
                emit_meter(self.telemetry, req, "failed",
                           replica="fleet")
            if self._slo_metrics:
                registry().counter("serve_requests_failed").inc()
            if self.telemetry is not None:
                self.telemetry.record(
                    "serve", event="failed", request=req.rid,
                    policy="fleet", error="no-live-replica",
                    detail=req.error, prompt_tokens=req.prompt_len,
                    new_tokens=len(req.generated))
            return 0
        # Prefer breaker-admitting peers, but never fail a migration
        # over an open breaker — a migrated request is existing load
        # being rescued, and the bounded queue is bypassed for the same
        # reason (enqueue force=True).
        candidates = [r for r in live
                      if self.breaker.allows(r.name, self._rounds)] or live
        self._emit_breaker_records()
        target, reason, loads = self.router.pick(req.prompt, candidates,
                                                 migrate=True, request=req,
                                                 sink=self.telemetry)
        pages = int(req.resume["k"].shape[1]) if req.resume else 0
        target.engine.enqueue(req, force=True)
        self._migrations += 1
        if self._slo_metrics:
            registry().counter("serve_router_assignments").inc()
            registry().counter("serve_migrations").inc()
        if self.telemetry is not None:
            # A drain placement is an assignment like any other: the
            # typed router record (reason=migrate, or `only` with one
            # peer) keeps the report's folded counts, the counter and
            # Router.assignments in agreement.
            self.telemetry.record(
                "router", request=req.rid, replica=target.name,
                reason=reason, round=self._rounds,
                loads={k: round(v, 3) for k, v in sorted(loads.items())})
            self.telemetry.record(
                "migration", request=req.rid, from_replica=source.name,
                to_replica=target.name, round=self._rounds,
                state=(req.resume["state"] if req.resume else "queued"),
                tokens_committed=len(req.generated), pages=pages,
                loads={k: round(v, 3) for k, v in sorted(loads.items())})
        return 1

    def _revive(self, rep: Replica) -> None:
        """Grow the replica back: reinstate + re-claim its exact device
        slice, then let the router resume sending it traffic (its cache
        is empty — the prefix tree refills from live traffic)."""
        self.pool.reinstate(rep.device_ids)
        self.pool.assign_ids(self._holder(rep), rep.device_ids)
        rep.state = LIVE
        rep.quarantined_round = None
        self.revive_times[rep.name] = self._now
        self._set_live_gauge()
        if self.telemetry is not None:
            self.telemetry.record(
                "event", message=f"fleet grow-back: replica {rep.name} "
                                 f"devices {rep.device_ids} back in "
                                 f"service")
        if (rep.cell is not None and rep.cell in self._cells_down
                and all(r.state == LIVE
                        for r in self._cell_members(rep.cell))):
            # The whole cell is back on its exact device slices: the
            # correlated failure's grow-back edge, as a unit.
            self._cells_down.discard(rep.cell)
            if self.telemetry is not None:
                self.telemetry.record(
                    "cell", event="grow-back", cell=rep.cell,
                    round=self._rounds,
                    replicas=[r.name
                              for r in self._cell_members(rep.cell)])

    # -- full fleet restart (serve/journal.py) -------------------------------

    @classmethod
    def recover(cls, params: dict, cfg, serve: ServeConfig,
                n_replicas: int, *, journal, telemetry=None, clock=None,
                **kw) -> "ServeFleet":
        """Restart a crashed fleet from its write-ahead journal: build a
        fresh fleet (same geometry, fresh engines, empty caches), then
        re-queue every journaled ACCEPTED request without a terminal at
        its disk watermark — replay prefill re-derives each committed
        prefix bitwise, terminals journaled before the crash are never
        re-served (exactly-once by rid dedup). Requests bypass
        :meth:`submit`: they are rescued load, not new demand — no
        re-stamp (the journaled trace id survives the restart), no
        queue bound, and ``journal.intent`` dedups their rids anyway.
        Torn trailing journal lines (a crash mid-write) are skipped by
        the fold; recovery proceeds on the surviving prefix."""
        t0 = time.monotonic()
        fleet = cls(params, cfg, serve, n_replicas, telemetry=telemetry,
                    clock=clock, journal=journal, **kw)
        st = journal.state()
        for rid in st.pending():
            rec = st.intents[rid]
            toks = st.tokens.get(rid, [])
            journal.discard_pending(rid)   # stale if the object survived
            req = Request(
                rid=rid,
                prompt=[int(t) for t in rec.get("prompt", ())],
                max_new_tokens=int(rec.get("max_new_tokens", 1)),
                arrival_s=float(rec.get("arrival_s", 0.0)),
                seed=int(rec.get("seed", 0)),
                priority=rec.get("priority", "interactive"),
                queue_budget_s=rec.get("queue_budget_s"),
                deadline_s=rec.get("deadline_s"),
                tenant=rec.get("tenant"))
            req.trace_id = rec.get("trace")
            req.generated = list(toks)
            req.replay = bool(toks)
            fleet._ids.add(rid)
            fleet._requests.append(req)
            # seq restarts at 1 in the new process: the joiner treats
            # the seq drop as an epoch boundary and links the restart
            # hop through this ``recovered`` event.
            tracing.rtrace(req, "recovered", sink=fleet.telemetry,
                           committed=len(toks), restart=True)
            fleet._pending.append(req)
        fleet._crash_recovered += len(fleet._pending)
        fleet.recovery_time_s += time.monotonic() - t0
        return fleet

    def _fail_fleet(self, detail: str) -> None:
        for rep in self.replicas:
            rep.engine._fail_inflight(detail)
        self._fail_pending(detail)

    def _fail_pending(self, detail: str) -> None:
        while self._pending:
            req = self._pending.popleft()
            req.state = RequestState.FAILED
            req.error = f"fleet-killed: {detail}"
            if self.journal is not None:
                self.journal.terminal(req.rid, "failed")
            tracing.rtrace(req, "failed", sink=self.telemetry,
                           error="fleet-killed")
            if self._meter:
                emit_meter(self.telemetry, req, "failed",
                           replica="fleet")
            t = req.tenant or "-"
            self._tenant_sheds[t] = self._tenant_sheds.get(t, 0) + 1
            if self._slo_metrics:
                registry().counter("serve_requests_failed").inc()
            if self.telemetry is not None:
                self.telemetry.record(
                    "serve", event="failed", request=req.rid,
                    policy="fleet", error="fleet-killed", detail=detail,
                    prompt_tokens=req.prompt_len,
                    new_tokens=len(req.generated))

    # -- results -------------------------------------------------------------

    def summary(self, *, record: bool = True) -> dict:
        """Fleet-level SLO + throughput rollup (one typed ``serve``
        summary record with ``policy="fleet"`` when recording)."""
        completed = [r for r in self._requests
                     if r.state is RequestState.COMPLETED]
        shed = [r for r in self._requests
                if r.state is RequestState.FAILED and r.shed_reason]
        failed = [r for r in self._requests
                  if r.state is RequestState.FAILED and not r.shed_reason]
        # Fleet-wide shed-by-reason and rejected counts: the fleet's
        # own (queue-full, fleet-queue expiry) plus every replica
        # engine's (post-dispatch expiries and aborts land there) — the
        # two must stay in one scope, or the report's "shed (rejected)"
        # line stops reconciling.
        shed_by_reason: dict[str, int] = dict(self._shed_by_reason)
        rejected = self._rejected
        for rep in self.replicas:
            rejected += rep.engine._rejected
            for reason, n in rep.engine._shed_by_reason.items():
                shed_by_reason[reason] = shed_by_reason.get(reason, 0) + n
        tokens = sum(len(r.generated) for r in completed)
        goodput_tokens = sum(
            len(r.generated) for r in completed
            if self.replicas[0].engine._in_deadline(r))
        ttft = [max(0.0, r.t_first_token - r.arrival_s) for r in completed
                if r.t_first_token is not None]
        waits = [max(0.0, r.t_admitted - r.arrival_s) for r in completed
                 if r.t_admitted is not None]
        token_lat = [
            (r.t_done - r.t_first_token) / (len(r.generated) - 1)
            for r in completed
            if len(r.generated) > 1 and r.t_first_token is not None]
        out = {
            "policy": "fleet",
            "n_replicas": len(self.replicas),
            "n_slots": self.serve.n_slots,
            "live_replicas": len(self._live()),
            "replicas": {r.name: {"state": r.state,
                                  "devices": list(r.device_ids),
                                  "kills": r.kills,
                                  "crashes": r.crashes}
                         for r in self.replicas},
            "requests_completed": len(completed),
            "requests_failed": len(failed),
            "requests_shed": len(shed),
            "requests_rejected": rejected,
            "shed_by_reason": dict(sorted(shed_by_reason.items())),
            "goodput_tokens": goodput_tokens,
            "goodput_tokens_per_s": (goodput_tokens / self._wall_s
                                     if self._wall_s > 0 else None),
            "breaker": {"opens": self.breaker.opens,
                        "states": self.breaker.snapshot()},
            "brownout_level_max": (
                max((r.engine.brownout.max_level_seen
                     for r in self.replicas
                     if r.engine.brownout is not None), default=None)
                if self.serve.brownout else None),
            "requests_migrated": sum(1 for r in self._requests
                                     if r.migrations > 0),
            "migrations": self._migrations,
            "replica_kills": self._kills,
            "replica_crashes": self._crashes,
            "crash_recovered": self._crash_recovered,
            "recovery_time_s": round(self.recovery_time_s, 6),
            "journal": (self.journal.summary()
                        if self.journal is not None else None),
            "tokens_generated": tokens,
            "wall_s": self._wall_s,
            "tokens_per_s": (tokens / self._wall_s if self._wall_s > 0
                             else None),
            "rounds": self._rounds,
            "router": {"assignments": dict(self.router.assignments),
                       "affinity_hits": self.router.affinity_hits,
                       "failovers": self.router.failovers},
            "cells": ({"layout": self.cells.as_dict(),
                       "live": self._live_cells(),
                       "cell_kills": self._cell_kills,
                       "partitioned": sorted(self._partitioned)}
                      if self.cells is not None else None),
            "ttft_s": summarize(ttft),
            "queue_wait_s": summarize(waits),
            "token_latency_s": summarize(token_lat),
            "metering": self._metering_summary() if self._meter else None,
        }
        if record and self.telemetry is not None:
            # Per-replica utilization records BEFORE the summary: the
            # capacity observatory (serve/capacity.py) reads both, and
            # crashed predecessors' duty history rides the same stream
            # under the replica name it served as.
            for m in self._meters():
                m.record_utilization(self.telemetry)
            self.telemetry.record("serve", event="summary", **out)
        return out

    def _metering_summary(self) -> dict | None:
        """Fleet metering rollup (utils/metering.py): the per-tenant
        cost + SLO-attainment table (every replica meter's closed bills
        plus the fleet's queue-only sheds), per-replica duty-cycle
        ledgers (a crashed predecessor's ledger folds into its replica
        name), per-cell and fleet-wide utilization, and the metering
        plane's own bookkeeping overhead — what ``dmp_capacity`` and
        the ``== capacity ==`` report section render."""
        meters = self._meters()
        if not meters:
            return None

        def _blank() -> dict:
            return {"requests": 0, "chip_s": 0.0, "page_s": 0.0,
                    "resident_s": 0.0, "tokens": 0, "good_tokens": 0,
                    "sheds": 0}

        by_tenant: dict[str, dict] = {}
        for m in meters:
            for tenant, row in m.by_tenant.items():
                agg = by_tenant.setdefault(tenant, _blank())
                for k, v in row.items():
                    agg[k] = agg.get(k, 0) + v
        for tenant, n in self._tenant_sheds.items():
            # Queue-only losses: no engine ever metered them, but the
            # tenant offered the demand — they count as requests and
            # sheds with zero chip time.
            agg = by_tenant.setdefault(tenant, _blank())
            agg["requests"] += n
            agg["sheds"] += n
        for agg in by_tenant.values():
            for k in ("chip_s", "page_s", "resident_s"):
                agg[k] = round(agg[k], 6)
            agg["goodput_fraction"] = (
                round(agg["good_tokens"] / agg["tokens"], 4)
                if agg["tokens"] else None)
        util: dict[str, dict] = {}
        for m in meters:
            name = m.replica or "-"
            u = m.utilization()
            if name in util:      # a crashed predecessor's ledger
                prev = util[name]
                for k, v in u.items():
                    prev[k] = prev.get(k, 0) + v
            else:
                util[name] = u
        return {
            "by_tenant": dict(sorted(by_tenant.items())),
            "utilization": util,
            "fleet_utilization": self._merged_utilization(meters),
            "cell_utilization": (
                {c: self._merged_utilization(self._meters(cell=c))
                 for c in self.cells.cells}
                if self.cells is not None else None),
            "chip_s": round(sum(m.chip_s_total() for m in meters), 6),
            "meter_write_s": round(sum(m.write_s for m in meters), 6),
        }
