"""Seeded production-traffic programs + the fleet's virtual clock.

"Millions of users" is not one Poisson trace (ROADMAP item 5b): real
serving load has diurnal curves, flash crowds, adversarial long-prompt
floods and mixed tenant classes with different SLOs. This module
synthesizes those shapes DETERMINISTICALLY — every program is a pure
function of its seed and knobs, returning a plain list of request dicts
(``rid``/``prompt``/``max_new``/``arrival_s``/``priority``/``seed`` plus
optional per-request ``queue_budget_s``/``deadline_s``) that replays
through :class:`~serve.fleet.ServeFleet` bit-for-bit on every run. The
scenario campaigns (scripts/dmp_soak.py ``--scenario
failover|flashcrowd|flood|diurnal``) gate on that replay determinism.

Arrivals come from a time-varying Poisson process via thinning (Lewis &
Shedler): draw candidate inter-arrivals at the program's peak rate, keep
each with probability ``rate(t)/peak`` — exact for any bounded rate
curve, and deterministic for a fixed ``random.Random`` seed.

:class:`SimClock` is the other half of determinism: a virtual monotonic
clock the fleet and its engines stamp time from (``clock=`` on
:class:`~serve.fleet.ServeFleet`). One fleet round advances one fixed
``dt``, idle gaps skip straight to the next arrival, and every TTFT /
deadline / goodput number is computed in virtual seconds — so a chaos
scenario's event schedule is identical on a loaded CI host and a fast
workstation. Without a SimClock the fleet keeps its real
``time.monotonic`` behavior.
"""

from __future__ import annotations

import random

__all__ = [
    "SimClock",
    "adversarial_flood",
    "diurnal",
    "flash_crowd",
    "merge_traces",
    "mixed_tenants",
    "poisson_arrivals",
]


class SimClock:
    """Virtual monotonic clock: starts at 0, advances only when told.

    Callable (``clock()`` -> current virtual seconds) so it drops in for
    ``time.monotonic``; :meth:`tick` advances one fleet round's ``dt``
    and :meth:`advance_to` skips idle gaps (never backwards — the
    monotonic contract).
    """

    def __init__(self, dt: float = 0.02):
        if dt <= 0:
            raise ValueError(f"dt must be > 0, got {dt}")
        self.dt = float(dt)
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float | None = None) -> float:
        self.t += self.dt if dt is None else float(dt)
        return self.t

    def advance_to(self, t: float) -> float:
        self.t = max(self.t, float(t))
        return self.t


def poisson_arrivals(rng: random.Random, rate_fn, horizon_s: float,
                     peak_rate: float) -> list[float]:
    """Arrival times on [0, horizon_s) of an inhomogeneous Poisson
    process with intensity ``rate_fn(t) <= peak_rate``, by thinning.
    Deterministic for a fixed rng state."""
    if peak_rate <= 0:
        return []
    out, t = [], 0.0
    while True:
        t += rng.expovariate(peak_rate)
        if t >= horizon_s:
            return out
        if rng.random() * peak_rate <= rate_fn(t):
            out.append(t)


# Per-SLO-class request shapes: (prompt_len range, max_new range). Sized
# for the tiny-model drill fleets (vocab 64, max_seq_len 64) — scenario
# scale comes from replica count x request count, not sequence length.
_CLASSES = {
    "interactive": {"prompt": (4, 10), "gen": (6, 14)},
    "batch": {"prompt": (8, 20), "gen": (10, 22)},
}


def _request(rng: random.Random, rid: str, arrival_s: float, *,
             priority: str, vocab: int, tenant: str | None = None,
             prompt_len: tuple[int, int] | None = None,
             gen: tuple[int, int] | None = None,
             queue_budget_s: float | None = None,
             deadline_s: float | None = None) -> dict:
    shape = _CLASSES[priority if priority in _CLASSES else "interactive"]
    plo, phi = prompt_len or shape["prompt"]
    glo, ghi = gen or shape["gen"]
    return {
        "rid": rid,
        "prompt": [rng.randrange(vocab) for _ in range(rng.randint(plo,
                                                                   phi))],
        "max_new": rng.randint(glo, ghi),
        "arrival_s": round(arrival_s, 6),
        "priority": priority,
        "seed": rng.randrange(2 ** 31),
        "tenant": tenant or priority,
        "queue_budget_s": queue_budget_s,
        "deadline_s": deadline_s,
    }


def merge_traces(*traces: list[dict]) -> list[dict]:
    """Compose programs: one trace, arrival-ordered (ties by rid so the
    merge itself is deterministic). Duplicate rids are rejected — every
    request must stay attributable to the program that emitted it."""
    out = [r for t in traces for r in t]
    rids = [r["rid"] for r in out]
    if len(set(rids)) != len(rids):
        dup = sorted({r for r in rids if rids.count(r) > 1})
        raise ValueError(f"duplicate rids across merged traces: {dup}")
    return sorted(out, key=lambda r: (r["arrival_s"], r["rid"]))


def diurnal(seed: int, *, horizon_s: float, base_rate: float,
            peak_rate: float, vocab: int = 64, prefix: str = "d",
            interactive_share: float = 0.7,
            queue_budget_s: float | None = None,
            deadline_s: float | None = None) -> list[dict]:
    """One compressed diurnal cycle: a sinusoid from ``base_rate``
    (midnight) up to ``peak_rate`` (midday, at ``horizon_s/2``) and back
    down, mixed interactive/batch."""
    import math

    rng = random.Random(seed)
    half = (peak_rate - base_rate) / 2.0

    def rate(t: float) -> float:
        return (base_rate + half
                * (1.0 - math.cos(2.0 * math.pi * t / horizon_s)))

    out = []
    for i, t in enumerate(poisson_arrivals(rng, rate, horizon_s,
                                           peak_rate)):
        prio = ("interactive" if rng.random() < interactive_share
                else "batch")
        out.append(_request(rng, f"{prefix}{i}", t, priority=prio,
                            vocab=vocab, queue_budget_s=queue_budget_s,
                            deadline_s=deadline_s))
    return out


def flash_crowd(seed: int, *, horizon_s: float, base_rate: float,
                spike_at_s: float, spike_s: float, spike_rate: float,
                vocab: int = 64, prefix: str = "f",
                queue_budget_s: float | None = None,
                deadline_s: float | None = None) -> list[dict]:
    """Steady interactive load with a rectangular arrival spike: rate
    jumps to ``spike_rate`` on [spike_at_s, spike_at_s + spike_s) — the
    everyone-hits-refresh event the brownout ladder exists for."""
    rng = random.Random(seed)

    def rate(t: float) -> float:
        return (spike_rate if spike_at_s <= t < spike_at_s + spike_s
                else base_rate)

    return [
        _request(rng, f"{prefix}{i}", t, priority="interactive",
                 vocab=vocab, queue_budget_s=queue_budget_s,
                 deadline_s=deadline_s)
        for i, t in enumerate(poisson_arrivals(
            rng, rate, horizon_s, max(base_rate, spike_rate)))]


def adversarial_flood(seed: int, *, horizon_s: float, base_rate: float,
                      flood_at_s: float, flood_n: int,
                      flood_prompt_len: tuple[int, int] = (24, 40),
                      flood_gen: tuple[int, int] = (16, 24),
                      flood_spacing_s: float = 0.0, vocab: int = 64,
                      prefix: str = "a",
                      queue_budget_s: float | None = None,
                      deadline_s: float | None = None) -> list[dict]:
    """Interactive background traffic plus an adversarial long-prompt
    burst: ``flood_n`` batch-class requests with outsized prompts and
    generations land (near-)simultaneously at ``flood_at_s`` — the
    page-pool-eating abuse shape the priority shed order and bounded
    queues must absorb without starving the interactive class."""
    rng = random.Random(seed)
    out = [
        _request(rng, f"{prefix}{i}", t, priority="interactive",
                 vocab=vocab, queue_budget_s=queue_budget_s,
                 deadline_s=deadline_s)
        for i, t in enumerate(poisson_arrivals(
            rng, lambda _t: base_rate, horizon_s, base_rate))]
    flood = [
        _request(rng, f"{prefix}flood{j}",
                 flood_at_s + j * flood_spacing_s, priority="batch",
                 vocab=vocab, tenant="flood",
                 prompt_len=flood_prompt_len, gen=flood_gen,
                 queue_budget_s=queue_budget_s, deadline_s=deadline_s)
        for j in range(flood_n)]
    return merge_traces(out, flood)


def mixed_tenants(seed: int, *, horizon_s: float,
                  tenants: dict[str, dict], vocab: int = 64,
                  prefix: str = "m") -> list[dict]:
    """Independent per-tenant Poisson streams with per-tenant SLO
    classes: each entry of ``tenants`` maps a name to ``{"rate",
    "priority"}`` plus optional ``queue_budget_s`` / ``deadline_s`` /
    ``prompt_len`` / ``gen`` overrides — the interactive tenants ride
    the PR 15 priority machinery (batch sheds first), the batch tenants
    soak up slack capacity."""
    streams = []
    for k, (name, spec) in enumerate(sorted(tenants.items())):
        rng = random.Random((seed * 1_000_003 + k) & 0x7FFFFFFF)
        rate = float(spec["rate"])
        streams.append([
            _request(rng, f"{prefix}-{name}-{i}", t,
                     priority=spec.get("priority", "interactive"),
                     vocab=vocab, tenant=name,
                     prompt_len=spec.get("prompt_len"),
                     gen=spec.get("gen"),
                     queue_budget_s=spec.get("queue_budget_s"),
                     deadline_s=spec.get("deadline_s"))
            for i, t in enumerate(poisson_arrivals(
                rng, lambda _t: rate, horizon_s, rate))])
    return merge_traces(*streams)
