"""Overload protection: the brownout ladder and the admission breaker.

Two pieces the serving stack leans on when offered load exceeds
capacity (docs/SERVING.md "Overload and graceful degradation"):

* :class:`BrownoutController` — a deterministic degradation ladder
  driven by the PR 12 :class:`~utils.alerts.AlertEngine`. Two rules
  watch the engine live: a burn-rate rule over per-request TTFT (the
  SLO the queue actually violates first) and a page-occupancy ceiling.
  While either fires, the controller walks one ladder level up per
  ``hold`` ticks; when both are healthy it walks back down. The steps,
  in order — each strictly sheds *optional work*, never changes tokens:

  1. ``spec-off``: stop dispatching speculative verify windows (the
     single-token decode program commits identical tokens — the pinned
     spec-on/off parity — at guaranteed-progress cost);
  2. ``prefill-share``: clamp ``prefill_chunks_per_iter`` to 1, so the
     resident batch's completions (which free pages) outrank new
     admissions' prefill;
  3. ``clamp-max-new``: cap newly admitted requests' ``max_new_tokens``
     at ``brownout_max_new`` — their reservation shrinks and they
     complete sooner. A clamped request's tokens are the bitwise PREFIX
     of its unclamped stream (tokens are a pure per-position function
     of (prompt, seed)), so degradation changes *which* requests
     complete and *when*, never the tokens they get.

  Every level move is a typed ``brownout`` record plus the
  ``serve_brownout_level`` gauge.

* :class:`CircuitBreaker` — the router-level per-replica admission
  breaker (serve/fleet.py): repeated admission failures (a full
  submission queue, or the injected ``admission_fail`` chaos kind)
  open the breaker and the router stops offering that replica traffic
  — *distinct from health quarantine*: the replica keeps serving its
  residents, it just takes no new work. After ``cooldown_rounds`` the
  breaker goes half-open and admits one probe; a success closes it, a
  failure re-opens. Transitions are typed ``breaker`` records.
"""

from __future__ import annotations

from distributed_model_parallel_tpu.utils.alerts import (
    AlertEngine,
    BurnRate,
    GaugeCeiling,
)
from distributed_model_parallel_tpu.utils import tracing

__all__ = ["BrownoutController", "CircuitBreaker", "LADDER",
           "apply_max_new_cap"]

# The degradation ladder, mildest first; level N applies steps [0, N).
LADDER = ("spec-off", "prefill-share", "clamp-max-new")


class DrainingBurnRate(BurnRate):
    """BurnRate that treats an empty/thin window as HEALTHY.

    The alerting engine's rule withholds a verdict below its evidence
    floor — right for an operator page, wrong for a control loop: a
    brownout that can only resolve while violations keep arriving never
    resolves after the load drops (the windows just drain). Here the
    burn is computed over whatever samples remain; firing still needs
    ``min_requests`` of evidence, but resolution does not — once the
    backlog drains, the ladder walks back.
    """

    def evaluate(self, state, now, signals):
        samples = state["samples"]
        while samples and now - samples[0][0] > self.long_s:
            samples.popleft()

        def burn(horizon: float) -> float:
            window = [bad for ts, bad in samples if now - ts <= horizon]
            if not window:
                return 0.0
            return (sum(window) / len(window)) / self.budget

        short, long_ = burn(self.short_s), burn(self.long_s)
        breached = (short > self.burn and long_ > self.burn
                    and len(samples) >= self.min_requests)
        return breached, {
            "value": round(short, 4), "threshold": self.burn,
            "burn_long": round(long_, 4), "metric": self.metric,
            "target_s": self.target_s}


class BrownoutController:
    """Deterministic degradation ladder over one engine (module
    docstring). The engine feeds it completions and occupancy and ticks
    it once per iteration; :meth:`tick` returns the transition payload
    (the typed ``brownout`` record body) when the level moved."""

    def __init__(self, serve):
        if serve.brownout_max_new < 1:
            raise ValueError(f"brownout_max_new must be >= 1, got "
                             f"{serve.brownout_max_new}")
        if serve.brownout_hold_iters < 1:
            raise ValueError(f"brownout_hold_iters must be >= 1, got "
                             f"{serve.brownout_hold_iters}")
        short = float(serve.brownout_window_s)
        self._max_new = int(serve.brownout_max_new)
        self.alerts = AlertEngine([
            DrainingBurnRate(
                metric="ttft_s", target_s=serve.brownout_ttft_target_s,
                budget=serve.brownout_budget, burn=1.0,
                short_s=short, long_s=4.0 * short, min_requests=4,
                name="brownout_ttft_burn", scope="global"),
            GaugeCeiling(signal="page_occupancy",
                         ceiling=serve.brownout_occupancy_ceiling,
                         name="brownout_page_saturation"),
        ])
        self.level = 0
        self.max_level = len(LADDER)
        self.max_level_seen = 0
        self.hold = int(serve.brownout_hold_iters)
        self.transitions: list[dict] = []
        self._ticks = 0
        self._last_move = -(10 ** 9)

    # -- feeds (the engine's per-iteration hooks) ---------------------------

    def observe_completed(self, ttft_s: float | None, now: float) -> None:
        if ttft_s is not None:
            self.alerts.observe({"kind": "serve", "event": "completed",
                                 "ttft_s": float(ttft_s),
                                 "ts": float(now)})

    def observe_occupancy(self, occupancy: float) -> None:
        self.alerts.set_signal("page_occupancy", float(occupancy))

    # -- the ladder ---------------------------------------------------------

    @property
    def spec_enabled(self) -> bool:
        """Level >= 1 stops dispatching speculative verify windows."""
        return self.level < 1

    @property
    def prefill_full_share(self) -> bool:
        """Level >= 2 clamps prefill_chunks_per_iter to 1."""
        return self.level < 2

    @property
    def max_new_cap(self) -> int | None:
        """Level >= 3 caps newly admitted requests' max_new_tokens."""
        return self._max_new if self.level >= 3 else None

    def tick(self, now: float) -> dict | None:
        """One evaluation pass at engine clock ``now``; walks the ladder
        one level (at most) per ``hold`` ticks and returns the
        transition payload, or ``None`` when the level held."""
        self.alerts.tick(now)
        self._ticks += 1
        firing = [f["rule"] for f in self.alerts.firing]
        if self._ticks - self._last_move < self.hold:
            return None
        old = self.level
        if firing and self.level < self.max_level:
            self.level += 1
        elif not firing and self.level > 0:
            self.level -= 1
        else:
            return None
        self._last_move = self._ticks
        self.max_level_seen = max(self.max_level_seen, self.level)
        transition = {
            "level": self.level, "previous": old,
            "direction": "degrade" if self.level > old else "recover",
            "applied": list(LADDER[:self.level]),
            "firing": firing,
        }
        self.transitions.append(transition)
        return transition

    def summary(self) -> dict:
        return {"level": self.level,
                "max_level_seen": self.max_level_seen,
                "transitions": len(self.transitions)}


def apply_max_new_cap(brown: BrownoutController, queue, now: float,
                      sink=None, trace_fields=None) -> int:
    """Apply the level-3 brownout clamp to arrived queued requests: cap
    ``max_new_tokens`` at the controller's ``max_new_cap``, remembering
    the original ask in ``max_new_requested``. Migrated-in requests
    (``resume`` payload) are exempt — their generation length is already
    committed on the source replica. Each newly clamped request gets a
    ``clamp`` rtrace record (the brownout's per-request attribution);
    returns how many were clamped this pass. A no-op below level 3."""
    cap = brown.max_new_cap
    if cap is None:
        return 0
    clamped = 0
    for r in queue:
        if r.arrival_s <= now and r.max_new_tokens > cap \
                and r.resume is None:
            if r.max_new_requested is None:
                r.max_new_requested = r.max_new_tokens
            r.max_new_tokens = cap
            clamped += 1
            tracing.rtrace(r, "clamp", sink=sink, cap=cap, level=3,
                           requested=r.max_new_requested,
                           **(trace_fields or {}))
    return clamped


# ---------------------------------------------------------------------------
# the admission circuit breaker
# ---------------------------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"


class CircuitBreaker:
    """Per-replica admission circuit breaker (module docstring).

    Deterministic: state moves only on :meth:`note` (admission
    outcomes) and :meth:`allows` (the cooldown expiring at a round
    count) — no wall clock. Transitions accumulate in
    :attr:`transitions` for the fleet to drain into typed ``breaker``
    records.
    """

    def __init__(self, *, threshold: int = 3, cooldown_rounds: int = 8):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if cooldown_rounds < 1:
            raise ValueError(f"cooldown_rounds must be >= 1, got "
                             f"{cooldown_rounds}")
        self.threshold = threshold
        self.cooldown_rounds = cooldown_rounds
        self.opens = 0
        self.transitions: list[dict] = []
        self._cells: dict[str, dict] = {}

    def _cell(self, name: str) -> dict:
        return self._cells.setdefault(
            name, {"state": CLOSED, "fails": 0, "opened_round": None})

    def state(self, name: str) -> str:
        return self._cell(name)["state"]

    def snapshot(self) -> dict[str, str]:
        return {name: c["state"] for name, c in sorted(self._cells.items())}

    def group_state(self, names) -> str:
        """Aggregated state over a group of replicas (a serving cell's
        per-cell rollup, serve/fleet.py): ``open`` when EVERY member's
        breaker is open (the whole group refuses traffic), ``degraded``
        when any member is open or half-open, else ``closed``."""
        states = [self.state(n) for n in names]
        if states and all(s == OPEN for s in states):
            return OPEN
        if any(s in (OPEN, HALF_OPEN) for s in states):
            return "degraded"
        return CLOSED

    def _transition(self, name: str, state: str, rnd: int,
                    fails: int) -> None:
        self.transitions.append({"replica": name, "state": state,
                                 "round": rnd, "failures": fails})

    def allows(self, name: str, rnd: int) -> bool:
        """May the router offer replica ``name`` traffic at round
        ``rnd``? An open breaker goes half-open (probe allowed) once
        the cooldown has passed."""
        c = self._cell(name)
        if (c["state"] == OPEN
                and rnd - c["opened_round"] >= self.cooldown_rounds):
            c["state"] = HALF_OPEN
            self._transition(name, HALF_OPEN, rnd, c["fails"])
        return c["state"] != OPEN

    def note(self, name: str, ok: bool, rnd: int) -> None:
        """Record an admission outcome for ``name``: ``threshold``
        consecutive failures (or one half-open probe failure) open the
        breaker; any success closes it."""
        c = self._cell(name)
        if ok:
            if c["state"] != CLOSED:
                c.update(state=CLOSED, fails=0, opened_round=None)
                self._transition(name, CLOSED, rnd, 0)
            else:
                c["fails"] = 0
            return
        c["fails"] += 1
        if c["state"] == HALF_OPEN or (c["state"] == CLOSED
                                       and c["fails"] >= self.threshold):
            c.update(state=OPEN, opened_round=rnd)
            self.opens += 1
            self._transition(name, OPEN, rnd, c["fails"])

    def drain_transitions(self) -> list[dict]:
        out, self.transitions = self.transitions, []
        return out
