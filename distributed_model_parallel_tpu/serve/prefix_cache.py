"""Radix tree over token prefixes — the prefix-sharing index.

vLLM/SGLang-style automatic prefix caching at **page granularity**: each
tree node is one full page of tokens (an edge label of ``page_size``
token ids) mapped to the physical pool page holding that span's KV. A
prompt's longest cached prefix is the deepest path whose page-sized
token chunks all match; the engine retains those pages for the new
sequence and prefills only the suffix.

The tree holds its own reference on every page it indexes
(``PagePool.retain``), so cached prefixes survive the sequences that
wrote them. When admission needs room, :meth:`evict` frees
**leaf-first, least-recently-matched** pages whose only remaining
reference is the tree's — a page some resident sequence still reads
(refcount > 1) is never evicted, and an interior node is evictable only
after its whole subtree is gone (children extend the parent's token
span; orphaning them would corrupt matching).

Everything here is deterministic: matching is exact token equality,
recency is a logical clock bumped per match (never wall time), and
eviction tie-breaks on insertion order — the same request sequence
always leaves the same tree.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class _Node:
    page: int                        # physical pool page id
    last_used: int                   # logical clock of the last match
    seq: int                         # insertion order (eviction tie-break)
    key: tuple[int, ...] = ()        # edge label under the parent
    parent: "_Node | None" = None    # None at root level
    children: dict[tuple[int, ...], "_Node"] = dataclasses.field(
        default_factory=dict)


class PrefixCache:
    """Page-granular radix tree over token prefixes (see module doc)."""

    def __init__(self, pool, page_size: int):
        self.pool = pool
        self.page_size = page_size
        self._root: dict[tuple[int, ...], _Node] = {}
        self._clock = 0
        self._seq = 0
        self._pages = 0                  # nodes (== pages) in the tree
        # cumulative counters for telemetry/statusz
        self.hits = 0
        self.hit_tokens = 0
        self.evictions = 0

    def __len__(self) -> int:
        return self._pages

    def _keys(self, tokens: list[int]):
        """Full-page token chunks of ``tokens`` (the partial tail page is
        never indexable — its span isn't a complete edge label)."""
        p = self.page_size
        for i in range(len(tokens) // p):
            yield tuple(tokens[i * p:(i + 1) * p])

    # -- match --------------------------------------------------------------

    def match(self, tokens: list[int], *, touch: bool = True) -> list[int]:
        """Physical pages of the longest cached full-page prefix of
        ``tokens``, root-down. ``touch=True`` bumps the matched path's
        recency (an admission); ``touch=False`` is the side-effect-free
        peek the scheduler's fit check uses."""
        if touch:
            self._clock += 1
        pages: list[int] = []
        level = self._root
        for key in self._keys(tokens):
            node = level.get(key)
            if node is None:
                break
            if touch:
                node.last_used = self._clock
            pages.append(node.page)
            level = node.children
        if touch and pages:
            self.hits += 1
            self.hit_tokens += len(pages) * self.page_size
        return pages

    def touch_path(self, tokens: list[int], n_pages: int) -> None:
        """Bump recency (and hit accounting) for the first ``n_pages``
        of ``tokens``'s cached path — the admission-time side effect of
        a successful match, split out so the fit check can peek once
        with ``touch=False`` and the admission needs only this cheap
        path walk instead of a second full match."""
        if n_pages < 1:
            return
        self._clock += 1
        level = self._root
        for i, key in enumerate(self._keys(tokens)):
            if i >= n_pages:
                break
            node = level[key]
            node.last_used = self._clock
            level = node.children
        self.hits += 1
        self.hit_tokens += n_pages * self.page_size

    # -- insert -------------------------------------------------------------

    def insert(self, tokens: list[int], pages: list[int]) -> int:
        """Index ``tokens``'s full pages, adopting from ``pages`` (the
        owning sequence's table, logical order). Existing nodes win —
        a concurrent writer of the same prefix keeps its pages and ours
        simply drop with our table's release. Every newly adopted page
        is retained by the tree. Returns the number adopted."""
        self._clock += 1
        adopted = 0
        level = self._root
        parent: _Node | None = None
        for i, key in enumerate(self._keys(tokens)):
            node = level.get(key)
            if node is None:
                if i >= len(pages):
                    raise ValueError(
                        f"prefix of {len(tokens)} tokens spans more full "
                        f"pages than the sequence's table ({len(pages)})")
                self._seq += 1
                node = _Node(page=pages[i], last_used=self._clock,
                             seq=self._seq, key=key, parent=parent)
                self.pool.retain([node.page])
                level[key] = node
                self._pages += 1
                adopted += 1
            else:
                node.last_used = self._clock
            parent = node
            level = node.children
        return adopted

    # -- eviction -----------------------------------------------------------

    def _nodes(self):
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def _evictable_now(self, node: _Node) -> bool:
        """A leaf whose only reference is the tree's."""
        return not node.children and self.pool.refcount(node.page) == 1

    def evictable_pages(self, exclude: set[int] | None = None) -> int:
        """Pages the tree could eventually free (cascading leaf-first):
        a node counts iff its page's only reference is the tree's, it is
        not in ``exclude`` (a path about to be retained by an admission),
        and its whole subtree counts too — a pinned descendant pins every
        ancestor, since interior nodes cannot be orphaned."""
        exclude = exclude or set()

        def count(node: _Node) -> tuple[int, bool]:
            n, all_ok = 0, True
            for child in node.children.values():
                cn, ok = count(child)
                n += cn
                all_ok = all_ok and ok
            mine = (self.pool.refcount(node.page) == 1
                    and node.page not in exclude)
            return n + (1 if mine and all_ok else 0), mine and all_ok

        return sum(count(n)[0] for n in self._root.values())

    def evict(self, n: int) -> list[int]:
        """Free up to ``n`` tree-only pages, least-recently-matched leaf
        first (insertion order breaks ties). ONE tree walk seeds the
        candidate leaves; freeing a leaf may expose its parent, which
        joins the candidates incrementally — so a cold chain unwinds
        fully without re-scanning the tree per freed page (the serving
        hot path pays O(nodes + k·candidates), not O(nodes·k)).
        Returns the freed physical pages (now on the pool free list)."""
        freed: list[int] = []
        cands = {id(nd): nd for nd in self._nodes()
                 if self._evictable_now(nd)}
        while len(freed) < n and cands:
            node = min(cands.values(),
                       key=lambda nd: (nd.last_used, nd.seq))
            del cands[id(node)]
            level = (node.parent.children if node.parent is not None
                     else self._root)
            del level[node.key]
            self._pages -= 1
            self.pool.free([node.page])
            self.evictions += 1
            freed.append(node.page)
            parent = node.parent
            if parent is not None and self._evictable_now(parent):
                cands[id(parent)] = parent
        return freed
