"""Paged prefill/decode forward over ``models/transformer`` params.

Two jitted programs serve every request shape:

* the **prefill step** runs one fixed-size chunk of one request's prompt
  against the growing paged cache (the final partial chunk is padded and
  its writes dropped), so any prompt length reuses one compiled program —
  the compile-cache story behind the CLI satellite;
* the **decode step** advances every active slot one token. It is
  compiled at the engine's fixed slot width with idle slots masked
  (writes dropped via out-of-range page ids), which is what makes a
  request's tokens independent of who shares the batch: same program,
  row-independent math, own pages — a mid-batch join decodes bitwise
  what a solo run would.

The block math is ``models/transformer``'s own pieces (``_qkv_proj``,
``apply_rope``, ``layer_norm``, ``_ffn``, ``unembed``) with the dense
cache's write/read swapped for the page pool
(``ops/paged_attention``) — the training/decode definitions stay single-
source. MoE FFNs are rejected by the engine: expert capacity dropping
couples co-resident tokens, which would break per-request determinism.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_model_parallel_tpu.models.transformer import (
    TransformerConfig,
    _ffn,
    _qkv_proj,
    apply_rope,
    layer_norm,
    make_sampler,
    unembed,
)
from distributed_model_parallel_tpu.ops.paged_attention import (
    paged_attention,
)


def paged_block(bp: dict, ck: jax.Array, cv: jax.Array, layer: jax.Array,
                x: jax.Array, positions: jax.Array, write_pages: jax.Array,
                write_offsets: jax.Array, tables: jax.Array,
                lengths: jax.Array, cfg: TransformerConfig, *,
                impl: str) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One transformer block over the paged cache.

    x: [B, C, d]; positions: [B, C] absolute; write_pages/write_offsets:
    [B, C] physical (page, offset) per token — an out-of-range page id
    drops the write (idle slots, prompt padding); tables: [B, N];
    lengths: [B] valid K prefix (after this step's writes); ck/cv:
    [L, P, page, Hkv, Dh] pools, ``layer`` (traced) selects the slab.
    The paged counterpart of ``transformer._cached_block``.
    """
    b, c = x.shape[:2]
    h = layer_norm(x, bp["ln1_scale"], bp["ln1_bias"])
    q, k, v = _qkv_proj(bp, h, cfg)          # q:[B,C,H,Dh] kv:[B,C,Hkv,Dh]
    if cfg.pos_embedding == "rope":
        # Per-row positions: the continuous batch has every row at its
        # own offset. The cache stores rotated keys, like the dense path.
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
    ck = ck.at[layer, write_pages, write_offsets].set(
        k.astype(ck.dtype), mode="drop")
    cv = cv.at[layer, write_pages, write_offsets].set(
        v.astype(cv.dtype), mode="drop")
    kp = jax.lax.dynamic_index_in_dim(ck, layer, 0, keepdims=False)
    vp = jax.lax.dynamic_index_in_dim(cv, layer, 0, keepdims=False)
    o = paged_attention(q, kp, vp, tables, positions, lengths,
                        window=cfg.attn_window, impl=impl)
    x = x + o.reshape(b, c, -1) @ bp["wo"]
    h = layer_norm(x, bp["ln2_scale"], bp["ln2_bias"])
    h, _ = _ffn(bp, h, cfg, tp_axis=None, ep_axis=None)
    return x + h, ck, cv


def _layers_scan(params: dict, ck, cv, x, positions, write_pages,
                 write_offsets, tables, lengths, cfg, impl):
    def layer(carry, xs):
        x, ck, cv = carry
        bp, li = xs
        x, ck, cv = paged_block(bp, ck, cv, li, x, positions, write_pages,
                                write_offsets, tables, lengths, cfg,
                                impl=impl)
        return (x, ck, cv), None

    (x, ck, cv), _ = jax.lax.scan(
        layer, (x, ck, cv),
        (params["blocks"], jnp.arange(cfg.n_layers)))
    return x, ck, cv


def _embed_rows(params: dict, tokens: jax.Array, positions: jax.Array,
                cfg: TransformerConfig) -> jax.Array:
    """[B, C] tokens at per-row absolute positions -> [B, C, d]. Learned
    positions gather per row (clipped: padded prefill tails may index
    past the table; their rows are never read)."""
    x = params["embed"][tokens]
    if cfg.pos_embedding == "learned":
        idx = jnp.clip(positions, 0, cfg.max_seq_len - 1)
        x = x + params["pos"][idx]
    return x


@functools.lru_cache(maxsize=64)
def make_prefill_step(cfg: TransformerConfig, *, page_size: int,
                      n_pages: int, chunk: int, impl: str,
                      temperature: float = 0.0, top_k: int | None = None,
                      top_p: float | None = None):
    """One request's prompt chunk against the paged cache.

    Returns ``step(params, ck, cv, tokens [1, chunk], pos0, n_valid,
    table [N], key) -> (ck, cv, next_token [1])``. ``pos0``/``n_valid``
    are traced scalars, so every chunk of every prompt length hits one
    compiled program. The returned token is sampled from the last VALID
    position's logits — meaningful only on the final chunk (it becomes
    the request's first generated token, ``generate()``'s ``tok0``);
    earlier chunks discard it.
    """
    sampler = make_sampler(cfg, temperature, top_k, top_p)
    sampled = temperature > 0

    def step(params, ck, cv, tokens, pos0, n_valid, table, key):
        positions = (pos0 + jnp.arange(chunk))[None]          # [1, C]
        valid = (jnp.arange(chunk) < n_valid)[None]           # [1, C]
        pages = table[jnp.clip(positions // page_size, 0,
                               table.shape[0] - 1)]
        pages = jnp.where(valid, pages, n_pages)              # drop pads
        offsets = positions % page_size
        lengths = (pos0 + n_valid)[None]                      # [1]
        x = _embed_rows(params, tokens, positions, cfg)
        x, ck, cv = _layers_scan(params, ck, cv, x, positions, pages,
                                 offsets, table[None], lengths, cfg, impl)
        xl = jax.lax.dynamic_slice_in_dim(x, n_valid - 1, 1, axis=1)
        logits = unembed(params, xl)[:, 0]                    # [1, V]
        sub = (jax.random.fold_in(key, pos0 + n_valid - 1) if sampled
               else key)
        return ck, cv, sampler(logits, sub)

    return jax.jit(step, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=64)
def make_verify_step(cfg: TransformerConfig, *, page_size: int,
                     n_pages: int, width: int, impl: str,
                     temperature: float = 0.0, top_k: int | None = None,
                     top_p: float | None = None):
    """Speculative-decoding verification: ``width`` tokens per slot in
    ONE batched forward (the last committed token plus ``width - 1``
    draft tokens), emitting the model's own choice at every position.

    Returns ``step(params, ck, cv, tokens [B, W], positions [B],
    n_valid [B], tables [B, N], active [B] bool, keys [B]) ->
    (ck, cv, out_tokens [B, W])`` where ``out_tokens[b, i]`` is the
    token the model picks for absolute position ``positions[b] + i + 1``
    given the window prefix through ``i`` — exactly what sequential
    decode would emit there, because each query row's math is
    position-independent of batch shape and sampling folds the
    per-request key with the query position (the same fold the
    single-token decode step uses). The host-side accept rule
    (serve/engine.py) keeps ``out[i]`` only while the drafts before it
    matched, so spec-on and spec-off token streams are identical by
    construction.

    ``n_valid`` clamps each row's window (a request near its token
    budget processes fewer positions); writes past it — and every write
    of an idle row — are dropped via out-of-range page ids, the same
    masking idiom as prefill padding.
    """
    sampler = make_sampler(cfg, temperature, top_k, top_p)
    sampled = temperature > 0

    def window_sample(logits, keys, positions):
        # logits [B, W, V]; fold each row's key with each query position
        # (positions[b] + i) — bitwise the decode/prefill fold for the
        # same (seed, position).
        if not sampled:
            b, w, v = logits.shape
            return sampler(logits.reshape(b * w, v), None).reshape(b, w)

        def row(lg, key, p0):
            subs = jax.vmap(jax.random.fold_in,
                            in_axes=(None, 0))(key, p0 + jnp.arange(width))
            return jax.vmap(lambda l, s: sampler(l[None], s)[0])(lg, subs)

        return jax.vmap(row)(logits, keys, positions)

    def step(params, ck, cv, tokens, positions, n_valid, tables, active,
             keys):
        pos = positions[:, None] + jnp.arange(width)[None]    # [B, W]
        valid = jnp.logical_and(
            jnp.arange(width)[None] < n_valid[:, None],
            active[:, None])                                  # [B, W]
        pages = jnp.take_along_axis(
            tables, jnp.clip(pos // page_size, 0, tables.shape[1] - 1),
            axis=1)
        pages = jnp.where(valid, pages, n_pages)              # drop invalid
        offsets = pos % page_size
        lengths = positions + n_valid                         # [B]
        x = _embed_rows(params, tokens, pos, cfg)
        x, ck, cv = _layers_scan(params, ck, cv, x, pos, pages, offsets,
                                 tables, lengths, cfg, impl)
        logits = unembed(params, x)                           # [B, W, V]
        return ck, cv, window_sample(logits, keys, positions)

    return jax.jit(step, donate_argnums=(1, 2))


@functools.lru_cache(maxsize=64)
def make_decode_step(cfg: TransformerConfig, *, page_size: int,
                     n_pages: int, impl: str, temperature: float = 0.0,
                     top_k: int | None = None, top_p: float | None = None):
    """One token for every slot of the fixed-width decode batch.

    Returns ``step(params, ck, cv, tokens [B], positions [B], tables
    [B, N], active [B] bool, keys [B]) -> (ck, cv, next_tokens [B])``.
    Idle slots compute garbage rows (masked writes, outputs ignored) so
    the program never re-specializes on occupancy. Sampling folds each
    row's key with its own position — a request's stream is a pure
    function of (request seed, position), independent of the batch.
    """
    sampler = make_sampler(cfg, temperature, top_k, top_p)
    sampled = temperature > 0

    def row_sample(logits, keys, positions):
        if not sampled:
            return sampler(logits, None)
        subs = jax.vmap(jax.random.fold_in)(keys, positions)
        return jax.vmap(lambda lg, s: sampler(lg[None], s)[0])(logits, subs)

    def step(params, ck, cv, tokens, positions, tables, active, keys):
        pos2 = positions[:, None]                             # [B, 1]
        pages = jnp.take_along_axis(tables, pos2 // page_size, axis=1)
        pages = jnp.where(active[:, None], pages, n_pages)    # idle: drop
        offsets = pos2 % page_size
        lengths = positions + 1
        x = _embed_rows(params, tokens[:, None], pos2, cfg)
        x, ck, cv = _layers_scan(params, ck, cv, x, pos2, pages, offsets,
                                 tables, lengths, cfg, impl)
        logits = unembed(params, x)[:, 0]                     # [B, V]
        return ck, cv, row_sample(logits, keys, positions)

    return jax.jit(step, donate_argnums=(1, 2))
