"""Continuous-batching decode service: paged KV cache + inflight scheduler.

The training side of this repo already had the decode kernels
(``ops/pallas_attention.py``, ``models/transformer.generate``); this
package turns them into a serving engine:

* :mod:`serve.paged_kv` — the device-resident page pool and host-side
  page tables (vLLM-style paged KV cache);
* :mod:`serve.model` — the paged prefill/decode forward over
  ``models/transformer`` params (one jitted program each, any prompt
  length — the compile-cache story);
* :mod:`serve.scheduler` — request queue + iteration-level
  (continuous/Orca-style) batching: admission by free pages (billed
  post-sharing), mid-batch join/evict, chunked prefill interleaved with
  decode;
* :mod:`serve.prefix_cache` — the radix tree over token prefixes:
  refcounted copy-on-write page sharing, so a request whose prompt is
  cached admits with near-zero prefill (vLLM/SGLang-style);
* :mod:`serve.spec` — the n-gram self-drafting proposer behind
  speculative decoding (``ServeConfig.spec_k``): k drafted tokens per
  iteration, verified in one batched forward, committed only when the
  model's own choice agrees — spec-on/off token streams are identical;
* :mod:`serve.engine` — the loop wiring them together, with per-request
  SLO accounting (TTFT, per-token latency, queue wait, cache hit rate,
  draft accept rate) in the telemetry registry and typed ``serve``
  records;
* :mod:`serve.router` — SLO-aware replica selection:
  power-of-two-choices over live queue depth + page occupancy with a
  prefix-affinity bonus (deterministic, seeded);
* :mod:`serve.fleet` — the self-healing multi-replica tier: N engine
  replicas on disjoint device-pool slices behind the router, wired into
  the device-health sentinel — a degrading replica is quarantined and
  its in-flight requests migrate live to peers (KV pages exported by
  value, re-imported at the exact committed position), then the replica
  grows back after probation;
* :mod:`serve.cells` — cell topology: replicas grouped into named
  cells that fail (``kill_cell`` / ``slow_cell`` / ``partition``,
  utils/faults.py) and grow back as correlated units, with
  deterministic home-cell routing + cross-cell failover;
* :mod:`serve.traffic` — seeded production-traffic programs (diurnal,
  flash crowd, adversarial flood, mixed tenants) and the virtual
  :class:`~serve.traffic.SimClock` the chaos scenarios replay on.

See docs/SERVING.md for the anatomy, the BENCH_serve recipe, the fleet
kill-drill recipe and the scenario catalog.
"""

from distributed_model_parallel_tpu.serve.cells import (  # noqa: F401
    CellDirectory,
)
from distributed_model_parallel_tpu.serve.engine import (  # noqa: F401
    Engine,
    EngineKilled,
    ServeConfig,
)
from distributed_model_parallel_tpu.serve.fleet import (  # noqa: F401
    Replica,
    ServeFleet,
)
from distributed_model_parallel_tpu.serve.overload import (  # noqa: F401
    BrownoutController,
    CircuitBreaker,
)
from distributed_model_parallel_tpu.serve.router import (  # noqa: F401
    Router,
)
from distributed_model_parallel_tpu.serve.paged_kv import (  # noqa: F401
    PagedKVCache,
    PagePool,
    PagePoolError,
)
from distributed_model_parallel_tpu.serve.prefix_cache import (  # noqa: F401
    PrefixCache,
)
from distributed_model_parallel_tpu.serve.spec import (  # noqa: F401
    NGramProposer,
)
from distributed_model_parallel_tpu.serve.scheduler import (  # noqa: F401
    Request,
    Scheduler,
)
from distributed_model_parallel_tpu.serve.traffic import (  # noqa: F401
    SimClock,
    adversarial_flood,
    diurnal,
    flash_crowd,
    merge_traces,
    mixed_tenants,
)
