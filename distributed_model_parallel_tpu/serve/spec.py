"""Self-drafting (n-gram lookup) proposer for speculative decoding.

No second model: the draft for the next ``k`` tokens is the continuation
of the most recent earlier occurrence of the sequence's current suffix
n-gram — "prompt lookup decoding". Real traffic (and small models run
greedily) is repetitive enough that this is free accuracy: templated
spans, quoted context, and decode loops all re-emit spans the sequence
has already seen.

Correctness never depends on the proposal: the engine's verify step
(serve/model.make_verify_step) computes the model's own token at every
drafted position and commits exactly the tokens the sequential decode
path would have produced — a bad draft only costs wasted verify width,
never a wrong token (docs/SERVING.md, "Speculative decoding").

The proposer is deterministic and incremental: a pure function of the
committed token stream, indexed as tokens arrive (O(orders) per token),
so replays — and the engine's pinned-determinism contract — hold with
drafting on.
"""

from __future__ import annotations


class NGramProposer:
    """Longest-suffix n-gram lookup over one sequence's committed tokens.

    ``orders`` n-gram sizes are tried longest-first; for each, the index
    maps the gram to its most recent end position. The draft is the
    ``k`` tokens that followed the match. ``propose`` returns ``[]``
    when no suffix recurs — the engine then runs an undrafted verify
    step (pad tokens can only be committed if the model itself picks
    them, so an empty draft degrades to plain decode).
    """

    def __init__(self, k: int, max_order: int = 3):
        if k < 1:
            raise ValueError(f"draft length k must be >= 1, got {k}")
        if max_order < 1:
            raise ValueError(f"max_order must be >= 1, got {max_order}")
        self.k = k
        self.orders = list(range(max_order, 0, -1))
        self.tokens: list[int] = []
        # per order: gram -> (latest end index, previous end index)
        self._index: dict[int, dict[tuple[int, ...], tuple[int, int]]] = {
            n: {} for n in self.orders}

    def extend(self, tokens: list[int]) -> None:
        """Commit tokens (prompt at admission, accepted tokens per
        verify round) and index every new suffix gram."""
        for t in tokens:
            self.tokens.append(int(t))
            i = len(self.tokens) - 1
            for n in self.orders:
                if i + 1 < n:
                    continue
                gram = tuple(self.tokens[i - n + 1:i + 1])
                idx = self._index[n]
                prev = idx.get(gram)
                idx[gram] = (i, prev[0] if prev else -1)

    def propose(self) -> list[int]:
        """Up to ``k`` draft tokens continuing the best suffix match —
        deterministic (most recent occurrence, longest order first)."""
        last = len(self.tokens) - 1
        for n in self.orders:
            if len(self.tokens) < n + 1:
                continue
            gram = tuple(self.tokens[-n:])
            hit = self._index[n].get(gram)
            if hit is None:
                continue
            j = hit[0] if hit[0] != last else hit[1]
            if j < 0 or j == last:
                continue
            return self.tokens[j + 1:j + 1 + self.k]
        return []

    def predict_next(self) -> int | None:
        """The proposer's single-token prediction — what the engine's
        SHADOW gate scores against each committed token on cheap rounds
        before risking verify width on this sequence (serve/engine.py)."""
        out = self.propose()
        return out[0] if out else None
