"""Paged KV cache: a device page pool + host-side page tables.

The dense decode cache pads every sequence to the batch maximum and holds
the slab until the whole batch drains. Here the cache is a pool of
fixed-size pages — ``[L, n_pages, page_size, Hkv, Dh]`` per K and V on
device — and each sequence owns exactly ``ceil(len / page_size)`` pages,
recorded in a host-side page table. Pages return to the free list the
moment a sequence finishes, so memory capacity (and therefore admission)
is decoupled from both batch width and the longest co-resident sequence.

Allocation is deterministic (FIFO free list): the same submit/finish
order always produces the same physical placement, which keeps engine
runs — and their telemetry — reproducible. Pages are **not** cleared on
free: the attention read path masks by sequence length with exact zeros
(ops/paged_attention.attend_rows), so stale contents are unreachable by
construction rather than by memset.
"""

from __future__ import annotations

from collections import deque

import jax.numpy as jnp
import numpy as np


class PagePoolError(RuntimeError):
    """A page-accounting invariant was violated (double alloc/free) or an
    allocation exceeded capacity that admission should have checked."""


class PagePool:
    """Host-side allocator over ``n_pages`` physical page ids.

    FIFO free list: deterministic placement for a deterministic op
    sequence. ``alloc`` raises :class:`PagePoolError` rather than
    over-committing — the scheduler checks ``free_pages`` before
    admitting, so a raise here is a scheduler bug, not backpressure.
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"pool needs >= 1 page, got {n_pages}")
        self.n_pages = n_pages
        self._free: deque[int] = deque(range(n_pages))
        self._used: set[int] = set()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return len(self._used)

    def alloc(self, n: int) -> list[int]:
        if n < 0:
            raise ValueError(f"alloc count must be >= 0, got {n}")
        if n > len(self._free):
            raise PagePoolError(
                f"allocation of {n} pages exceeds the {len(self._free)} "
                f"free (of {self.n_pages}); admission must queue, not "
                f"over-commit")
        pages = [self._free.popleft() for _ in range(n)]
        self._used.update(pages)
        return pages

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if p not in self._used:
                raise PagePoolError(
                    f"freeing page {p} that is not allocated (double "
                    f"free, or a page the pool never handed out)")
            self._used.remove(p)
            self._free.append(p)


class PagedKVCache:
    """Device page pools + per-sequence page tables for one model.

    ``ck``/``cv``: [L, n_pages, page_size, Hkv, Dh] device arrays the
    engine threads through its jitted steps (donated, so XLA updates
    them in place). The page table of sequence ``sid`` maps logical page
    ``i`` (tokens [i*page, (i+1)*page)) to a physical pool page;
    :meth:`table_array` pads it to the static per-sequence maximum with
    id 0 — padded entries are masked by length in the attention read, so
    any in-range id is safe.
    """

    def __init__(self, cfg, *, n_pages: int, page_size: int,
                 max_seq_len: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if max_seq_len < 1:
            raise ValueError(f"max_seq_len must be >= 1, got {max_seq_len}")
        self.cfg = cfg
        self.page_size = page_size
        self.max_seq_len = max_seq_len
        self.pages_per_seq = -(-max_seq_len // page_size)
        self.pool = PagePool(n_pages)
        self._tables: dict[object, list[int]] = {}
        shape = (cfg.n_layers, n_pages, page_size, cfg.kv_heads,
                 cfg.head_dim)
        self.ck = jnp.zeros(shape, cfg.dtype)
        self.cv = jnp.zeros_like(self.ck)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def open(self, sid) -> None:
        if sid in self._tables:
            raise PagePoolError(f"sequence {sid!r} is already open")
        self._tables[sid] = []

    def ensure(self, sid, n_tokens: int) -> None:
        """Grow ``sid``'s table to cover ``n_tokens`` positions. The
        scheduler reserves capacity at admission, so a raise here means
        an accounting bug, not load."""
        if n_tokens > self.max_seq_len:
            raise PagePoolError(
                f"sequence {sid!r} wants {n_tokens} tokens > max_seq_len "
                f"{self.max_seq_len}")
        table = self._tables[sid]
        need = self.pages_needed(n_tokens) - len(table)
        if need > 0:
            table.extend(self.pool.alloc(need))

    def release(self, sid) -> None:
        """Return every page of ``sid`` to the pool (eviction/completion)."""
        self.pool.free(self._tables.pop(sid))

    def table_array(self, sid) -> np.ndarray:
        """[pages_per_seq] int32, padded with 0 (masked by length)."""
        table = self._tables[sid]
        out = np.zeros((self.pages_per_seq,), np.int32)
        out[:len(table)] = table
        return out

    @property
    def occupancy(self) -> float:
        return self.pool.used_pages / self.pool.n_pages
