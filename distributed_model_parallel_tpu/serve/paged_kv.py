"""Paged KV cache: a device page pool + host-side page tables.

The dense decode cache pads every sequence to the batch maximum and holds
the slab until the whole batch drains. Here the cache is a pool of
fixed-size pages — ``[L, n_pages, page_size, Hkv, Dh]`` per K and V on
device — and each sequence owns exactly ``ceil(len / page_size)`` pages,
recorded in a host-side page table. Pages return to the free list the
moment a sequence finishes, so memory capacity (and therefore admission)
is decoupled from both batch width and the longest co-resident sequence.

Pages are **refcounted** (copy-on-write prefix sharing,
serve/prefix_cache.py): a page written once for a token prefix can back
every sequence whose prompt starts with those tokens — each holder takes
a reference, and the page returns to the free list only when the last
reference drops. "Copy-on-write" here is page-granular and by
construction: a sharer's own writes always land at positions past the
shared prefix, i.e. in freshly allocated pages, so a shared page is never
written twice and no actual copy ever happens.

Allocation is deterministic (FIFO free list): the same submit/finish
order always produces the same physical placement, which keeps engine
runs — and their telemetry — reproducible. Pages are **not** cleared on
free: the attention read path masks past-length positions to exact 0.0
(ops/paged_attention.attend_rows), so stale contents are unreachable by
construction rather than by memset.

Pages are also the **migration unit** (serve/fleet.py): a live sequence
leaves one replica and resumes on another by copying its written pages'
contents — :meth:`PagedKVCache.export_request` serializes the K/V
contents of a sequence's written prefix to host arrays, and
:meth:`PagedKVCache.import_request` allocates **fresh** pages on the
destination pool and writes those contents back. The payload is pure
values, never page ids, so a migrated sequence carries no references
into the source replica's pool or radix tree — the source can drop
everything (and be quarantined) the moment the export returns.
"""

from __future__ import annotations

import math
from collections import deque

import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.utils import tracing


class PagePoolError(RuntimeError):
    """A page-accounting invariant was violated (double alloc/free) or an
    allocation exceeded capacity that admission should have checked."""


class PagePool:
    """Host-side refcounting allocator over ``n_pages`` physical ids.

    FIFO free list: deterministic placement for a deterministic op
    sequence. ``alloc`` hands out pages at refcount 1; ``retain`` adds a
    reference (prefix sharing); ``free`` drops one reference per page and
    returns the page to the free list only at refcount 0. ``alloc``
    raises :class:`PagePoolError` rather than over-committing — the
    scheduler checks ``free_pages`` before admitting, so a raise here is
    a scheduler bug, not backpressure.
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"pool needs >= 1 page, got {n_pages}")
        self.n_pages = n_pages
        self._free: deque[int] = deque(range(n_pages))
        self._refs: dict[int, int] = {}
        # Low-water mark of the free list over the pool's lifetime — the
        # memory-pressure gauge rtrace decode records carry (how close
        # did this pool ever come to stalling admission).
        self.free_watermark = n_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return len(self._refs)

    @property
    def shared_pages(self) -> int:
        """Pages held by more than one reference (prefix sharing live)."""
        return sum(1 for c in self._refs.values() if c > 1)

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def alloc(self, n: int) -> list[int]:
        if n < 0:
            raise ValueError(f"alloc count must be >= 0, got {n}")
        if n > len(self._free):
            raise PagePoolError(
                f"allocation of {n} pages exceeds the {len(self._free)} "
                f"free (of {self.n_pages}); admission must queue, not "
                f"over-commit")
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        if len(self._free) < self.free_watermark:
            self.free_watermark = len(self._free)
        return pages

    def retain(self, pages: list[int]) -> None:
        """Add one reference to each allocated page (a sharer joining)."""
        for p in pages:
            if p not in self._refs:
                raise PagePoolError(
                    f"retaining page {p} that is not allocated")
        for p in pages:
            self._refs[p] += 1

    def free(self, pages: list[int]) -> None:
        """Drop one reference per page; a page returns to the free list
        only when its last holder lets go (refcount 0)."""
        for p in pages:
            if p not in self._refs:
                raise PagePoolError(
                    f"freeing page {p} that is not allocated (double "
                    f"free, or a page the pool never handed out)")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)


class PagedKVCache:
    """Device page pools + per-sequence page tables for one model.

    ``ck``/``cv``: [L, n_pages, page_size, Hkv, Dh] device arrays the
    engine threads through its jitted steps (donated, so XLA updates
    them in place). The page table of sequence ``sid`` maps logical page
    ``i`` (tokens [i*page, (i+1)*page)) to a physical pool page;
    :meth:`table_array` pads it to the static per-sequence maximum with
    id 0 — padded entries are masked by length in the attention read, so
    any in-range id is safe.

    ``prefix_cache=True`` keeps a radix tree over token prefixes
    (serve/prefix_cache.py): finished prefixes stay resident (refcounted
    by the tree), a new sequence whose prompt matches admits holding the
    cached pages, and the tree is evicted LRU-leaf-first when admission
    needs the capacity back. ``share_granularity`` (tokens; a multiple of
    ``page_size``) quantizes how much prefix a sharer may reuse — the
    engine passes ``lcm(page_size, prefill_chunk)`` so a cache-hit
    request's remaining prefill chunks are bit-identical program
    invocations to the cold run's (the determinism argument in
    docs/SERVING.md).
    """

    def __init__(self, cfg, *, n_pages: int, page_size: int,
                 max_seq_len: int, prefix_cache: bool = False,
                 share_granularity: int | None = None):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if max_seq_len < 1:
            raise ValueError(f"max_seq_len must be >= 1, got {max_seq_len}")
        self.cfg = cfg
        self.page_size = page_size
        self.max_seq_len = max_seq_len
        self.pages_per_seq = -(-max_seq_len // page_size)
        self.pool = PagePool(n_pages)
        self._tables: dict[object, list[int]] = {}
        if share_granularity is None:
            share_granularity = page_size
        if share_granularity % page_size != 0:
            raise ValueError(
                f"share_granularity {share_granularity} must be a "
                f"multiple of page_size {page_size}")
        self.share_granularity = share_granularity
        if prefix_cache:
            from distributed_model_parallel_tpu.serve.prefix_cache import (
                PrefixCache,
            )

            self.prefix = PrefixCache(self.pool, page_size)
        else:
            self.prefix = None
        shape = (cfg.n_layers, n_pages, page_size, cfg.kv_heads,
                 cfg.head_dim)
        self.ck = jnp.zeros(shape, cfg.dtype)
        self.cv = jnp.zeros_like(self.ck)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def open(self, sid) -> None:
        if sid in self._tables:
            raise PagePoolError(f"sequence {sid!r} is already open")
        self._tables[sid] = []

    def ensure(self, sid, n_tokens: int) -> None:
        """Grow ``sid``'s table to cover ``n_tokens`` positions. The
        scheduler reserves capacity at admission, so a raise here means
        an accounting bug, not load."""
        if n_tokens > self.max_seq_len:
            raise PagePoolError(
                f"sequence {sid!r} wants {n_tokens} tokens > max_seq_len "
                f"{self.max_seq_len}")
        table = self._tables[sid]
        need = self.pages_needed(n_tokens) - len(table)
        if need > 0:
            table.extend(self.pool.alloc(need))

    def release(self, sid) -> None:
        """Drop ``sid``'s reference on every page of its table
        (eviction/completion). Shared pages survive under the prefix
        tree's (or another sequence's) reference."""
        self.pool.free(self._tables.pop(sid))

    def table_array(self, sid) -> np.ndarray:
        """[pages_per_seq] int32, padded with 0 (masked by length)."""
        table = self._tables[sid]
        out = np.zeros((self.pages_per_seq,), np.int32)
        out[:len(table)] = table
        return out

    @property
    def occupancy(self) -> float:
        return self.pool.used_pages / self.pool.n_pages

    # -- prefix sharing ------------------------------------------------------

    def _usable_prefix(self, tokens: list[int], matched_pages: int) -> int:
        """Tokens of a raw page-tree match a sharer may actually reuse:
        quantized down to ``share_granularity`` and capped at
        ``len(tokens) - 1`` — the final prompt token is always recomputed
        so the last prefill chunk produces the first-token logits."""
        g = self.share_granularity
        m = min(matched_pages * self.page_size, len(tokens) - 1)
        return max(0, (m // g) * g)

    def _admission(self, tokens: list[int],
                   capacity: int) -> tuple[int, list[int], int, int]:
        """One radix match + one evictable walk:
        ``(cached_tokens, shared_pages, fresh_pages, available_pages)``.
        The request fits iff ``fresh_pages <= available_pages`` —
        available counts the free list plus tree pages evictable without
        touching the would-be-shared path."""
        cached = 0
        shared: list[int] = []
        if self.prefix is not None:
            pages = self.prefix.match(tokens, touch=False)
            cached = self._usable_prefix(tokens, len(pages))
            shared = pages[:cached // self.page_size]
        fresh = self.pages_needed(capacity) - len(shared)
        avail = self.pool.free_pages
        if self.prefix is not None:
            avail += self.prefix.evictable_pages(exclude=set(shared))
        return cached, shared, fresh, avail

    def peek_admission(self, tokens: list[int],
                       capacity: int) -> tuple[int, int, int]:
        """Side-effect-free admission bill:
        ``(cached_tokens, fresh_pages, available_pages)``."""
        cached, _, fresh, avail = self._admission(tokens, capacity)
        return cached, fresh, avail

    def try_admit(self, sid, tokens: list[int],
                  capacity: int) -> int | None:
        """Admission in ONE pass (the scheduler's per-iteration hot
        path): peek the post-sharing bill, and — when it fits — open
        ``sid`` holding the cached prefix, evict tree-only pages if the
        fresh suffix needs the room, and allocate the rest of the
        reservation. Returns the cached token count, or ``None`` when
        the request must keep queuing (no side effects then)."""
        cached, shared, fresh, avail = self._admission(tokens, capacity)
        if fresh > avail:
            return None
        self.open(sid)
        if shared:
            # Recency bump + hit accounting: a cheap matched-path walk,
            # not a second full match.
            self.prefix.touch_path(tokens, len(shared))
            self.pool.retain(shared)
            self._tables[sid].extend(shared)
        short = (self.pages_needed(capacity) - len(self._tables[sid])
                 - self.pool.free_pages)
        if short > 0:
            self.prefix.evict(short)
        self.ensure(sid, capacity)
        return cached

    def admit_with_prefix(self, sid, tokens: list[int],
                          capacity: int) -> int:
        """:meth:`try_admit` for callers that already checked the fit —
        insufficient room here raises (an accounting bug, not
        backpressure)."""
        got = self.try_admit(sid, tokens, capacity)
        if got is None:
            cached, fresh, avail = self.peek_admission(tokens, capacity)
            raise PagePoolError(
                f"sequence {sid!r} needs {fresh} fresh pages but only "
                f"{avail} are free or evictable; admission must queue")
        return got

    def insert_prefix(self, sid, tokens: list[int]) -> int:
        """Offer ``sid``'s pages for the **fully written** prefix
        ``tokens`` to the radix tree (no-op without a prefix cache).
        Only full pages are insertable; the tree retains every page it
        adopts, so they outlive the sequence. Returns pages newly
        adopted. Callers must pass only tokens whose KV is verified
        written — under speculative decoding the last committed token's
        slot may hold a rejected draft's KV, so the engine always trims
        the tail (serve/engine.py)."""
        if self.prefix is None:
            return 0
        return self.prefix.insert(tokens, self._tables[sid])

    @property
    def evictable_pages(self) -> int:
        if self.prefix is None:
            return 0
        return self.prefix.evictable_pages()

    def page_share(self, sid) -> float:
        """``sid``'s fractional page-pool reservation: one per exclusive
        page, ``1/refcount`` per shared one — a page three holders share
        costs each of them a third. The resource meter integrates this
        over residency into page-seconds (utils/metering.py); pages held
        only by the prefix tree belong to nobody and cost nobody. 0.0
        for an unknown/evicted sid (the meter may tick between eviction
        and bill close)."""
        table = self._tables.get(sid)
        if not table:
            return 0.0
        refcount = self.pool.refcount
        return sum(1.0 / c for p in table if (c := refcount(p)) > 0)

    @property
    def shared_pages(self) -> int:
        return self.pool.shared_pages

    # -- live request migration (serve/fleet.py) -----------------------------

    def export_request(self, sid, n_tokens: int, *, req=None, sink=None,
                       trace_fields=None):
        """Serialize the K/V **contents** of ``sid``'s first ``n_tokens``
        written positions to host arrays ``(k, v)`` of shape
        ``[L, pages, page_size, Hkv, Dh]`` — whole pages, values only.
        Shared prefix pages are exported by value like any other, so the
        payload holds no reference to this pool (the destination
        allocates fresh pages; see :meth:`import_request`). The caller
        guarantees every exported position's KV is actually written —
        the engine's drain hook passes the committed-and-written prefix
        (serve/engine.py ``drain``). When the caller passes the traced
        ``req`` (and its stream ``sink``), the hop's source half lands
        on the request timeline as an ``export`` rtrace record."""
        table = self._tables[sid]
        n = self.pages_needed(n_tokens)
        if n > len(table):
            raise PagePoolError(
                f"sequence {sid!r}: exporting {n_tokens} tokens spans "
                f"{n} pages but the table holds {len(table)}")
        idx = np.asarray(table[:n], np.int32)
        # One host fetch per pool: [L, n, page, Hkv, Dh].
        k = np.asarray(self.ck[:, idx]) if n else np.zeros(
            (self.cfg.n_layers, 0, self.page_size, self.ck.shape[3],
             self.ck.shape[4]), self.ck.dtype)
        v = np.asarray(self.cv[:, idx]) if n else np.zeros_like(k)
        if req is not None:
            tracing.rtrace(req, "export", sink=sink, pages=n,
                           n_tokens=n_tokens, **(trace_fields or {}))
        return k, v

    def import_request(self, sid, k, v, capacity: int, *,
                       req=None, sink=None, trace_fields=None) -> bool:
        """Admit a migrated sequence: reserve ``capacity`` positions of
        **fresh** pages (evicting tree-only pages if the room is needed
        — the exported KV is authoritative, so nothing is shared on
        arrival) and write the exported page contents into them. Returns
        ``False`` without side effects when the reservation does not
        fit — the scheduler keeps the request queued, exactly like a
        cold admission that finds no pages. A traced ``req``/``sink``
        records the hop's destination half (an ``import`` rtrace) on
        success only — a bounced import is queue time, not a hop."""
        need = self.pages_needed(capacity)
        avail = self.pool.free_pages
        if self.prefix is not None:
            avail += self.prefix.evictable_pages()
        if need > avail:
            return False
        n = int(k.shape[1])
        if n > need:
            raise PagePoolError(
                f"sequence {sid!r}: payload carries {n} pages but the "
                f"reservation is only {need}")
        self.open(sid)
        short = need - self.pool.free_pages
        if short > 0:
            self.prefix.evict(short)
        self.ensure(sid, capacity)
        if n:
            idx = jnp.asarray(self._tables[sid][:n], jnp.int32)
            self.ck = self.ck.at[:, idx].set(
                jnp.asarray(k).astype(self.ck.dtype))
            self.cv = self.cv.at[:, idx].set(
                jnp.asarray(v).astype(self.cv.dtype))
        if req is not None:
            tracing.rtrace(req, "import", sink=sink, pages=n,
                           **(trace_fields or {}))
        return True

    def cached_prefix_tokens(self, tokens: list[int]) -> int:
        """Usable cached-prefix length for ``tokens`` (quantized to the
        share granularity, side-effect free) — the router's
        prefix-affinity signal (serve/router.py). 0 without a cache."""
        if self.prefix is None:
            return 0
        pages = self.prefix.match(tokens, touch=False)
        return self._usable_prefix(tokens, len(pages))

    def drop_prefix(self) -> int:
        """Evict the ENTIRE radix tree (a replica being quarantined must
        return every page it holds). Pages still referenced by a
        resident sequence survive its tree reference dropping — callers
        drain sequences first. Returns pages freed."""
        if self.prefix is None:
            return 0
        return len(self.prefix.evict(len(self.prefix)))


def memory_gauges(cache: PagedKVCache) -> dict:
    """The memory-pressure snapshot rtrace ``decode`` records carry
    (docs/TRACING.md "Request tracing"): pool occupancy, free/used page
    counts, pages resident under the prefix radix tree, and the pool's
    lifetime free-list low-water mark — enough to tell a latency stall
    caused by page pressure from one caused by compute."""
    return {
        "occupancy": cache.occupancy,
        "free_pages": cache.pool.free_pages,
        "used_pages": cache.pool.used_pages,
        "prefix_pages": len(cache.prefix) if cache.prefix is not None else 0,
        "free_watermark": cache.pool.free_watermark,
    }


def share_granularity_for(page_size: int, prefill_chunk: int) -> int:
    """The engine's prefix-share quantum: a shared prefix must end on a
    page boundary (whole pages are the sharing unit) AND on a prefill
    chunk boundary (so the cold and cached runs dispatch bit-identical
    suffix chunks — same compiled program, same ``pos0`` stream)."""
    return math.lcm(page_size, prefill_chunk)
