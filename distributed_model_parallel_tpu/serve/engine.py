"""The serving engine loop: continuous batching over the paged KV cache.

One iteration = admit → prefill (a bounded number of chunks, interleaved
so long prompts never stall the resident batch) → one decode step for
every active slot → evict finished sequences (their slot and pages are
reusable the very next iteration). The decode step runs at a fixed slot
width with idle rows masked, so a request's tokens are a pure function of
its own (prompt, seed) — joining a busy batch mid-flight decodes exactly
what a solo run would (tests/test_serve.py pins this).

SLO accounting: per-request TTFT, queue wait and per-token latency land
in the process metrics registry (``serve_ttft_s`` / ``serve_queue_wait_s``
/ ``serve_token_latency_s`` histograms, ``serve_page_occupancy`` gauge)
and as typed ``serve`` telemetry records the report renders
(docs/OBSERVABILITY.md). A killed engine never drops requests silently:
every in-flight and queued request is marked failed with a typed error
and a ``serve`` record before the exception propagates.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.models.transformer import (
    TransformerConfig,
    validate_sampling,
)
from distributed_model_parallel_tpu.serve.model import (
    make_decode_step,
    make_prefill_step,
)
from distributed_model_parallel_tpu.serve.paged_kv import PagedKVCache
from distributed_model_parallel_tpu.serve.scheduler import (
    Request,
    RequestState,
    Scheduler,
    summarize,
)
from distributed_model_parallel_tpu.utils import tracing
from distributed_model_parallel_tpu.utils.telemetry import registry
from distributed_model_parallel_tpu.utils.tracing import span


class EngineKilled(RuntimeError):
    """The engine loop died mid-stream; every in-flight request has been
    marked failed (typed) before this propagated."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine geometry + sampling policy (per-engine, compiled in).

    ``n_pages`` is the pool capacity — the admission backpressure point;
    ``max_seq_len`` bounds any single request (prompt + generation) and
    sets the static per-sequence page-table width; ``prefill_chunk`` is
    the one compiled prompt-chunk size (any prompt length = some number
    of chunks, so repeated CLI calls hit the compile cache).
    """

    n_slots: int = 8
    page_size: int = 16
    n_pages: int = 256
    max_seq_len: int = 512
    prefill_chunk: int = 32
    prefill_chunks_per_iter: int = 1
    policy: str = "continuous"       # "continuous" | "static" (baseline)
    attn_impl: str = "auto"          # paged-attention impl (ops/)
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    eos_id: int | None = None
    # Live status exporter (utils/statusz.py): queue depth, page
    # occupancy and slot state under /statusz, SLO histograms under
    # /metrics. Same one-exporter-per-process semantics as
    # TrainConfig.statusz_port; None = DMP_STATUSZ_PORT, unset = no-op.
    statusz_port: int | None = None


class Engine:
    """Continuous-batching decode engine over one replicated model.

    ``step_hook(iteration)`` (tests, chaos drills) runs once per loop
    iteration; an exception it raises takes the typed-failure path like
    any other engine death.
    """

    def __init__(self, params: dict, cfg: TransformerConfig,
                 serve: ServeConfig, *, telemetry=None, step_hook=None,
                 slo_metrics: bool = True):
        if cfg.moe_experts:
            raise ValueError(
                "MoE decode routing is batch-coupled (expert-capacity "
                "drops depend on co-resident tokens), which breaks "
                "continuous batching's per-request determinism; decode "
                "MoE models via models.transformer.generate")
        if cfg.tp_axis is not None or cfg.sp_axis is not None:
            raise ValueError("the serving engine runs replicated; build "
                             "it with tp_axis=None/sp_axis=None (sharded "
                             "decode stays on generate_sharded)")
        if serve.max_seq_len > cfg.max_seq_len:
            raise ValueError(
                f"serve max_seq_len {serve.max_seq_len} exceeds the "
                f"model's max_seq_len {cfg.max_seq_len}")
        if serve.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{serve.prefill_chunk}")
        validate_sampling(cfg, serve.temperature, serve.top_k, serve.top_p)
        self.params = params
        self.cfg = cfg
        self.serve = serve
        self.telemetry = telemetry
        self.step_hook = step_hook
        # slo_metrics=False keeps this engine out of the process-wide
        # registry (serve_* counters/histograms/gauge) — warmup/probe
        # engines must not pollute the samples a telemetry stream's
        # metrics record snapshots for the real runs.
        self._slo_metrics = slo_metrics
        self.cache = PagedKVCache(cfg, n_pages=serve.n_pages,
                                  page_size=serve.page_size,
                                  max_seq_len=serve.max_seq_len)
        self.sched = Scheduler(self.cache, serve.n_slots,
                               policy=serve.policy,
                               prefill_chunks_per_iter=(
                                   serve.prefill_chunks_per_iter))
        self._sampled = serve.temperature > 0
        kw = dict(page_size=serve.page_size, n_pages=serve.n_pages,
                  impl=serve.attn_impl, temperature=serve.temperature,
                  top_k=serve.top_k, top_p=serve.top_p)
        self._prefill = make_prefill_step(cfg, chunk=serve.prefill_chunk,
                                          **kw)
        self._decode = make_decode_step(cfg, **kw)
        self._requests: list[Request] = []
        # Per-slot page tables, maintained incrementally: reservation ==
        # allocation, so a request's table is final at admission — one
        # host write per join, not a rebuild per decode step.
        self._tables_np = np.zeros(
            (serve.n_slots, self.cache.pages_per_seq), np.int32)
        self._auto_rid = 0
        self._iterations = 0
        self._decode_steps = 0
        self._decode_tokens = 0       # useful tokens out of decode steps
        self._occupancy: list[float] = []
        self._wall_s = 0.0
        # Live status exporter (utils/statusz.py): queue depth / page
        # occupancy / slot state under /statusz. No-op when no port is
        # configured anywhere in the process.
        from distributed_model_parallel_tpu.utils import statusz

        statusz.maybe_serve(serve.statusz_port)
        # One provider per policy: a later engine of the same policy
        # replaces the entry. Warmup/probe engines (slo_metrics=False)
        # stay off the exporter like they stay out of the registry.
        if slo_metrics:
            statusz.register(f"serve-{serve.policy}", self._status)

    def _status(self) -> dict:
        """The engine's /statusz provider payload."""
        return {
            "workload": "serve",
            "policy": self.serve.policy,
            "iterations": self._iterations,
            "queue_depth": len(self.sched.queue),
            "active_requests": sum(1 for r in self._requests
                                   if not r.done and r.slot is not None),
            "n_slots": self.serve.n_slots,
            "page_occupancy": self.cache.occupancy,
            "requests_submitted": len(self._requests),
            "healthy": True,
        }

    # -- submission ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *, rid: str | None = None,
               arrival_s: float = 0.0, seed: int = 0) -> Request:
        prompt = [int(t) for t in prompt]
        bad = [t for t in prompt if not (0 <= t < self.cfg.vocab_size)]
        if bad:
            raise ValueError(f"prompt tokens {bad} outside vocab "
                             f"[0, {self.cfg.vocab_size})")
        if rid is None:
            rid = f"req-{self._auto_rid}"
            self._auto_rid += 1
        req = Request(rid=rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      arrival_s=float(arrival_s), seed=int(seed))
        self.sched.submit(req)
        self._requests.append(req)
        return req

    # -- the loop -----------------------------------------------------------

    def run(self, *, max_iterations: int | None = None) -> dict:
        """Drive the loop until every submitted request is terminal (or
        ``max_iterations``). Returns the summary dict (also emitted as
        the ``serve`` summary telemetry record)."""
        t0 = time.monotonic()
        try:
            # Spans from the loop (prefill chunks, decode rounds,
            # admissions) go to this engine's own stream for the scope
            # of the run — the request-lifecycle timeline
            # scripts/dmp_trace.py renders next to the per-request
            # serve records.
            with tracing.sink_scope(self.telemetry):
                while not self.sched.idle():
                    if (max_iterations is not None
                            and self._iterations >= max_iterations):
                        break
                    now = time.monotonic() - t0
                    if self.step_hook is not None:
                        self.step_hook(self._iterations)
                    self._iterations += 1
                    made_progress = self._iterate(now, t0)
                    if not made_progress:
                        nxt = self.sched.next_arrival()
                        if nxt is not None:
                            # Open loop: nothing resident, next request
                            # not arrived yet — sleep to its arrival.
                            time.sleep(max(0.0, min(nxt - now, 0.05)))
        except BaseException as e:
            self._fail_inflight(f"{type(e).__name__}: {e}")
            self._wall_s = time.monotonic() - t0
            if self.telemetry is not None:
                self.telemetry.failure(
                    "engine-killed", detail=f"{type(e).__name__}: {e}",
                    iteration=self._iterations)
            # Crash flight recorder (utils/flightrec.py): capture the
            # state at the moment of death — ring records, thread
            # stacks, span stacks, page-pool state. No-op when no
            # recorder is installed.
            from distributed_model_parallel_tpu.utils import flightrec

            flightrec.dump("engine-killed", telemetry_run=self.telemetry,
                           error=e)
            if not isinstance(e, Exception):
                # KeyboardInterrupt/SystemExit keep their semantics —
                # the typed-failure bookkeeping above still ran.
                raise
            raise EngineKilled(
                f"engine died at iteration {self._iterations}; "
                f"in-flight requests marked failed") from e
        self._wall_s = time.monotonic() - t0
        return self.summary()

    def _iterate(self, now: float, t0: float) -> bool:
        progress = False
        for req in self.sched.admit(now):
            self._tables_np[req.slot] = self.cache.table_array(req.rid)
            self._record_queue_wait(req)
        for req in self.sched.prefilling():
            self._prefill_chunk(req, t0)
            progress = True
        decoding = self.sched.decoding()
        if decoding:
            self._decode_round(decoding, t0)
            progress = True
        occ = self.cache.occupancy
        self._occupancy.append(occ)
        if self._slo_metrics:
            registry().gauge("serve_page_occupancy").set(occ)
        return progress

    # -- prefill ------------------------------------------------------------

    def _prefill_chunk(self, req: Request, t0: float) -> None:
        with span("prefill_chunk", request=req.rid,
                  cursor=req.prefill_cursor):
            self._prefill_chunk_inner(req, t0)

    def _prefill_chunk_inner(self, req: Request, t0: float) -> None:
        chunk = self.serve.prefill_chunk
        lo = req.prefill_cursor
        n_valid = min(chunk, req.prompt_len - lo)
        toks = np.zeros((1, chunk), np.int32)
        toks[0, :n_valid] = req.prompt[lo:lo + n_valid]
        table = jnp.asarray(self._tables_np[req.slot])
        key = jax.random.key(req.seed)
        self.cache.ck, self.cache.cv, tok = self._prefill(
            self.params, self.cache.ck, self.cache.cv, jnp.asarray(toks),
            jnp.int32(lo), jnp.int32(n_valid), table, key)
        req.prefill_cursor = lo + n_valid
        if req.prefill_cursor >= req.prompt_len:
            # Final chunk: its sampled token is the request's first
            # generated token (position t0) — TTFT stops here.
            first = int(jax.device_get(tok)[0])
            req.generated.append(first)
            req.t_first_token = time.monotonic() - t0
            req.state = RequestState.DECODE
            self._record_ttft(req)
            if self._finished(req, first):
                self._complete(req, t0)

    # -- decode -------------------------------------------------------------

    def _decode_round(self, decoding: list[Request], t0: float) -> None:
        with span("decode_round", batch=len(decoding)):
            self._decode_round_inner(decoding, t0)

    def _decode_round_inner(self, decoding: list[Request], t0: float) -> None:
        b = self.serve.n_slots
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        seeds = np.zeros((b,), np.uint32)
        for req in decoding:
            s = req.slot
            tokens[s] = req.generated[-1]
            positions[s] = req.prompt_len + len(req.generated) - 1
            active[s] = True
            seeds[s] = req.seed
        keys = (jax.vmap(jax.random.key)(jnp.asarray(seeds))
                if self._sampled else None)
        self.cache.ck, self.cache.cv, nxt = self._decode(
            self.params, self.cache.ck, self.cache.cv,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(self._tables_np), jnp.asarray(active), keys)
        nxt = np.asarray(jax.device_get(nxt))
        self._decode_steps += 1
        self._decode_tokens += len(decoding)
        for req in decoding:
            tok = int(nxt[req.slot])
            req.generated.append(tok)
            if self._finished(req, tok):
                self._complete(req, t0)

    def _finished(self, req: Request, tok: int) -> bool:
        return (len(req.generated) >= req.max_new_tokens
                or (self.serve.eos_id is not None
                    and tok == self.serve.eos_id))

    # -- lifecycle ----------------------------------------------------------

    def _complete(self, req: Request, t0: float) -> None:
        req.t_done = time.monotonic() - t0
        req.state = RequestState.COMPLETED
        self.sched.evict(req)
        token_s = None
        if len(req.generated) > 1 and req.t_first_token is not None:
            token_s = ((req.t_done - req.t_first_token)
                       / (len(req.generated) - 1))
        if self._slo_metrics:
            reg = registry()
            reg.counter("serve_requests_completed").inc()
            reg.counter("serve_tokens_generated").inc(len(req.generated))
            if token_s is not None:
                reg.histogram("serve_token_latency_s").observe(token_s)
        if self.telemetry is not None:
            self.telemetry.record(
                "serve", event="completed", request=req.rid,
                policy=self.serve.policy,
                prompt_tokens=req.prompt_len,
                new_tokens=len(req.generated),
                queue_wait_s=self._queue_wait(req),
                ttft_s=self._ttft(req), token_latency_s=token_s,
                wall_s=req.t_done - req.arrival_s)

    def _fail_inflight(self, detail: str) -> None:
        for req in self._requests:
            if req.done:
                continue
            if req.slot is not None:
                self.sched.evict(req)
            elif any(q is req for q in self.sched.queue):
                self.sched.queue = deque(
                    q for q in self.sched.queue if q is not req)
            req.state = RequestState.FAILED
            req.error = f"engine-killed: {detail}"
            if self._slo_metrics:
                registry().counter("serve_requests_failed").inc()
            if self.telemetry is not None:
                self.telemetry.record(
                    "serve", event="failed", request=req.rid,
                    policy=self.serve.policy,
                    error="engine-killed", detail=detail,
                    prompt_tokens=req.prompt_len,
                    new_tokens=len(req.generated))

    # -- SLO bookkeeping ----------------------------------------------------

    def _queue_wait(self, req: Request) -> float | None:
        if req.t_admitted is None:
            return None
        return max(0.0, req.t_admitted - req.arrival_s)

    def _ttft(self, req: Request) -> float | None:
        if req.t_first_token is None:
            return None
        return max(0.0, req.t_first_token - req.arrival_s)

    def _record_queue_wait(self, req: Request) -> None:
        w = self._queue_wait(req)
        if w is not None and self._slo_metrics:
            registry().histogram("serve_queue_wait_s").observe(w)

    def _record_ttft(self, req: Request) -> None:
        t = self._ttft(req)
        if t is not None and self._slo_metrics:
            registry().histogram("serve_ttft_s").observe(t)

    # -- results ------------------------------------------------------------

    def results(self) -> list[Request]:
        return list(self._requests)

    def summary(self) -> dict:
        """Aggregate SLO + throughput view (and the ``serve`` summary
        record when a telemetry stream is attached)."""
        completed = [r for r in self._requests
                     if r.state is RequestState.COMPLETED]
        failed = [r for r in self._requests
                  if r.state is RequestState.FAILED]
        tokens = sum(len(r.generated) for r in completed)
        token_lat = [
            (r.t_done - r.t_first_token) / (len(r.generated) - 1)
            for r in completed
            if len(r.generated) > 1 and r.t_first_token is not None]
        out = {
            "policy": self.serve.policy,
            "n_slots": self.serve.n_slots,
            "requests_completed": len(completed),
            "requests_failed": len(failed),
            "tokens_generated": tokens,
            "wall_s": self._wall_s,
            "tokens_per_s": (tokens / self._wall_s if self._wall_s > 0
                             else None),
            "iterations": self._iterations,
            "decode_steps": self._decode_steps,
            # Slot efficiency: useful tokens per decode step over the
            # batch width — the deterministic (timing-free) continuous-
            # vs-static comparison the tests gate on.
            "slot_utilization": (
                self._decode_tokens
                / (self._decode_steps * self.serve.n_slots)
                if self._decode_steps else None),
            "ttft_s": summarize(
                [t for t in (self._ttft(r) for r in completed)
                 if t is not None]),
            "queue_wait_s": summarize(
                [w for w in (self._queue_wait(r) for r in completed)
                 if w is not None]),
            "token_latency_s": summarize(token_lat),
            "page_occupancy": summarize(self._occupancy),
        }
        if self.telemetry is not None:
            self.telemetry.record("serve", event="summary", **out)
        return out
