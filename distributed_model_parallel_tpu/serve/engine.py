"""The serving engine loop: continuous batching over the paged KV cache.

One iteration = admit → prefill (a bounded number of chunks, interleaved
so long prompts never stall the resident batch) → one decode step for
every active slot → evict finished sequences (their slot and pages are
reusable the very next iteration). The decode step runs at a fixed slot
width with idle rows masked, so a request's tokens are a pure function of
its own (prompt, seed) — joining a busy batch mid-flight decodes exactly
what a solo run would (tests/test_serve.py pins this).

SLO accounting: per-request TTFT, queue wait and per-token latency land
in the process metrics registry (``serve_ttft_s`` / ``serve_queue_wait_s``
/ ``serve_token_latency_s`` histograms, ``serve_page_occupancy`` gauge)
and as typed ``serve`` telemetry records the report renders
(docs/OBSERVABILITY.md). A killed engine never drops requests silently:
every in-flight and queued request is marked failed with a typed error
and a ``serve`` record before the exception propagates.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from distributed_model_parallel_tpu.models.transformer import (
    TransformerConfig,
    validate_sampling,
)
from distributed_model_parallel_tpu.serve.model import (
    make_decode_step,
    make_prefill_step,
    make_verify_step,
)
from distributed_model_parallel_tpu.serve.paged_kv import (
    PagedKVCache,
    PagePoolError,
    memory_gauges,
    share_granularity_for,
)
from distributed_model_parallel_tpu.serve.spec import NGramProposer
from distributed_model_parallel_tpu.serve.scheduler import (
    Request,
    RequestState,
    Scheduler,
    summarize,
)
from distributed_model_parallel_tpu.utils import tracing
from distributed_model_parallel_tpu.utils.metering import EngineMeter
from distributed_model_parallel_tpu.utils.telemetry import registry
from distributed_model_parallel_tpu.utils.tracing import span


class EngineKilled(RuntimeError):
    """The engine loop died mid-stream; every in-flight request has been
    marked failed (typed) before this propagated."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine geometry + sampling policy (per-engine, compiled in).

    ``n_pages`` is the pool capacity — the admission backpressure point;
    ``max_seq_len`` bounds any single request (prompt + generation) and
    sets the static per-sequence page-table width; ``prefill_chunk`` is
    the one compiled prompt-chunk size (any prompt length = some number
    of chunks, so repeated CLI calls hit the compile cache).
    """

    n_slots: int = 8
    page_size: int = 16
    n_pages: int = 256
    max_seq_len: int = 512
    prefill_chunk: int = 32
    prefill_chunks_per_iter: int = 1
    policy: str = "continuous"       # "continuous" | "static" (baseline)
    attn_impl: str = "auto"          # paged-attention impl (ops/)
    # Prefix-cache reuse (serve/prefix_cache.py): finished prefixes stay
    # resident in a refcounted radix tree; a request whose prompt matches
    # admits holding the cached pages, prefills only the suffix, and its
    # admission reservation bills only the uncached pages.
    prefix_cache: bool = False
    # Speculative decoding: an n-gram self-drafting proposer (serve/
    # spec.py) proposes up to spec_k tokens per iteration and one
    # batched verify forward (serve/model.make_verify_step) commits the
    # model-verified prefix. 0 = off (single-token decode, PR 9 path).
    spec_k: int = 0
    spec_ngram: int = 3              # longest lookup order tried
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    eos_id: int | None = None
    # -- overload protection (docs/SERVING.md "Overload and graceful
    # degradation"). Defaults are per-request-overridable on Request.
    # A queued request older than queue_budget_s sheds typed (reason
    # queue-deadline); one past deadline_s sheds (queued) or aborts
    # (in-flight, pages returned immediately) with reason
    # total-deadline. None = no budget (the PR 9 behavior).
    queue_budget_s: float | None = None
    deadline_s: float | None = None
    # Submission-queue bound: beyond it, submission is REJECTED with a
    # typed record (reason queue-full) instead of growing an unbounded
    # host-side queue. The fleet bounds its own pending list at
    # max_queue * n_replicas. None = unbounded (PR 9 behavior).
    max_queue: int | None = None
    # Brownout: the deterministic degradation ladder (serve/overload.py)
    # driven by a TTFT burn-rate rule and a page-occupancy ceiling —
    # spec-off -> prefill-share -> clamp-max-new, walked back on
    # resolution. Degradation never changes a completed request's
    # tokens (a clamped request's stream is the bitwise prefix of its
    # unclamped one).
    brownout: bool = False
    brownout_ttft_target_s: float = 1.0   # SLO target feeding the burn rule
    brownout_budget: float = 0.25         # tolerated violation fraction
    brownout_window_s: float = 10.0       # short burn window (long = 4x)
    brownout_occupancy_ceiling: float = 0.95
    brownout_max_new: int = 32            # level-3 cap on admissions' max_new
    brownout_hold_iters: int = 8          # min ticks between level moves
    # Live status exporter (utils/statusz.py): queue depth, page
    # occupancy and slot state under /statusz, SLO histograms under
    # /metrics. Same one-exporter-per-process semantics as
    # TrainConfig.statusz_port; None = DMP_STATUSZ_PORT, unset = no-op.
    statusz_port: int | None = None


class Engine:
    """Continuous-batching decode engine over one replicated model.

    ``step_hook(iteration)`` (tests, chaos drills) runs once per loop
    iteration; an exception it raises takes the typed-failure path like
    any other engine death.
    """

    def __init__(self, params: dict, cfg: TransformerConfig,
                 serve: ServeConfig, *, telemetry=None, step_hook=None,
                 slo_metrics: bool = True, replica: str | None = None,
                 clock=None, journal=None, meter: bool = True):
        if cfg.moe_experts:
            raise ValueError(
                "MoE decode routing is batch-coupled (expert-capacity "
                "drops depend on co-resident tokens), which breaks "
                "continuous batching's per-request determinism; decode "
                "MoE models via models.transformer.generate")
        if cfg.tp_axis is not None or cfg.sp_axis is not None:
            raise ValueError("the serving engine runs replicated; build "
                             "it with tp_axis=None/sp_axis=None (sharded "
                             "decode stays on generate_sharded)")
        if serve.max_seq_len > cfg.max_seq_len:
            raise ValueError(
                f"serve max_seq_len {serve.max_seq_len} exceeds the "
                f"model's max_seq_len {cfg.max_seq_len}")
        if serve.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{serve.prefill_chunk}")
        validate_sampling(cfg, serve.temperature, serve.top_k, serve.top_p)
        if serve.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {serve.spec_k}")
        if serve.spec_ngram < 1:
            raise ValueError(f"spec_ngram must be >= 1, got "
                             f"{serve.spec_ngram}")
        self.params = params
        self.cfg = cfg
        self.serve = serve
        self.telemetry = telemetry
        self.step_hook = step_hook
        # Write-ahead request journal (serve/journal.py): committed-token
        # watermarks from the decode loop, exactly one terminal per
        # accepted request. None = journal off, zero behavior change.
        self.journal = journal
        # Resource meter (utils/metering.py): per-request chip-second /
        # page-second bills and the per-iteration utilization ledger.
        # Pure observation — the soak drill gates a byte-identical
        # schedule digest with metering on vs off, and metering overhead
        # at < 2% of iteration time. meter=False turns the plane off.
        self.meter = EngineMeter(replica=replica) if meter else None
        # Fleet membership (serve/fleet.py): the replica name tags this
        # engine's serve records and statusz provider so a multi-replica
        # stream stays attributable. None = standalone engine (PR 9
        # behavior, provider named serve-{policy}).
        self.replica = replica
        # Pluggable clock: every timestamp the engine takes (run-loop
        # now, TTFT, completion) comes from here. Default is the real
        # monotonic clock; a SimClock (serve/traffic.py) makes the whole
        # request lifecycle a deterministic function of the trace — the
        # chaos-scenario replay contract.
        self._clock = clock if clock is not None else time.monotonic
        # slo_metrics=False keeps this engine out of the process-wide
        # registry (serve_* counters/histograms/gauge) — warmup/probe
        # engines must not pollute the samples a telemetry stream's
        # metrics record snapshots for the real runs.
        self._slo_metrics = slo_metrics
        self.cache = PagedKVCache(
            cfg, n_pages=serve.n_pages, page_size=serve.page_size,
            max_seq_len=serve.max_seq_len,
            prefix_cache=serve.prefix_cache,
            # Shared prefixes end on a page AND prefill-chunk boundary,
            # so a cache-hit request's remaining chunks are the same
            # compiled program at the same pos0 stream as the cold run's
            # — the bitwise-parity argument in docs/SERVING.md.
            share_granularity=share_granularity_for(serve.page_size,
                                                    serve.prefill_chunk))
        self.sched = Scheduler(self.cache, serve.n_slots,
                               policy=serve.policy,
                               prefill_chunks_per_iter=(
                                   serve.prefill_chunks_per_iter),
                               queue_budget_s=serve.queue_budget_s,
                               deadline_s=serve.deadline_s,
                               max_queue=serve.max_queue)
        # Request-trace plane (docs/TRACING.md "Request tracing"): the
        # scheduler's admission-side rtrace records go to this engine's
        # stream, tagged with the replica origin in fleet mode so the
        # timeline joiner can attribute them (and link migration hops)
        # on the fleet's shared stream.
        self.sched.sink = telemetry
        self._trace_fields = ({"replica": replica}
                              if replica is not None else {})
        self.sched.trace_fields = self._trace_fields
        # Brownout ladder (serve/overload.py): per-engine, fed and
        # ticked once per iteration; None = feature off, zero cost.
        if serve.brownout:
            from distributed_model_parallel_tpu.serve.overload import (
                BrownoutController,
            )

            self.brownout = BrownoutController(serve)
        else:
            self.brownout = None
        self._shed_by_reason: dict[str, int] = {}
        self._rejected = 0
        self._sampled = serve.temperature > 0
        kw = dict(page_size=serve.page_size, n_pages=serve.n_pages,
                  impl=serve.attn_impl, temperature=serve.temperature,
                  top_k=serve.top_k, top_p=serve.top_p)
        self._prefill = make_prefill_step(cfg, chunk=serve.prefill_chunk,
                                          **kw)
        self._decode = make_decode_step(cfg, **kw)
        # Speculative decoding: decode rounds run a verify program from a
        # compiled WIDTH LADDER (powers of two up to spec_k + 1) — each
        # round dispatches the smallest width covering its longest live
        # draft, so a round where only one row drafts two tokens never
        # pays the full spec_k forward (the fixed-width program's cost is
        # set by its width, not by how many drafts actually ride it).
        self._verify_widths: list[int] = []
        self._verify: dict[int, object] = {}
        if serve.spec_k:
            w = 2
            while w < serve.spec_k + 1:
                self._verify_widths.append(w)
                w *= 2
            self._verify_widths.append(serve.spec_k + 1)
            self._verify = {w: make_verify_step(cfg, width=w, **kw)
                            for w in self._verify_widths}
        self._proposers: dict[str, NGramProposer] = {}
        # SHADOW gating: acceptance is bursty — the model wanders, then
        # locks into spans the n-gram index predicts perfectly — so a
        # request drafts for real only after its proposer has proven
        # itself, scoring single-token predictions against committed
        # tokens on the cheap path (free, host-side). Two consecutive
        # shadow hits go live; a zero-accept verify round goes back to
        # shadow. Deterministic: a pure function of the committed
        # stream, so the pinned spec-on/off parity is untouched (gating
        # moves WHEN drafts ride, never which tokens commit).
        self._spec_streak: dict[str, int] = {}
        self._spec_live: dict[str, bool] = {}
        self._requests: list[Request] = []
        # Per-slot page tables, maintained incrementally: reservation ==
        # allocation, so a request's table is final at admission — one
        # host write per join, not a rebuild per decode step.
        self._tables_np = np.zeros(
            (serve.n_slots, self.cache.pages_per_seq), np.int32)
        self._auto_rid = 0
        self._iterations = 0
        self._now = 0.0               # live open-loop clock (last iteration)
        self._decode_steps = 0
        self._decode_tokens = 0       # useful tokens out of decode steps
        self._occupancy: list[float] = []
        self._wall_s = 0.0            # accumulates across run() calls
        # Real (monotonic) per-iteration wall samples, independent of
        # the pluggable clock — the crashrecovery scenario gates journal
        # overhead against their p50 even under a SimClock.
        self._iter_s: list[float] = []
        # prefix-cache + speculative-decoding accounting
        self._prompt_tokens = 0       # prompt tokens of admitted requests
        self._cached_tokens = 0       # of those, served from the tree
        self._draft_proposed = 0
        self._draft_accepted = 0
        # Live status exporter (utils/statusz.py): queue depth / page
        # occupancy / slot state under /statusz. No-op when no port is
        # configured anywhere in the process.
        from distributed_model_parallel_tpu.utils import statusz

        statusz.maybe_serve(serve.statusz_port)
        # One provider per policy (or per fleet replica): a later engine
        # of the same name replaces the entry. Warmup/probe engines
        # (slo_metrics=False) stay off the exporter like they stay out
        # of the registry.
        self._provider = (f"serve-{replica}" if replica is not None
                          else f"serve-{serve.policy}")
        if slo_metrics:
            statusz.register(self._provider, self._status)

    def _status(self) -> dict:
        """The engine's /statusz provider payload."""
        return {
            "workload": "serve",
            "policy": self.serve.policy,
            "replica": self.replica,
            "iterations": self._iterations,
            "queue_depth": len(self.sched.queue),
            "active_requests": sum(1 for r in self._requests
                                   if not r.done and r.slot is not None),
            "n_slots": self.serve.n_slots,
            "page_occupancy": self.cache.occupancy,
            "requests_submitted": len(self._requests),
            # overload protection, live (docs/SERVING.md)
            "requests_shed": sum(self._shed_by_reason.values()),
            "requests_rejected": self._rejected,
            "shed_by_reason": dict(sorted(self._shed_by_reason.items())),
            "brownout_level": (self.brownout.level
                               if self.brownout is not None else None),
            "max_queue": self.serve.max_queue,
            # prefix sharing + speculative decoding, live
            "prefix_cache": self.serve.prefix_cache,
            "spec_k": self.serve.spec_k,
            "cache_hit_rate": self.cache_hit_rate,
            "shared_pages": self.cache.shared_pages,
            "cached_prefix_pages": (len(self.cache.prefix)
                                    if self.cache.prefix is not None
                                    else 0),
            "draft_accept_rate": self.draft_accept_rate,
            # resource metering, live (utils/metering.py)
            "utilization": (self.meter.utilization()
                            if self.meter is not None else None),
            "open_bills": (len(self.meter._bills)
                           if self.meter is not None else None),
            "healthy": True,
        }

    @property
    def cache_hit_rate(self) -> float | None:
        """Prompt tokens served from the prefix tree / prompt tokens
        admitted (None before any admission or with the cache off)."""
        if not self.serve.prefix_cache or not self._prompt_tokens:
            return None
        return self._cached_tokens / self._prompt_tokens

    @property
    def draft_accept_rate(self) -> float | None:
        if not self.serve.spec_k or not self._draft_proposed:
            return None
        return self._draft_accepted / self._draft_proposed

    def warmup(self) -> None:
        """Dispatch every compiled program once with INERT inputs (no
        active rows, no valid prefill tokens — every cache write masked
        away, outputs discarded), so compilation happens here and never
        inside a timed serving run. Idle-safe: pool/tables/stats are
        untouched; cache buffers round-trip through the donating calls.
        The step builders are memoized per geometry, so one warmed
        engine warms every engine sharing its geometry — including the
        whole speculative width ladder, which otherwise compiles lazily
        at the first round that drafts each width."""
        b = self.serve.n_slots
        n = self.cache.pages_per_seq
        key = jax.random.key(0)
        table = jnp.zeros((n,), jnp.int32)
        # prefill: zero valid tokens -> every write dropped
        self.cache.ck, self.cache.cv, _ = self._prefill(
            self.params, self.cache.ck, self.cache.cv,
            jnp.zeros((1, self.serve.prefill_chunk), jnp.int32),
            jnp.int32(0), jnp.int32(0), table, key)
        tables = jnp.zeros((b, n), jnp.int32)
        idle = jnp.zeros((b,), bool)
        keys = (jax.vmap(jax.random.key)(jnp.zeros((b,), jnp.uint32))
                if self._sampled else None)
        self.cache.ck, self.cache.cv, _ = self._decode(
            self.params, self.cache.ck, self.cache.cv,
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
            tables, idle, keys)
        for w in self._verify_widths:
            self.cache.ck, self.cache.cv, _ = self._verify[w](
                self.params, self.cache.ck, self.cache.cv,
                jnp.zeros((b, w), jnp.int32), jnp.zeros((b,), jnp.int32),
                jnp.ones((b,), jnp.int32), tables, idle, keys)

    # -- submission ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *, rid: str | None = None,
               arrival_s: float = 0.0, seed: int = 0,
               priority: str = "interactive",
               queue_budget_s: float | None = None,
               deadline_s: float | None = None,
               tenant: str | None = None) -> Request:
        prompt = [int(t) for t in prompt]
        if rid is None:
            rid = f"req-{self._auto_rid}"
            self._auto_rid += 1
        req = Request(rid=rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      arrival_s=float(arrival_s), seed=int(seed),
                      priority=priority, queue_budget_s=queue_budget_s,
                      deadline_s=deadline_s, tenant=tenant)
        # Stamp the request trace at entry into the serving tier: every
        # later rtrace record (admission, prefill, decode, terminal)
        # rides this identity. No stream, no stamp — rtrace then no-ops
        # everywhere downstream.
        if self.telemetry is not None and req.trace_id is None:
            req.trace_id = tracing.new_trace_id()
            self._rtrace(req, "submitted", prompt_tokens=req.prompt_len,
                         max_new_tokens=req.max_new_tokens,
                         priority=req.priority)
        return self.enqueue(req)

    def _rtrace(self, req: Request, event: str, **fields) -> None:
        """Engine-side rtrace emission: this engine's stream as the
        sink, tagged with the replica origin in fleet mode."""
        tracing.rtrace(req, event, sink=self.telemetry,
                       **self._trace_fields, **fields)

    def _validate_prompt(self, req: Request) -> None:
        bad = [t for t in req.prompt
               if not (0 <= t < self.cfg.vocab_size)]
        if bad:
            raise ValueError(f"prompt tokens {bad} outside vocab "
                             f"[0, {self.cfg.vocab_size})")

    def enqueue(self, req: Request, *, force: bool = False) -> Request:
        """Accept an already-built :class:`Request` — the fleet router's
        entry point (serve/fleet.py), and the re-admission path for a
        request drained off a quarantined peer (its committed tokens,
        cursor and ``resume`` payload ride on the object). A full
        bounded queue (``ServeConfig.max_queue``) REJECTS the request
        with a typed ``shed`` record (reason ``queue-full``) instead of
        growing without bound — callers check ``req.done``.
        ``force=True`` bypasses the bound: a migrated-in request is
        already-admitted load being moved, not new demand, and must
        never be dropped by its destination's queue bound."""
        self._validate_prompt(req)
        # The bound rejects ALREADY-ARRIVED submissions against the live
        # arrived backlog (the runaway-client case). Future-dated
        # open-loop trace entries are pre-registrations, not load — they
        # enqueue, and the per-iteration overflow trim (``_iterate``)
        # bounds the live backlog once they arrive.
        if (not force and self.sched.max_queue is not None
                and req.arrival_s <= self._now
                and self.sched.arrived_backlog(self._now)
                >= self.sched.max_queue):
            self._reject(req, "queue-full")
            return req
        self.sched.submit(req)
        self._requests.append(req)
        return req

    def try_enqueue(self, req: Request) -> bool:
        """Bounded enqueue with NO side effects on refusal — the fleet
        dispatcher's entry point: a ``False`` feeds the router's
        circuit breaker and the request stays on the fleet queue."""
        self._validate_prompt(req)
        if self.sched.full:
            return False
        self.sched.submit(req)
        self._requests.append(req)
        return True

    def _reject(self, req: Request, reason: str) -> None:
        """Typed submission rejection (queue-full): terminal, counted,
        recorded — never an unbounded host-side list."""
        req.state = RequestState.FAILED
        req.shed_reason = reason
        req.error = f"rejected: {reason}"
        if self.journal is not None:
            # A rejected request usually predates its intent (the
            # journal drops unknown rids); a fleet-accepted one whose
            # re-dispatch bounced still owes its single terminal.
            self.journal.terminal(req.rid, "shed")
        if self.meter is not None:
            self.meter.terminal(req, "shed", self.telemetry)
        self._rtrace(req, "shed", reason=reason, state="queued")
        self._requests.append(req)
        self._rejected += 1
        self._shed_by_reason[reason] = self._shed_by_reason.get(reason, 0) + 1
        if self._slo_metrics:
            reg = registry()
            reg.counter("serve_rejected_total").inc()
            reg.counter("serve_shed_total").inc()
        if self.telemetry is not None:
            self.telemetry.record(
                "shed", request=req.rid, reason=reason,
                priority=req.priority, state="queued",
                policy=self.serve.policy, prompt_tokens=req.prompt_len,
                new_tokens=len(req.generated),
                **({"replica": self.replica}
                   if self.replica is not None else {}))

    # -- live migration (serve/fleet.py) ------------------------------------

    def drain(self) -> list[Request]:
        """Take every live request off this engine for migration to a
        peer replica, in submission order. Each resident request's
        committed state is serialized onto the object itself: the
        ``resume`` payload carries its written KV pages **by value**
        (``PagedKVCache.export_request``), so nothing references this
        engine's pool or radix tree afterwards. Queued requests ride
        along untouched (a queued request that was itself migrated in
        keeps the payload it still carries). Slots and pages return to
        this engine immediately; terminal requests stay for the record.
        """
        out: list[Request] = []
        for req in self._requests:
            if req.done:
                continue
            if req.slot is not None:
                if req.state is RequestState.PREFILL:
                    # Positions [0, cursor) are prefilled and written.
                    n_written = req.prefill_cursor
                else:
                    # Plain decode feeds a committed token back BEFORE
                    # writing its KV, so the last committed token's slot
                    # is unwritten (and under speculation may hold a
                    # rejected draft's write) — the same boundary
                    # ``_complete`` trims before the prefix tree.
                    n_written = req.prompt_len + len(req.generated) - 1
                k, v = self.cache.export_request(
                    req.rid, n_written, req=req, sink=self.telemetry,
                    trace_fields=self._trace_fields)
                req.resume = {
                    "k": k, "v": v, "n_written": n_written,
                    "state": ("decode" if req.state is RequestState.DECODE
                              else "prefill"),
                }
                req.state = RequestState.QUEUED
            if self.meter is not None:
                # Residency ends here for this replica: a ``hop`` meter
                # record bills it for exactly what it hosted (hop index
                # = the residency being closed; the destination's next
                # record carries migrations + 1, so the chain links).
                self.meter.close_hop(req, self.telemetry)
            self.sched.withdraw(req)
            self._proposers.pop(req.rid, None)
            self._spec_streak.pop(req.rid, None)
            self._spec_live.pop(req.rid, None)
            req.migrations += 1
            out.append(req)
        self._requests = [r for r in self._requests if r.done]
        return out

    def _restore_imported(self, req: Request) -> None:
        """Finish admitting a migrated-in request: its pages are already
        imported — resume at the exact committed position (mid-prefill
        cursors are chunk-aligned, so the remaining chunks replay the
        cold run's exact program stream; mid-decode requests re-enter
        the decode batch as if they had never left)."""
        payload = req.resume
        req.resume = None
        req.state = (RequestState.DECODE if payload["state"] == "decode"
                     else RequestState.PREFILL)
        if self.serve.spec_k:
            # The proposer is a pure function of the committed stream —
            # rebuild it from prompt + committed tokens. Gating restarts
            # in shadow mode (re-prove on this replica); that moves WHEN
            # drafts ride, never which tokens commit.
            prop = NGramProposer(self.serve.spec_k,
                                 max_order=self.serve.spec_ngram)
            prop.extend(req.prompt)
            prop.extend(req.generated)
            self._proposers[req.rid] = prop

    def clear_cache(self) -> int:
        """Drop the prefix tree and verify every page is back on the
        free list — the quarantine invariant ("all pages of the dead
        replica are returned"). Call after :meth:`drain`; a page still
        held here would mean an exported request left a reference
        behind. Returns the tree pages freed."""
        freed = self.cache.drop_prefix()
        if self.cache.pool.used_pages:
            raise PagePoolError(
                f"engine {self._provider}: {self.cache.pool.used_pages} "
                f"pages still held after drain + prefix drop")
        return freed

    # -- hard crash (serve/journal.py crash recovery) -----------------------

    def kill(self, reason: str = "injected-crash") -> None:
        """Hard-crash this engine: NO drain, no per-request terminals —
        engine object, page pool and prefix tree are simply abandoned
        (``ServeFleet.crash_replica`` discards them). The exhaust is a
        typed failure record carrying the journal position (the exact
        replay point) and a flight-recorder bundle, so the postmortem is
        self-contained; re-serving the lost requests is the journal's
        job, not this method's."""
        if self.telemetry is not None:
            self.telemetry.failure(
                "replica-crashed", detail=reason,
                iteration=self._iterations,
                **({"replica": self.replica}
                   if self.replica is not None else {}),
                **({"journal": self.journal.position()}
                   if self.journal is not None else {}))
        from distributed_model_parallel_tpu.utils import flightrec

        flightrec.dump("replica-crashed", telemetry_run=self.telemetry)

    # -- the loop -----------------------------------------------------------

    def run(self, *, max_iterations: int | None = None,
            record_summary: bool = True) -> dict:
        """Drive the loop until every submitted request is terminal (or
        ``max_iterations``). Returns the summary dict (also emitted as
        the ``serve`` summary telemetry record unless
        ``record_summary=False`` — multi-wave drivers like BENCH_serve's
        chat mode run() per wave and record ONE campaign summary at the
        end instead of one per wave)."""
        t0 = self._clock()
        try:
            # Spans from the loop (prefill chunks, decode rounds,
            # admissions) go to this engine's own stream for the scope
            # of the run — the request-lifecycle timeline
            # scripts/dmp_trace.py renders next to the per-request
            # serve records.
            with tracing.sink_scope(self.telemetry):
                while not self.sched.idle():
                    if (max_iterations is not None
                            and self._iterations >= max_iterations):
                        break
                    now = self._clock() - t0
                    made_progress = self.step_once(now, t0)
                    if not made_progress:
                        nxt = self.sched.next_arrival()
                        if nxt is not None:
                            # Open loop: nothing resident, next request
                            # not arrived yet — sleep to its arrival (a
                            # virtual clock skips straight there).
                            adv = getattr(self._clock, "advance_to",
                                          None)
                            if adv is not None:
                                adv(t0 + nxt)
                            else:
                                time.sleep(max(0.0, min(nxt - now,
                                                        0.05)))
        except BaseException as e:
            self._fail_inflight(f"{type(e).__name__}: {e}")
            self._wall_s += self._clock() - t0
            if self.telemetry is not None:
                self.telemetry.failure(
                    "engine-killed", detail=f"{type(e).__name__}: {e}",
                    iteration=self._iterations,
                    **({"journal": self.journal.position()}
                       if self.journal is not None else {}))
            # Crash flight recorder (utils/flightrec.py): capture the
            # state at the moment of death — ring records, thread
            # stacks, span stacks, page-pool state. No-op when no
            # recorder is installed.
            from distributed_model_parallel_tpu.utils import flightrec

            flightrec.dump("engine-killed", telemetry_run=self.telemetry,
                           error=e)
            if not isinstance(e, Exception):
                # KeyboardInterrupt/SystemExit keep their semantics —
                # the typed-failure bookkeeping above still ran.
                raise
            raise EngineKilled(
                f"engine died at iteration {self._iterations}; "
                f"in-flight requests marked failed") from e
        # Accumulate: a multi-turn driver (BENCH_serve chat mode) calls
        # run() per wave and reads one whole-campaign summary at the end.
        self._wall_s += self._clock() - t0
        return self.summary(record=record_summary)

    def step_once(self, now: float, t0: float) -> bool:
        """One engine iteration (admit → prefill chunk(s) → decode round
        → evict) at open-loop clock ``now`` (seconds since the monotonic
        origin ``t0``). ``run()`` loops over this; the fleet
        (serve/fleet.py) drives its replicas' iterations round-robin
        through it directly so every replica shares one clock."""
        if self.step_hook is not None:
            self.step_hook(self._iterations)
        self._iterations += 1
        self._now = now
        w0 = time.monotonic()
        progress = False
        try:
            progress = self._iterate(now, t0)
            return progress
        finally:
            dt = time.monotonic() - w0
            self._iter_s.append(dt)
            if self.meter is not None:
                # The SAME wall sample just appended to _iter_s — that
                # identity is what makes the duty buckets partition the
                # iteration wall exactly (dmp_capacity --gate). A raise
                # out of _iterate ticks with progress=False; the dead
                # engine's ledger still sums to its wall.
                self.meter.tick(
                    dt, progress=progress,
                    brownout=(self.brownout is not None
                              and self.brownout.level >= 1),
                    has_work=(any(r is not None for r in self.sched.slots)
                              or self.sched.arrived_backlog(now) > 0),
                    cache=self.cache)

    def _iterate(self, now: float, t0: float) -> bool:
        progress = False
        # Overload protection first: shed queued requests past their
        # budgets, abort in-flight ones past their total deadline (pages
        # return immediately — before admission, so a freed reservation
        # can admit someone this very iteration), then apply the
        # brownout ladder's admission-side knobs.
        for req, reason in self.sched.expire(now):
            self._shed(req, reason, now)
        for req in self.sched.active():
            dl = (req.deadline_s if req.deadline_s is not None
                  else self.serve.deadline_s)
            if dl is not None and now - req.arrival_s > dl:
                self._shed(req, "total-deadline", now)
        bo = self.brownout
        if bo is not None:
            from distributed_model_parallel_tpu.serve.overload import (
                apply_max_new_cap,
            )

            self.sched.prefill_chunks_per_iter = (
                self.serve.prefill_chunks_per_iter
                if bo.prefill_full_share else 1)
            # Clamp while waiting under level-3 brownout: the
            # reservation shrinks BEFORE admission bills it. The clamp
            # sticks (deterministic accounting); the clamped stream is
            # the bitwise prefix of the unclamped one. Each newly
            # clamped request gets a ``clamp`` rtrace record
            # (serve/overload.py).
            apply_max_new_cap(bo, self.sched.queue, now,
                              sink=self.telemetry,
                              trace_fields=self._trace_fields)
        for req in self.sched.admit(now):
            self._tables_np[req.slot] = self.cache.table_array(req.rid)
            if self.meter is not None:
                # Residency starts here for cold, migrated-in and
                # crash-replayed admissions alike — each replica bills
                # only the residency it actually hosts.
                self.meter.open_bill(req.rid)
            if req.resume is not None:
                # A migrated-in request: its pages were imported by the
                # scheduler; resume at the exact committed position —
                # no prompt/cache accounting (its prefill was billed on
                # the source replica) and no second queue-wait sample.
                self._restore_imported(req)
                continue
            # Cache-hit admission: the shared pages already hold the
            # prefix KV — prefill starts at the first uncached token.
            req.prefill_cursor = req.cached_prompt_tokens
            self._prompt_tokens += req.prompt_len
            self._cached_tokens += req.cached_prompt_tokens
            if self.serve.spec_k:
                prop = NGramProposer(self.serve.spec_k,
                                     max_order=self.serve.spec_ngram)
                # Journal replays seed the proposer with the whole
                # replayed prefix (prompt + committed tokens minus the
                # re-sampled last); the final prefill chunk extends the
                # last one, so the stream carries every committed token.
                prop.extend(req.prefill_tokens)
                self._proposers[req.rid] = prop
            if self._slo_metrics and req.cached_prompt_tokens:
                registry().counter("serve_prefill_tokens_saved").inc(
                    req.cached_prompt_tokens)
            self._record_queue_wait(req)
        # Queue-bound trim AFTER admission (work-conserving: a request a
        # freed slot just absorbed must not count against the bound),
        # so the arrived backlog leaves every iteration within
        # max_queue — batch first, newest first.
        for req in self.sched.overflow(now):
            self._shed(req, "queue-full", now)
        for req in self.sched.prefilling():
            self._prefill_chunk(req, t0)
            progress = True
        decoding = self.sched.decoding()
        if decoding:
            self._decode_round(decoding, t0)
            progress = True
        occ = self.cache.occupancy
        self._occupancy.append(occ)
        if bo is not None:
            bo.observe_occupancy(occ)
            transition = bo.tick(now)
            if transition is not None:
                if self.telemetry is not None:
                    self.telemetry.record(
                        "brownout", policy=self.serve.policy,
                        **transition,
                        **({"replica": self.replica}
                           if self.replica is not None else {}))
                if self._slo_metrics and self.replica is None:
                    registry().gauge("serve_brownout_level").set(bo.level)
        # Fleet replicas (self.replica set) skip the process-global
        # gauge writes: N engines flapping one unlabeled gauge would
        # report whichever iterated last. The fleet aggregates ALL of
        # these gauges across live replicas itself (ServeFleet
        # _set_engine_gauges: occupancy max, shared-pages sum, pooled
        # hit/accept rates); per-replica values live on the /statusz
        # providers.
        if self._slo_metrics and self.replica is None:
            reg = registry()
            reg.gauge("serve_page_occupancy").set(occ)
            if self.serve.prefix_cache:
                reg.gauge("serve_shared_pages").set(self.cache.shared_pages)
                if self.cache_hit_rate is not None:
                    reg.gauge("serve_cache_hit_rate").set(
                        self.cache_hit_rate)
            if self.serve.spec_k and self.draft_accept_rate is not None:
                reg.gauge("serve_draft_accept_rate").set(
                    self.draft_accept_rate)
        return progress

    # -- prefill ------------------------------------------------------------

    def _prefill_chunk(self, req: Request, t0: float) -> None:
        with span("prefill_chunk", request=req.rid,
                  cursor=req.prefill_cursor):
            self._prefill_chunk_inner(req, t0)

    def _prefill_chunk_inner(self, req: Request, t0: float) -> None:
        chunk = self.serve.prefill_chunk
        # A journal-replay request prefills prompt + committed tokens
        # (minus the last, re-sampled below) — the crash-recovery path
        # (serve/journal.py); everyone else prefills just the prompt.
        seq = req.prefill_tokens
        replaying = req.replay and bool(req.generated)
        lo = req.prefill_cursor
        n_valid = min(chunk, len(seq) - lo)
        toks = np.zeros((1, chunk), np.int32)
        toks[0, :n_valid] = seq[lo:lo + n_valid]
        table = jnp.asarray(self._tables_np[req.slot])
        key = jax.random.key(req.seed)
        m = self.meter
        d0 = time.monotonic() if m is not None else 0.0
        self.cache.ck, self.cache.cv, tok = self._prefill(
            self.params, self.cache.ck, self.cache.cv, jnp.asarray(toks),
            jnp.int32(lo), jnp.int32(n_valid), table, key)
        if m is not None:
            # A prefill chunk owns the whole slice: its full dispatch
            # wall bills to this one request (utils/metering.py).
            m.bill_prefill(req.rid, time.monotonic() - d0)
        req.prefill_cursor = lo + n_valid
        if req.prefill_cursor < len(seq):
            self._rtrace(req, "prefill", cursor=req.prefill_cursor,
                         tokens=n_valid)
        else:
            # Final chunk: its sampled token is the request's first
            # generated token (position t0) — TTFT stops here. On a
            # replay it is the LAST journaled token, re-sampled: the
            # determinism contract (tokens = f(prompt, seed)) makes it
            # bitwise-identical, and we assert that rather than trust it.
            first = int(jax.device_get(tok)[0])
            if replaying:
                want = req.generated[-1]
                if first != want:
                    raise AssertionError(
                        f"journal replay diverged for {req.rid!r}: "
                        f"re-sampled token {first} != journaled {want} "
                        f"at position {len(seq)} — the determinism "
                        f"contract (tokens = f(prompt, seed)) is broken")
            else:
                req.generated.append(first)
                if self.journal is not None:
                    self.journal.commit(req.rid, (first,))
            req.replay = False
            if req.t_first_token is None:
                req.t_first_token = self._clock() - t0
                self._record_ttft(req)
            req.state = RequestState.DECODE
            self._rtrace(req, "prefill", cursor=req.prefill_cursor,
                         tokens=n_valid, ttft_s=self._ttft(req),
                         **({"replayed": len(req.generated)}
                            if replaying else {}))
            # Every prefilled position's KV is now written — offer the
            # pages to the prefix tree so the next request with this
            # prefix (the multi-turn case) admits warm. ``seq`` is the
            # prompt, or on replay the prompt + committed tokens minus
            # the re-sampled last — the same verified-written trim
            # boundary ``_complete`` uses.
            self.cache.insert_prefix(req.rid, seq)
            # The proposer's stream must carry EVERY committed token —
            # skipping the first generated one would shift its whole
            # index around the prompt/generation boundary.
            prop = self._proposers.get(req.rid)
            if prop is not None:
                self._shadow_score(req, first)
                prop.extend([first])
            if self._finished(req, first):
                self._complete(req, t0)

    # -- decode -------------------------------------------------------------

    def _decode_round(self, decoding: list[Request], t0: float) -> None:
        # Brownout level >= 1 sheds the speculative verify windows: the
        # single-token program commits identical tokens (the pinned
        # spec-on/off parity) at guaranteed-progress cost per round.
        spec = bool(self._verify) and (self.brownout is None
                                       or self.brownout.spec_enabled)
        with span("decode_round", batch=len(decoding), spec=spec):
            if spec:
                self._spec_round_inner(decoding, t0)
            else:
                self._decode_round_inner(decoding, t0)

    def _decode_round_inner(self, decoding: list[Request], t0: float) -> None:
        b = self.serve.n_slots
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        seeds = np.zeros((b,), np.uint32)
        for req in decoding:
            s = req.slot
            tokens[s] = req.generated[-1]
            positions[s] = req.prompt_len + len(req.generated) - 1
            active[s] = True
            seeds[s] = req.seed
        keys = (jax.vmap(jax.random.key)(jnp.asarray(seeds))
                if self._sampled else None)
        m = self.meter
        d0 = time.monotonic() if m is not None else 0.0
        self.cache.ck, self.cache.cv, nxt = self._decode(
            self.params, self.cache.ck, self.cache.cv,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(self._tables_np), jnp.asarray(active), keys)
        nxt = np.asarray(jax.device_get(nxt))
        if m is not None:
            # The round's wall (dispatch + host sync) apportions evenly
            # across the live decode slots it served.
            m.bill_decode([r.rid for r in decoding],
                          time.monotonic() - d0)
        self._decode_steps += 1
        self._decode_tokens += len(decoding)
        # Memory-pressure gauges ride every decode rtrace, computed once
        # per round (page state only moves on admission/eviction, never
        # inside the round) — the attribution that tells a memory stall
        # from a compute stall (ISSUE 16).
        gauges = (memory_gauges(self.cache) if self.telemetry is not None
                  else None)
        for req in decoding:
            tok = int(nxt[req.slot])
            req.generated.append(tok)
            if self.journal is not None:
                # Watermark the journal at the exact commit point — a
                # token enters ``generated`` iff the model chose it, so
                # the journal never sees a rejected draft.
                self.journal.commit(req.rid, (tok,))
            if gauges is not None:
                self._rtrace(req, "decode", new_tokens=1, **gauges)
            if self._finished(req, tok):
                self._complete(req, t0)
            else:
                # Spec engines route draft-less rounds through here —
                # score the shadow prediction, then feed the proposer
                # the committed token.
                prop = self._proposers.get(req.rid)
                if prop is not None:
                    self._shadow_score(req, tok)
                    prop.extend([tok])

    def _spec_round_inner(self, decoding: list[Request], t0: float) -> None:
        """One speculative round: every active slot verifies its n-gram
        draft in ONE fixed-width forward and commits the model-verified
        prefix — between 1 and ``width`` tokens per request per round.

        ``out[s, i]`` is the model's token for the position after window
        index ``i``; it is committed only while every draft before it
        matched the model's own choice, so the committed stream is
        bitwise the sequential decode stream (a draft can never smuggle
        in a token the model would not have produced — docs/SERVING.md,
        "Speculative decoding"). KV hygiene: a rejected draft leaves
        garbage KV only at positions at or past the NEXT round's window
        start, and every round rewrites its whole window before reading
        it, so garbage is always overwritten before it becomes readable;
        the last committed token's slot is the one position that may
        still hold a rejected write, which is why completion trims it
        before offering pages to the prefix tree.
        """
        b = self.serve.n_slots
        cap = self.serve.spec_k + 1
        proposals: dict[str, list[int]] = {}
        for req in decoding:
            remaining = req.max_new_tokens - len(req.generated)
            if remaining > 1 and self._spec_live.get(req.rid):
                proposals[req.rid] = self._proposers[req.rid].propose()[
                    :min(cap, remaining) - 1]
            else:
                proposals[req.rid] = []      # shadow mode: prove it first
        longest = max((len(d) for d in proposals.values()), default=0)
        if longest == 0:
            # No row drafted (cold proposers, backoff, ends of budgets):
            # the single-token program commits the identical tokens (the
            # spec-on/off parity the tests pin) at 1/width the FLOPs.
            self._decode_round_inner(decoding, t0)
            return
        # Smallest compiled verify width covering the longest live draft.
        width = next(w for w in self._verify_widths if w >= longest + 1)
        tokens = np.zeros((b, width), np.int32)
        positions = np.zeros((b,), np.int32)
        # Idle rows keep n_valid=1 (writes are dropped via the active
        # mask; a zero-length row would make its garbage softmax all
        # -inf, and NaNs — however masked — have no business existing).
        n_valid = np.ones((b,), np.int32)
        active = np.zeros((b,), bool)
        seeds = np.zeros((b,), np.uint32)
        drafts: dict[str, list[int]] = {}
        for req in decoding:
            s = req.slot
            remaining = req.max_new_tokens - len(req.generated)
            w = min(width, remaining)
            draft = proposals[req.rid][:w - 1]
            drafts[req.rid] = draft
            tokens[s, 0] = req.generated[-1]
            tokens[s, 1:1 + len(draft)] = draft
            positions[s] = req.prompt_len + len(req.generated) - 1
            n_valid[s] = w
            active[s] = True
            seeds[s] = req.seed
        keys = (jax.vmap(jax.random.key)(jnp.asarray(seeds))
                if self._sampled else None)
        m = self.meter
        d0 = time.monotonic() if m is not None else 0.0
        self.cache.ck, self.cache.cv, out = self._verify[width](
            self.params, self.cache.ck, self.cache.cv,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(n_valid), jnp.asarray(self._tables_np),
            jnp.asarray(active), keys)
        out = np.asarray(jax.device_get(out))
        if m is not None:
            # A verify round is one batched forward like plain decode —
            # equal shares per live slot regardless of draft widths.
            m.bill_decode([r.rid for r in decoding],
                          time.monotonic() - d0)
        self._decode_steps += 1
        round_proposed = round_accepted = 0
        gauges = (memory_gauges(self.cache) if self.telemetry is not None
                  else None)
        for req in decoding:
            s = req.slot
            draft = drafts[req.rid]
            emitted: list[int] = []
            for i in range(int(n_valid[s])):
                if i > 0 and tokens[s, i] != out[s, i - 1]:
                    break                      # draft i-1 rejected
                tok = int(out[s, i])
                emitted.append(tok)
                if (self.serve.eos_id is not None
                        and tok == self.serve.eos_id):
                    break
            req.generated.extend(emitted)
            if self.journal is not None:
                # Only the model-verified prefix reaches ``generated``
                # (the loop above breaks at the first rejected draft),
                # so the watermark can never advance past a speculative
                # tail the model didn't commit.
                self.journal.commit(req.rid, emitted)
            self._decode_tokens += len(emitted)
            # Accept accounting over REAL proposals only (window padding
            # that happens to match is decode luck, not drafting).
            accepted = max(0, min(len(emitted) - 1, len(draft)))
            round_proposed += len(draft)
            round_accepted += accepted
            if draft:
                if accepted == 0:
                    # Streak broken: back to shadow mode until the
                    # proposer re-proves itself on committed tokens.
                    self._spec_live[req.rid] = False
                    self._spec_streak[req.rid] = 0
            else:
                self._shadow_score(req, emitted[0])
            if gauges is not None:
                self._rtrace(req, "decode", new_tokens=len(emitted),
                             spec_proposed=len(draft),
                             spec_accepted=accepted, **gauges)
            if self._finished(req, emitted[-1]):
                self._complete(req, t0)
            else:
                self._proposers[req.rid].extend(emitted)
        self._draft_proposed += round_proposed
        self._draft_accepted += round_accepted
        if self._slo_metrics and round_proposed:
            reg = registry()
            reg.counter("serve_draft_tokens_proposed").inc(round_proposed)
            reg.counter("serve_draft_tokens_accepted").inc(round_accepted)

    def _shadow_score(self, req: Request, committed: int) -> None:
        """Score the proposer's single-token prediction against the
        token the model actually committed (called BEFORE the proposer
        sees it). Two consecutive hits promote the request to live
        drafting — the free filter that keeps verify width off the
        wander phase and on the predictable spans."""
        pred = self._proposers[req.rid].predict_next()
        if pred is not None and pred == committed:
            streak = self._spec_streak.get(req.rid, 0) + 1
            self._spec_streak[req.rid] = streak
            if streak >= 2:
                self._spec_live[req.rid] = True
        else:
            self._spec_streak[req.rid] = 0

    def _finished(self, req: Request, tok: int) -> bool:
        return (len(req.generated) >= req.max_new_tokens
                or (self.serve.eos_id is not None
                    and tok == self.serve.eos_id))

    # -- lifecycle ----------------------------------------------------------

    def _complete(self, req: Request, t0: float) -> None:
        req.t_done = self._clock() - t0
        req.state = RequestState.COMPLETED
        if self.journal is not None:
            # Durable terminal BEFORE the engine forgets the request —
            # dedup'd by rid, so a recovered request re-completing after
            # a crash that already journaled its terminal is a no-op
            # (exactly-once accounting).
            self.journal.terminal(req.rid, "completed")
        # Offer the whole committed sequence (prompt + generation) to the
        # prefix tree BEFORE eviction drops our page references — this is
        # what makes a multi-turn follow-up (prior turns re-sent as the
        # new prompt) admit warm. The final token is always trimmed: its
        # KV slot is either unwritten (plain decode feeds a token back
        # before writing it) or may hold a rejected draft's write
        # (speculative rounds) — only verified-written positions are
        # shareable.
        self.cache.insert_prefix(
            req.rid, (req.prompt + req.generated)[:-1])
        self._proposers.pop(req.rid, None)
        self._spec_streak.pop(req.rid, None)
        self._spec_live.pop(req.rid, None)
        if self.meter is not None:
            # Close the bill BEFORE eviction drops the page table, so
            # the meter record reflects the final page reservation.
            self.meter.terminal(
                req, "completed", self.telemetry,
                good_tokens=(len(req.generated)
                             if self._in_deadline(req) else 0))
        self.sched.evict(req)
        if self.brownout is not None:
            self.brownout.observe_completed(self._ttft(req), req.t_done)
        token_s = None
        if len(req.generated) > 1 and req.t_first_token is not None:
            token_s = ((req.t_done - req.t_first_token)
                       / (len(req.generated) - 1))
        if self._slo_metrics:
            reg = registry()
            reg.counter("serve_requests_completed").inc()
            reg.counter("serve_tokens_generated").inc(len(req.generated))
            if token_s is not None:
                reg.histogram("serve_token_latency_s").observe(
                    token_s, exemplar=req.trace_id)
        self._rtrace(req, "completed", new_tokens=len(req.generated),
                     ttft_s=self._ttft(req),
                     queue_wait_s=self._queue_wait(req),
                     token_latency_s=token_s,
                     wall_s=req.t_done - req.arrival_s)
        if self.telemetry is not None:
            self.telemetry.record(
                "serve", event="completed", request=req.rid,
                policy=self.serve.policy,
                prompt_tokens=req.prompt_len,
                new_tokens=len(req.generated),
                queue_wait_s=self._queue_wait(req),
                ttft_s=self._ttft(req), token_latency_s=token_s,
                wall_s=req.t_done - req.arrival_s,
                **({"replica": self.replica, "migrations": req.migrations}
                   if self.replica is not None else {}))

    def _shed(self, req: Request, reason: str, now: float) -> None:
        """Shed one request with a typed record: a queued expiry (the
        scheduler already dequeued it) or an in-flight deadline abort —
        the latter evicts mid-stream, returning every reserved page
        immediately (chunk-aligned mid-prefill aborts included: eviction
        frees the whole table). Terminal, counted, never silent."""
        state_at = req.state.value
        if self.meter is not None:
            # One terminal meter record whether the request was resident
            # (deadline abort: its bill carries real cost) or still
            # queued (zero bill) — matching the rtrace terminal below.
            self.meter.terminal(
                req,
                "expired" if reason in ("total-deadline",
                                        "queue-deadline") else "shed",
                self.telemetry)
        if req.slot is not None:
            self.sched.evict(req)
        self._proposers.pop(req.rid, None)
        self._spec_streak.pop(req.rid, None)
        self._spec_live.pop(req.rid, None)
        req.state = RequestState.FAILED
        req.shed_reason = reason
        req.error = f"shed: {reason}"
        if self.journal is not None:
            self.journal.terminal(req.rid, "shed")
        self._shed_by_reason[reason] = self._shed_by_reason.get(reason, 0) + 1
        if reason == "queue-full":
            self._rejected += 1
        if self._slo_metrics:
            registry().counter("serve_shed_total").inc()
            if reason == "queue-full":
                registry().counter("serve_rejected_total").inc()
        # Typed terminal rtrace: deadline expiries are ``expired``,
        # everything else (queue-full displacement) is ``shed`` — the
        # joiner requires exactly one terminal event per trace.
        self._rtrace(req,
                     "expired" if reason in ("total-deadline",
                                             "queue-deadline") else "shed",
                     reason=reason, state=state_at,
                     waited_s=round(max(0.0, now - req.arrival_s), 4))
        if self.telemetry is not None:
            self.telemetry.record(
                "shed", request=req.rid, reason=reason,
                priority=req.priority, state=state_at,
                policy=self.serve.policy,
                waited_s=round(max(0.0, now - req.arrival_s), 4),
                prompt_tokens=req.prompt_len,
                new_tokens=len(req.generated),
                **({"replica": self.replica}
                   if self.replica is not None else {}))

    def _fail_inflight(self, detail: str) -> None:
        for req in self._requests:
            if req.done:
                continue
            if self.meter is not None:
                self.meter.terminal(req, "failed", self.telemetry)
            if req.slot is not None:
                self.sched.evict(req)
            elif any(q is req for q in self.sched.queue):
                self.sched.queue = deque(
                    q for q in self.sched.queue if q is not req)
            self._proposers.pop(req.rid, None)
            self._spec_streak.pop(req.rid, None)
            self._spec_live.pop(req.rid, None)
            req.state = RequestState.FAILED
            req.error = f"engine-killed: {detail}"
            if self.journal is not None:
                # A typed failure is REPORTED to the client, so it is a
                # real terminal: journal it and recovery never re-serves
                # the request. Hard crashes (Engine.kill) never run this
                # path — their requests stay non-terminal and the
                # journal replays them.
                self.journal.terminal(req.rid, "failed")
            self._rtrace(req, "failed", error="engine-killed")
            if self._slo_metrics:
                registry().counter("serve_requests_failed").inc()
            if self.telemetry is not None:
                self.telemetry.record(
                    "serve", event="failed", request=req.rid,
                    policy=self.serve.policy,
                    error="engine-killed", detail=detail,
                    prompt_tokens=req.prompt_len,
                    new_tokens=len(req.generated),
                    **({"replica": self.replica}
                       if self.replica is not None else {}))

    # -- SLO bookkeeping ----------------------------------------------------

    def _queue_wait(self, req: Request) -> float | None:
        if req.t_admitted is None:
            return None
        return max(0.0, req.t_admitted - req.arrival_s)

    def _ttft(self, req: Request) -> float | None:
        if req.t_first_token is None:
            return None
        return max(0.0, req.t_first_token - req.arrival_s)

    def _record_queue_wait(self, req: Request) -> None:
        w = self._queue_wait(req)
        if w is not None and self._slo_metrics:
            registry().histogram("serve_queue_wait_s").observe(
                w, exemplar=req.trace_id)

    def _record_ttft(self, req: Request) -> None:
        t = self._ttft(req)
        if t is not None and self._slo_metrics:
            registry().histogram("serve_ttft_s").observe(
                t, exemplar=req.trace_id)

    def _in_deadline(self, req: Request) -> bool:
        """Did this completed request land within its total deadline?
        (Always True with no deadline configured — goodput then equals
        throughput.)"""
        dl = (req.deadline_s if req.deadline_s is not None
              else self.serve.deadline_s)
        if dl is None or req.t_done is None:
            return True
        return req.t_done - req.arrival_s <= dl

    # -- results ------------------------------------------------------------

    def results(self) -> list[Request]:
        return list(self._requests)

    def summary(self, *, record: bool = True) -> dict:
        """Aggregate SLO + throughput view (and the ``serve`` summary
        record when a telemetry stream is attached and ``record``)."""
        completed = [r for r in self._requests
                     if r.state is RequestState.COMPLETED]
        # Shed requests (typed: deadlines, queue-full) are accounted
        # apart from real failures — shedding is the overload plane
        # WORKING, a failure is something breaking.
        shed = [r for r in self._requests
                if r.state is RequestState.FAILED and r.shed_reason]
        failed = [r for r in self._requests
                  if r.state is RequestState.FAILED and not r.shed_reason]
        tokens = sum(len(r.generated) for r in completed)
        goodput_tokens = sum(len(r.generated) for r in completed
                             if self._in_deadline(r))
        token_lat = [
            (r.t_done - r.t_first_token) / (len(r.generated) - 1)
            for r in completed
            if len(r.generated) > 1 and r.t_first_token is not None]
        out = {
            "policy": self.serve.policy,
            "n_slots": self.serve.n_slots,
            "requests_completed": len(completed),
            "requests_failed": len(failed),
            # Overload-protection accounting (docs/SERVING.md): typed
            # sheds by reason, bounded-queue rejections, goodput (tokens
            # of requests that completed WITHIN their deadline — equal
            # to tokens_generated when no deadline is configured), and
            # the brownout ladder's travel.
            "requests_shed": len(shed),
            "requests_rejected": self._rejected,
            "shed_by_reason": dict(sorted(self._shed_by_reason.items())),
            "goodput_tokens": goodput_tokens,
            "goodput_tokens_per_s": (goodput_tokens / self._wall_s
                                     if self._wall_s > 0 else None),
            "brownout": (self.brownout.summary()
                         if self.brownout is not None else None),
            "tokens_generated": tokens,
            "wall_s": self._wall_s,
            "tokens_per_s": (tokens / self._wall_s if self._wall_s > 0
                             else None),
            "iterations": self._iterations,
            "decode_steps": self._decode_steps,
            # Slot efficiency: useful tokens per decode ROUND over the
            # batch width — the deterministic (timing-free) continuous-
            # vs-static comparison the tests gate on. Under speculative
            # decoding a round can commit several tokens per slot, so
            # this can legitimately exceed 1.0 — there it reads as the
            # tokens-per-round speedup, not a utilization fraction.
            "slot_utilization": (
                self._decode_tokens
                / (self._decode_steps * self.serve.n_slots)
                if self._decode_steps else None),
            # Prefix-cache reuse + speculative decoding (docs/SERVING.md;
            # BENCH_serve chat mode gates on these).
            "prefix_cache": self.serve.prefix_cache,
            "spec_k": self.serve.spec_k,
            "cache_hit_rate": self.cache_hit_rate,
            "prefill_tokens_saved": self._cached_tokens,
            "shared_pages": self.cache.shared_pages,
            "cached_prefix_pages": (len(self.cache.prefix)
                                    if self.cache.prefix is not None
                                    else 0),
            "prefix_evictions": (self.cache.prefix.evictions
                                 if self.cache.prefix is not None else 0),
            "draft_accept_rate": self.draft_accept_rate,
            "draft_tokens_proposed": self._draft_proposed,
            "draft_tokens_accepted": self._draft_accepted,
            "ttft_s": summarize(
                [t for t in (self._ttft(r) for r in completed)
                 if t is not None]),
            "queue_wait_s": summarize(
                [w for w in (self._queue_wait(r) for r in completed)
                 if w is not None]),
            "token_latency_s": summarize(token_lat),
            "page_occupancy": summarize(self._occupancy),
            # REAL per-iteration wall time (monotonic even under a
            # SimClock) — the denominator of the crashrecovery
            # scenario's journal-overhead gate (< 3% of p50).
            "iteration_s": summarize(self._iter_s),
            # Resource-metering plane (utils/metering.py): duty-cycle
            # ledger, per-tenant cost rollup, metering's own overhead.
            "metering": (self.meter.summary()
                         if self.meter is not None else None),
        }
        if record and self.telemetry is not None:
            self.telemetry.record("serve", event="summary", **out)
            if self.meter is not None and self.replica is None:
                # Standalone engines emit their own utilization record;
                # fleet replicas' are emitted (with cell labels) by
                # ServeFleet.summary so quarantine time is folded first.
                self.meter.record_utilization(self.telemetry)
        return out
