"""Deterministic, config-driven fault injection for chaos-testing the
training stack.

The reference has no failure story at all (SURVEY.md §5) and — until this
module — neither did we have a way to *provoke* one on demand: the guards
(train/guards.py) and the recovery supervisor (train/resilience.py) could
only be tested against failures that happened to occur. A ``FaultInjector``
closes that gap: a ``RecoveryConfig.faults`` plan names exactly which fault
fires at exactly which occurrence of which hook site, so a chaos test (or
``scripts/dmp_chaos.py``) is a deterministic program, not a flaky race.

Fault taxonomy (``kind`` → hook site → effect):

=============  ======  =====================================================
kind           site    effect when fired
=============  ======  =====================================================
``nan_loss``   step    poison that step's metrics with NaN (a loss
                       explosion as the guards see it)
``nan_params`` step    poison the live parameters with NaN (detected at the
                       next params-cadence finiteness check)
``preempt``    step    request a graceful preemption (exactly what a TPU
                       maintenance SIGTERM does, minus the signal)
``stall``      sync    sleep ``param`` seconds inside the guarded blocking
                       drain, so the sync overruns the stall budget
``save_fail``  save    die "mid-write": leave a torn version directory
                       behind and raise ``InjectedFaultError``
``tear_save``  save    let the save commit, then truncate its files — the
                       torn-newest-checkpoint scenario a crashed writer or
                       partial copy leaves on disk
=============  ======  =====================================================

Sites are consulted by the trainers (``step``), ``GuardRunner.watch``
(``sync``) and ``Checkpointer.save`` (``save``). Each ``poll(site)`` call
advances that site's occurrence counter; a spec fires when its ``at`` index
matches — once, deterministically, independent of wall clock.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Sequence

__all__ = [
    "FAULT_SITES",
    "FaultInjector",
    "FaultSpec",
    "InjectedFaultError",
    "parse_faults",
    "poison",
    "tear_checkpoint",
]


class InjectedFaultError(RuntimeError):
    """Raised by an injected ``save_fail`` fault (never by real code paths)."""


FAULT_SITES = {
    "nan_loss": "step",
    "nan_params": "step",
    "preempt": "step",
    "stall": "sync",
    "save_fail": "save",
    "tear_save": "save",
}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One planned fault: ``kind`` fires at the ``at``-th occurrence
    (0-based) of its hook site; ``param`` is the kind-specific knob
    (sleep seconds for ``stall``)."""

    kind: str
    at: int
    param: float = 0.0

    def __post_init__(self):
        if self.kind not in FAULT_SITES:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: "
                f"{sorted(FAULT_SITES)}")
        if self.at < 0:
            raise ValueError(f"fault occurrence index must be >= 0, got "
                             f"{self.at}")

    @property
    def site(self) -> str:
        return FAULT_SITES[self.kind]


def parse_faults(spec: str) -> tuple[FaultSpec, ...]:
    """Parse a CLI/env fault plan: comma-separated ``kind@at[:param]``
    entries, e.g. ``"nan_loss@1,stall@0:0.5"``."""
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "@" not in entry:
            raise ValueError(
                f"bad fault entry {entry!r}: expected kind@at[:param]")
        kind, _, rest = entry.partition("@")
        at_s, _, param_s = rest.partition(":")
        out.append(FaultSpec(kind=kind.strip(), at=int(at_s),
                             param=float(param_s) if param_s else 0.0))
    return tuple(out)


def _coerce_spec(f: "FaultSpec | str") -> FaultSpec:
    if isinstance(f, FaultSpec):
        return f
    parsed = parse_faults(f)
    if len(parsed) != 1:
        raise ValueError(f"one fault entry expected, got {f!r}")
    return parsed[0]


class FaultInjector:
    """Deterministic fault firing against named hook sites.

    ``poll(site)`` advances the site's occurrence counter and returns the
    specs scheduled for that occurrence (usually zero or one). A disabled
    injector (empty plan) polls as a cheap no-op, so trainers can call it
    unconditionally. ``on_fire`` (settable after construction — the
    supervisor wires itself in) observes every firing for telemetry.
    """

    def __init__(self, faults: Sequence["FaultSpec | str"] = (),
                 *, on_fire: Callable[[FaultSpec, str, int], None]
                 | None = None):
        self.plan: tuple[FaultSpec, ...] = tuple(
            _coerce_spec(f) for f in (faults or ()))
        self.on_fire = on_fire
        self.fired: list[FaultSpec] = []
        self._counts: dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return bool(self.plan)

    def poll(self, site: str) -> list[FaultSpec]:
        if not self.plan:
            return []
        i = self._counts.get(site, 0)
        self._counts[site] = i + 1
        out = [s for s in self.plan if s.site == site and s.at == i]
        for s in out:
            self.fired.append(s)
            if self.on_fire is not None:
                self.on_fire(s, site, i)
        return out

    def maybe_stall(self, site: str = "sync") -> None:
        """Poll ``site`` and serve any ``stall`` fault by sleeping — called
        inside the watchdog-guarded region so the delay is observed."""
        for spec in self.poll(site):
            if spec.kind == "stall":
                time.sleep(spec.param)


def poison(tree: Any) -> Any:
    """NaN every floating-point leaf of a pytree (the injected-NaN faults'
    payload; non-float leaves pass through untouched)."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda x: (jnp.full_like(x, jnp.nan)
                   if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                   else x), tree)


def tear_checkpoint(path: str) -> None:
    """Simulate a torn checkpoint write: truncate every regular file under
    ``path`` to half its size (the integrity manifest, when present, is left
    intact so verification can catch the tear — exactly the state a crashed
    writer or interrupted copy leaves behind)."""
    from distributed_model_parallel_tpu.train.checkpoint import (
        MANIFEST_FILENAME,
    )

    for root, _dirs, files in os.walk(path):
        for fn in files:
            if fn == MANIFEST_FILENAME:
                continue
            p = os.path.join(root, fn)
            size = os.path.getsize(p)
            with open(p, "r+b") as f:
                f.truncate(size // 2)
