"""Deterministic, config-driven fault injection for chaos-testing the
training stack.

The reference has no failure story at all (SURVEY.md §5) and — until this
module — neither did we have a way to *provoke* one on demand: the guards
(train/guards.py) and the recovery supervisor (train/resilience.py) could
only be tested against failures that happened to occur. A ``FaultInjector``
closes that gap: a ``RecoveryConfig.faults`` plan names exactly which fault
fires at exactly which occurrence of which hook site, so a chaos test (or
``scripts/dmp_chaos.py``) is a deterministic program, not a flaky race.

Fault taxonomy (``kind`` → hook site → effect):

=============  ======  =====================================================
kind           site    effect when fired
=============  ======  =====================================================
``nan_loss``   step    poison that step's metrics with NaN (a loss
                       explosion as the guards see it)
``nan_params`` step    poison the live parameters with NaN (detected at the
                       next params-cadence finiteness check)
``preempt``    step    request a graceful preemption (exactly what a TPU
                       maintenance SIGTERM does, minus the signal)
``stall``      sync    sleep ``param`` seconds inside the guarded blocking
                       drain, so the sync overruns the stall budget
``save_fail``  save    die "mid-write": leave a torn version directory
                       behind and raise ``InjectedFaultError``
``tear_save``  save    let the save commit, then truncate its files — the
                       torn-newest-checkpoint scenario a crashed writer or
                       partial copy leaves on disk
``bitflip``    step    flip one bit of one element of leaf ``param`` on ONE
                       data-parallel replica (a silent data corruption, the
                       "cores that don't count" failure mode) — detectable
                       only by cross-replica comparison
                       (train/consistency.py)
``desync``     step    multiply every float leaf of one replica's params by
                       ``1 + param`` (default 1e-3): replica drift, as a
                       slowly-diverging core or torn HBM write produces
``grad_skew``  step    add ``param`` (default 1e-3) to every float leaf of
                       one replica's params — the accumulated effect of one
                       replica applying a skewed gradient
``slow_device`` step   PERSISTENT degradation: from the firing step on,
                       every step sleeps a linearly RAMPING delay
                       (``param`` seconds, default 0.05, times the number
                       of steps since firing, capped at 4x) — a device
                       thermal-throttling its way toward death, as the
                       health sentinel (utils/health.py) sees it
``flaky_sync`` sync    PERSISTENT degradation: from the firing sync on,
                       every SECOND guarded sync sleeps ``param`` seconds
                       (default 0.05) — an intermittently flaky link whose
                       stalls stay under the watchdog budget and are only
                       visible as latency jitter
``slow_replica`` serve PERSISTENT degradation: from the firing serve-site
                       poll on, every poll sleeps ``param`` seconds
                       (default 0.05) — a serving replica slowing down
                       (thermal throttle, noisy neighbor), as the health
                       sentinel's ``serve`` signal sees it. The serving
                       fleet polls this site inside the victim replica's
                       timed engine round (serve/fleet.py)
``crash_replica`` serve HARD-crash the victim replica at the firing
                       serve-site poll: engine object, page pool and
                       prefix tree discarded with NO drain — nothing
                       exported, exactly what a process death leaves
                       behind; the write-ahead journal re-admits every
                       accepted non-terminal request on a live peer at
                       its committed watermark
                       (serve/fleet.py ``crash_replica``)
``admission_fail`` admit PERSISTENT (bounded): from the firing admit-site
                       poll on, the next ``param`` admission attempts
                       (default 6) to the victim replica FAIL — a replica
                       whose submission path is broken while its residents
                       keep decoding; the router's circuit breaker is the
                       intended detector (serve/overload.py)
``kill_cell``  cell    quarantine + drain EVERY replica of the serving
                       fleet's victim cell at once (the regional-failure
                       shape: a rack power event, a cell-wide rollout
                       gone bad) — each member walks the REAL
                       quarantine→drain→migrate path and the cell grows
                       back as a unit (serve/fleet.py ``kill_cell``)
``slow_cell``  cell    PERSISTENT degradation: from the firing cell-site
                       poll on, the victim cell's replicas run engine
                       iterations only every ``param``-th fleet round
                       (default 4, must be >= 2) — a whole cell slowed
                       in lockstep (thermal event, antagonist job), so
                       its residents decode slower and its SLOs sag
                       while the rest of the fleet is untouched
``partition``  cell    PERSISTENT (bounded): for ``param`` cell-site
                       polls after firing (default 8) the router cannot
                       reach the victim cell — no new dispatches, no
                       migration placements land there — while its
                       residents keep decoding and drain out on heal
                       (serve/fleet.py queries ``partition_active``)
=============  ======  =====================================================

Sites are consulted by the trainers (``step``), ``GuardRunner.watch``
(``sync``) and ``Checkpointer.save`` (``save``). Each ``poll(site)`` call
advances that site's occurrence counter; a spec fires when its ``at`` index
matches — once, deterministically, independent of wall clock.

The three CORRUPTION_KINDS perturb exactly one data-parallel replica (the
highest replica index) via :func:`corrupt_one_replica` — a ``shard_map``
over the live mesh, so the corrupted copy exists only in that replica's
device buffers, exactly like real silent corruption. They therefore
require ``>= 2`` data-parallel replicas; trainers whose topology has no
replicated state reject them loudly at construction.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Sequence

__all__ = [
    "CORRUPTION_KINDS",
    "DEGRADATION_KINDS",
    "FAULT_SITES",
    "FaultInjector",
    "FaultSpec",
    "InjectedFaultError",
    "corrupt_one_replica",
    "parse_faults",
    "poison",
    "tear_checkpoint",
]


class InjectedFaultError(RuntimeError):
    """Raised by an injected ``save_fail`` fault (never by real code paths)."""


FAULT_SITES = {
    "nan_loss": "step",
    "nan_params": "step",
    "preempt": "step",
    "stall": "sync",
    "save_fail": "save",
    "tear_save": "save",
    "bitflip": "step",
    "desync": "step",
    "grad_skew": "step",
    "slow_device": "step",
    "flaky_sync": "sync",
    "slow_replica": "serve",
    "crash_replica": "serve",
    "admission_fail": "admit",
    "kill_cell": "cell",
    "slow_cell": "cell",
    "partition": "cell",
}

# Faults that silently corrupt ONE data-parallel replica's state (served by
# corrupt_one_replica); they need >= 2 replicas to be meaningful — and to be
# detectable at all.
CORRUPTION_KINDS = frozenset({"bitflip", "desync", "grad_skew"})

# PERSISTENT degradations: unlike every other kind (one effect at one
# occurrence), these register at their ``at`` occurrence and keep acting on
# every later poll of their site — gradual decline, not an event. Served by
# FaultInjector.poll itself (the injector owns the ramp state), detected by
# the device-health sentinel (utils/health.py), not by the guards.
DEGRADATION_KINDS = frozenset({"slow_device", "flaky_sync",
                               "slow_replica", "admission_fail",
                               "slow_cell", "partition"})

# slow_device ramp: delay = param * min(polls_since_firing, cap) — linear
# decline toward a bounded worst case, so a soak stays finite.
SLOW_DEVICE_RAMP_CAP = 4
# flaky_sync intermittency: sleep on every PERIOD-th sync after firing.
FLAKY_SYNC_PERIOD = 2
# admission_fail duration: admissions fail for this many admit-site polls
# after firing (param overrides) — bounded, so the breaker's half-open
# probe eventually lands and the cycle closes.
ADMISSION_FAIL_POLLS = 6
# slow_cell cadence: the victim cell's replicas run an engine iteration
# only every PERIOD-th fleet round while the degradation is active
# (param overrides; must be >= 2 or nothing is slowed).
SLOW_CELL_PERIOD = 4
# partition duration: the victim cell is router-unreachable for this
# many cell-site polls after firing (param overrides) — bounded, so the
# cell always heals and its residents drain out.
PARTITION_POLLS = 8


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One planned fault: ``kind`` fires at the ``at``-th occurrence
    (0-based) of its hook site; ``param`` is the kind-specific knob
    (sleep seconds for ``stall``). ``None`` means "not given" — each
    consumer applies its own documented default, and an EXPLICIT value
    is never silently replaced (``desync@5:0`` is rejected, not bumped
    to the default magnitude)."""

    kind: str
    at: int
    param: float | None = None

    def __post_init__(self):
        if self.kind not in FAULT_SITES:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: "
                f"{sorted(FAULT_SITES)}")
        if self.at < 0:
            raise ValueError(f"fault occurrence index must be >= 0, got "
                             f"{self.at}")

    @property
    def site(self) -> str:
        return FAULT_SITES[self.kind]


def parse_faults(spec: str) -> tuple[FaultSpec, ...]:
    """Parse a CLI/env fault plan: comma-separated ``kind@at[:param]``
    entries, e.g. ``"nan_loss@1,stall@0:0.5"``."""
    out = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "@" not in entry:
            raise ValueError(
                f"bad fault entry {entry!r}: expected kind@at[:param]")
        kind, _, rest = entry.partition("@")
        at_s, _, param_s = rest.partition(":")
        out.append(FaultSpec(kind=kind.strip(), at=int(at_s),
                             param=float(param_s) if param_s else None))
    return tuple(out)


def _coerce_spec(f: "FaultSpec | str") -> FaultSpec:
    if isinstance(f, FaultSpec):
        return f
    parsed = parse_faults(f)
    if len(parsed) != 1:
        raise ValueError(f"one fault entry expected, got {f!r}")
    return parsed[0]


class FaultInjector:
    """Deterministic fault firing against named hook sites.

    ``poll(site)`` advances the site's occurrence counter and returns the
    specs scheduled for that occurrence (usually zero or one). A disabled
    injector (empty plan) polls as a cheap no-op, so trainers can call it
    unconditionally. ``on_fire`` (settable after construction — the
    supervisor wires itself in) observes every firing for telemetry.
    """

    def __init__(self, faults: Sequence["FaultSpec | str"] = (),
                 *, on_fire: Callable[[FaultSpec, str, int], None]
                 | None = None):
        self.plan: tuple[FaultSpec, ...] = tuple(
            _coerce_spec(f) for f in (faults or ()))
        self.on_fire = on_fire
        self.fired: list[FaultSpec] = []
        self._counts: dict[str, int] = {}
        # Active persistent degradations (DEGRADATION_KINDS): spec ->
        # polls of its site since it fired. The injector owns the ramp
        # state so every trainer gets the decline for free via poll().
        self._degradations: dict[FaultSpec, int] = {}

    @property
    def enabled(self) -> bool:
        return bool(self.plan)

    @property
    def active_degradations(self) -> tuple[FaultSpec, ...]:
        return tuple(self._degradations)

    def poll(self, site: str) -> list[FaultSpec]:
        if not self.plan:
            return []
        i = self._counts.get(site, 0)
        self._counts[site] = i + 1
        out = [s for s in self.plan if s.site == site and s.at == i]
        for s in out:
            self.fired.append(s)
            if self.on_fire is not None:
                self.on_fire(s, site, i)
            if s.kind in DEGRADATION_KINDS:
                self._degradations[s] = 0
        self._serve_degradations(site)
        return out

    def _serve_degradations(self, site: str) -> None:
        """Serve the active persistent degradations scheduled on this
        site: ``slow_device`` sleeps its linear ramp on every step,
        ``flaky_sync`` sleeps intermittently (every FLAKY_SYNC_PERIOD-th
        sync). The sleeps land inside the trainers' timed regions, so
        the device-health sentinel observes them exactly like a real
        thermal throttle or flaky link (utils/health.py)."""
        for s, n in list(self._degradations.items()):
            if s.site != site:
                continue
            self._degradations[s] = n = n + 1
            if s.kind == "slow_device":
                time.sleep((s.param if s.param is not None else 0.05)
                           * min(n, SLOW_DEVICE_RAMP_CAP))
            elif s.kind == "flaky_sync" and n % FLAKY_SYNC_PERIOD == 0:
                time.sleep(s.param if s.param is not None else 0.05)
            elif s.kind == "slow_replica":
                # Flat per-round delay inside the fleet's timed engine
                # round (serve/fleet.py polls the serve site there) —
                # the health sentinel's serve signal sees the outlier.
                time.sleep(s.param if s.param is not None else 0.05)
            # admission_fail: no sleep — queried via admission_blocked().
            # slow_cell / partition: no sleep — queried by the fleet via
            # cell_slow_period() / partition_active(); a wall-clock
            # sleep would break the virtual-clock scenario replays.

    def admission_blocked(self) -> bool:
        """True while an ``admission_fail`` degradation is active: it
        fired, and fewer than its duration (``param`` admit-site polls,
        default ADMISSION_FAIL_POLLS) have elapsed since. The serving
        fleet consults this on every admission attempt to the victim
        replica (serve/fleet.py) — the failures open the router's
        circuit breaker, and the recovery closes it through a half-open
        probe."""
        for s, n in self._degradations.items():
            if s.kind != "admission_fail":
                continue
            dur = (int(s.param) if s.param is not None
                   else ADMISSION_FAIL_POLLS)
            if n <= dur:
                return True
        return False

    def cell_slow_period(self) -> int | None:
        """The active ``slow_cell`` degradation's iteration period, or
        ``None`` when no slow_cell is live: while active, the victim
        cell's replicas run an engine iteration only every period-th
        fleet round (serve/fleet.py) — lockstep cell-wide slowdown with
        no wall-clock sleep, so virtual-clock replays stay exact."""
        for s in self._degradations:
            if s.kind != "slow_cell":
                continue
            period = (int(s.param) if s.param is not None
                      else SLOW_CELL_PERIOD)
            if period < 2:
                raise ValueError(
                    f"slow_cell period must be >= 2 (a period of "
                    f"{period} slows nothing)")
            return period
        return None

    def partition_active(self) -> bool:
        """True while a ``partition`` degradation is active: it fired,
        and fewer than its duration (``param`` cell-site polls, default
        PARTITION_POLLS) have elapsed since. The serving fleet consults
        this once per round — an active partition removes the victim
        cell from the routing AND migration candidate sets while its
        residents keep decoding (serve/fleet.py)."""
        for s, n in self._degradations.items():
            if s.kind != "partition":
                continue
            dur = (int(s.param) if s.param is not None
                   else PARTITION_POLLS)
            if n <= dur:
                return True
        return False

    def maybe_stall(self, site: str = "sync") -> None:
        """Poll ``site`` and serve any ``stall`` fault by sleeping — called
        inside the watchdog-guarded region so the delay is observed."""
        for spec in self.poll(site):
            if spec.kind == "stall":
                time.sleep(spec.param or 0.0)


def poison(tree: Any) -> Any:
    """NaN every floating-point leaf of a pytree (the injected-NaN faults'
    payload; non-float leaves pass through untouched)."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda x: (jnp.full_like(x, jnp.nan)
                   if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                   else x), tree)


def validate_corruption_plan(plan: Sequence[FaultSpec], n_replicas: int,
                             *, context: str) -> None:
    """Reject a fault plan that injects silent corruption into a run with
    no replicated data axis to diverge (``n_replicas < 2``) — the shared
    fail-fast check every trainer constructor runs. ``context`` names the
    topology for the error message (e.g. ``"strategy='fsdp'"``)."""
    corrupting = sorted({s.kind for s in plan if s.kind in CORRUPTION_KINDS})
    if corrupting and n_replicas < 2:
        raise ValueError(
            f"corruption faults {corrupting} perturb one data-parallel "
            f"replica relative to the others, but {context} has "
            f"{n_replicas} replicated replica(s) — nothing to diverge "
            f"from, and no redundancy for the consistency sentinel to "
            f"detect it with")


def _spec_axes(pspec) -> set:
    """Mesh axis names a PartitionSpec shards over (also used by the
    consistency sentinel's sharding filter — train/consistency.py)."""
    out: set = set()
    for entry in tuple(pspec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.update(entry)
        else:
            out.add(entry)
    return out


def _combined_replica_index(axis_names) -> "Any":
    """Flat replica index over the (possibly hierarchical) data axes,
    row-major in axis order — must match the all_gather row order the
    consistency sentinel reads (pinned by tests/test_psum_canary.py)."""
    import jax

    idx = None
    for name in axis_names:
        i = jax.lax.axis_index(name)
        n = jax.lax.psum(1, name)
        idx = i if idx is None else idx * n + i
    return idx


def corrupt_one_replica(tree: Any, mesh_spec: Any, kind: str,
                        param: float | None = None, *,
                        replica: int | None = None) -> Any:
    """Silently corrupt ONE data-parallel replica's copy of ``tree``.

    Runs a ``shard_map`` over ``mesh_spec.mesh`` in which only the target
    replica (default: the highest replica index) perturbs its local block —
    the returned arrays carry divergent per-device buffers under a sharding
    that still *claims* replication over the data axis, exactly the state a
    flipped bit or drifting core leaves behind. Every leaf must be a
    committed ``jax.Array`` with a ``NamedSharding`` on that mesh.

    Effects (see the module fault table): ``bitflip`` flips the lowest
    exponent bit of element 0 of float leaf ``int(param)`` (default 0);
    ``desync`` multiplies every float leaf by ``1 + param``; ``grad_skew``
    adds ``param`` to every float leaf (both default to magnitude 1e-3
    when ``param`` is omitted; an explicit 0 is rejected — a
    zero-magnitude "corruption" corrupts nothing, so the drill would
    claim an injection that never happened).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    if kind not in CORRUPTION_KINDS:
        raise ValueError(f"not a corruption fault kind: {kind!r} "
                         f"(known: {sorted(CORRUPTION_KINDS)})")
    data_axes = mesh_spec.data_axes
    n_replicas = mesh_spec.num_data
    if n_replicas < 2:
        raise ValueError(
            f"corruption fault {kind!r} perturbs one replica relative to "
            f"the others, but the mesh has {n_replicas} data-parallel "
            f"replica(s) — nothing to diverge from")
    target = n_replicas - 1 if replica is None else int(replica)
    if not 0 <= target < n_replicas:
        # An out-of-range index matches no device in the shard_map mask,
        # so the "corruption" would silently touch nothing — the drill
        # would claim an injection that never happened (same
        # no-silent-no-op rule as the zero-magnitude rejection below).
        raise ValueError(
            f"corrupt_one_replica: replica index {target} out of range "
            f"for {n_replicas} data-parallel replicas")
    leaves, treedef = jax.tree.flatten(tree)
    for i, leaf in enumerate(leaves):
        if not isinstance(getattr(leaf, "sharding", None), NamedSharding):
            raise ValueError(
                f"corrupt_one_replica needs NamedSharding-committed leaves; "
                f"leaf {i} has {getattr(leaf, 'sharding', None)!r}")
    specs = tuple(leaf.sharding.spec for leaf in leaves)
    float_idx = [i for i, leaf in enumerate(leaves)
                 if jnp.issubdtype(leaf.dtype, jnp.floating)]
    if not float_idx:
        raise ValueError("corrupt_one_replica: tree has no float leaves")
    if kind == "bitflip":
        leaf_i = 0 if param is None else param
        if leaf_i != int(leaf_i):
            # "kind@at:param" parses params as floats; a fractional leaf
            # index silently truncated would corrupt a different tensor
            # than the drill asserts on — same no-silent-replacement rule
            # as the explicit-zero rejection for desync/grad_skew.
            raise ValueError(
                f"bitflip leaf index must be a whole number, got {param}")
        leaf_i = int(leaf_i)
        if not 0 <= leaf_i < len(float_idx):
            # A plan naming a leaf that doesn't exist would otherwise
            # corrupt some other tensor than the drill asserts on.
            raise ValueError(
                f"bitflip leaf index {leaf_i} out of range: the tree "
                f"has {len(float_idx)} float leaves")
        flip_leaf = float_idx[leaf_i]
        # The shard_map body flips element 0 of the LOCAL block, so a leaf
        # sharded over non-data axes (tp/pp) would otherwise get one flip
        # per shard — not the documented "one bit of one element". Gate
        # the flip to shard index 0 of those axes; copies along axes the
        # leaf is replicated over all flip (one logical element, kept
        # consistent within the replica).
        flip_sharded_other = tuple(
            a for a in _spec_axes(specs[flip_leaf]) if a not in data_axes)
    if param == 0 and kind in ("desync", "grad_skew"):
        raise ValueError(
            f"{kind} with explicit magnitude 0 perturbs nothing — omit "
            f"the param for the 1e-3 default or give a nonzero magnitude")
    scale = 1e-3 if param is None else param

    def body(*ls):
        bad = _combined_replica_index(data_axes) == target
        out = []
        for i, x in enumerate(ls):
            if i not in float_idx:
                out.append(x)
                continue
            if kind == "bitflip":
                if i != flip_leaf:
                    out.append(x)
                    continue
                # Flip the lowest exponent bit of element 0 — a large but
                # finite change (mantissa flips near zero can land on
                # denormals the CPU backend flushes back to zero).
                nbits = x.dtype.itemsize * 8
                uint = jnp.dtype(f"uint{nbits}")
                flat = x.reshape(-1)
                u = jax.lax.bitcast_convert_type(flat[0], uint)
                bit = jnp.asarray(1 << jnp.finfo(x.dtype).nmant, uint)
                flipped = jax.lax.bitcast_convert_type(u ^ bit, x.dtype)
                hit = bad
                if flip_sharded_other:
                    hit = jnp.logical_and(
                        bad,
                        _combined_replica_index(flip_sharded_other) == 0)
                out.append(flat.at[0].set(
                    jnp.where(hit, flipped, flat[0])).reshape(x.shape))
            elif kind == "desync":
                out.append(jnp.where(bad, x * (1.0 + scale), x))
            else:                                        # grad_skew
                out.append(jnp.where(bad, x + jnp.asarray(scale, x.dtype), x))
        return tuple(out)

    fn = jax.jit(jax.shard_map(body, mesh=mesh_spec.mesh, in_specs=specs,
                               out_specs=specs, check_vma=False))
    return jax.tree.unflatten(treedef, fn(*leaves))


def tear_checkpoint(path: str) -> None:
    """Simulate a torn checkpoint write: truncate every regular file under
    ``path`` to half its size (the integrity manifest, when present, is left
    intact so verification can catch the tear — exactly the state a crashed
    writer or interrupted copy leaves behind)."""
    from distributed_model_parallel_tpu.train.checkpoint import (
        MANIFEST_FILENAME,
    )

    for root, _dirs, files in os.walk(path):
        for fn in files:
            if fn == MANIFEST_FILENAME:
                continue
            p = os.path.join(root, fn)
            size = os.path.getsize(p)
            with open(p, "r+b") as f:
                f.truncate(size // 2)
