"""Device-health sentinel: rolling per-device scores with hysteresis.

Real TPU pods degrade *gradually* before they die — thermal throttling,
a flaky ICI link, a slowly failing HBM channel — and one slow device
stalls every synchronous collective for the whole job (the paper's DDP
internals: a ring allreduce moves at the pace of its slowest member).
The rest of the resilience stack reacts *after* a failure (watchdog
stall, NaN, torn checkpoint); this module is the proactive half: it
turns signals the stack already collects into a rolling per-device
health score, so the orchestrator can quarantine a straggler and migrate
its tenants through the ordinary preempt-checkpoint path *before* the
crash, and reinstate the device after a probation period.

Signals (all host-side wall clock, fed by the trainers and supervisors):

* per-step timing from the trainers' step windows (``observe_step``);
* sync/drain latency under the guard watch (``observe_sync``);
* consistency-sentinel fingerprint-fetch latency (``observe_fetch``,
  train/consistency.py);
* checkpoint I/O latency from the supervisor's good-slot saves
  (``observe_io``, train/resilience.py);
* serving-replica engine-iteration wall time (``observe_serve``,
  serve/fleet.py — the serving fleet is a tenant of this sentinel too:
  a quarantined replica's requests migrate live to its peers);
* watchdog stall escalations (``observe_stall`` — a hard penalty, no
  baseline needed).

Scoring model: every device starts at score 1.0. Timing observations are
compared against a per-(signal, device-slice) EWMA baseline — per slice,
because a CNN step and an LM step have nothing in common, and the first
``warmup`` observations only establish the baseline. An observation
exceeding ``max(baseline * outlier_factor, baseline + min_outlier_s)``
penalizes every device of the observing slice (a synchronous program
cannot tell *which* member stalled it — blame is shared, and the slice
that keeps stalling is the slice that holds the straggler); a healthy
observation credits them back. Hysteresis: a device whose score falls to
``quarantine_below`` is QUARANTINED (the orchestrator takes it out of
scheduling and migrates its holder); it is only reinstated after at
least ``min_probation_ticks`` quarantined control-loop ticks *and* its
score has healed past ``reinstate_above`` — the two thresholds are far
apart precisely so a device cannot flap in and out of service.

The monitor is deliberately pure bookkeeping: observations in, scored
state + typed events out. The orchestrator owns the actions (DevicePool
``quarantine``/``reinstate``, tenant migration, grow-back) — see
orchestrator/orchestrator.py. Trainers feed the module-level observe
functions, which no-op unless a monitor is :func:`install`-ed, so
standalone (non-orchestrated) runs pay one ``is None`` check per window.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Iterable, Sequence

__all__ = [
    "DeviceDegradedError",
    "DeviceHealthMonitor",
    "HealthPolicy",
    "install",
    "installed",
    "observe_fetch",
    "observe_io",
    "observe_serve",
    "observe_stall",
    "observe_step",
    "observe_step_warmed",
    "observe_sync",
    "uninstall",
]


class DeviceDegradedError(RuntimeError):
    """A degraded/quarantined device was asked to do scheduled work.

    Raised by :meth:`DeviceHealthMonitor.assert_usable` (and by
    ``DevicePool.assign``'s defensive check) when a grant would land on a
    device the health sentinel has quarantined — a scheduling bug, since
    quarantined devices are removed from the free pool."""


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Scoring and hysteresis knobs (see the module docstring).

    Defaults are sized for the soak's simulated degradations: ~3
    consecutive outlier steps quarantine a slice, ~3 quiet probation
    ticks heal it back. Production values would be larger on both sides.
    """

    # Observations per (signal, slice) that only establish the baseline.
    warmup: int = 3
    # Outlier when value > max(baseline * factor, baseline + min_s):
    # the ratio catches slow big-step devices, the absolute floor keeps
    # microsecond-step CPU jitter from ever counting as degradation.
    outlier_factor: float = 3.0
    min_outlier_s: float = 0.1
    # Baseline EWMA weight (healthy observations only — outliers must not
    # teach the baseline that slow is normal).
    ewma: float = 0.3
    # Score dynamics: [0, 1], start 1.0.
    outlier_penalty: float = 0.25
    stall_penalty: float = 0.5
    recovery_credit: float = 0.05
    # Probation healing per control-loop tick while quarantined (the
    # device is idle — no observations arrive to credit it).
    idle_credit: float = 0.25
    # Hysteresis thresholds: quarantine at/below the low one, reinstate
    # only past the high one (and after min_probation_ticks).
    quarantine_below: float = 0.35
    reinstate_above: float = 0.8
    min_probation_ticks: int = 3

    def __post_init__(self):
        if not (0.0 <= self.quarantine_below < self.reinstate_above <= 1.0):
            raise ValueError(
                f"hysteresis requires 0 <= quarantine_below < "
                f"reinstate_above <= 1, got {self.quarantine_below} / "
                f"{self.reinstate_above}")
        if self.warmup < 1:
            raise ValueError(f"warmup must be >= 1, got {self.warmup}")


HEALTHY = "healthy"
QUARANTINED = "quarantined"


class DeviceHealthMonitor:
    """Rolling per-device health scores from slice-level observations.

    Thread-safe: trainers observe from tenant threads while the
    orchestrator ticks from the control loop. Deterministic: state is a
    pure function of the observation/tick sequence (no wall clock, no
    rng), so a seeded campaign replays identical health transitions.
    """

    def __init__(self, policy: HealthPolicy | None = None):
        self.policy = policy or HealthPolicy()
        self._lock = threading.Lock()
        self._score: dict[int, float] = {}
        self._state: dict[int, str] = {}
        self._probation: dict[int, int] = {}
        # Devices whose quarantine event has not yet been DELIVERED to
        # the control loop: the tick that hands the event over must not
        # already count as probation (the orchestrator has not even
        # migrated the holder yet).
        self._quarantine_pending: set[int] = set()
        # (signal, slice-ids) -> [ewma baseline, n observations]
        self._baseline: dict[tuple, list] = {}
        self._events: list[dict] = []
        self.ticks = 0

    # -- views ---------------------------------------------------------------
    def score(self, device_id: int) -> float:
        with self._lock:
            return self._score.get(device_id, 1.0)

    def state(self, device_id: int) -> str:
        with self._lock:
            return self._state.get(device_id, HEALTHY)

    @property
    def quarantined_ids(self) -> tuple[int, ...]:
        with self._lock:
            return tuple(sorted(i for i, s in self._state.items()
                                if s == QUARANTINED))

    def quarantined_fraction(self, ids) -> float:
        """Fraction of ``ids`` currently quarantined (0.0 for an empty
        group) — the serving fleet's per-cell device-health rollup
        (serve/fleet.py ``_cell_status``)."""
        ids = list(ids)
        if not ids:
            return 0.0
        bad = set(self.quarantined_ids)
        return sum(1 for i in ids if i in bad) / len(ids)

    def snapshot(self) -> dict:
        """JSON-ready view of the sentinel's state — the statusz
        exporter's ``/statusz`` health block and the flight recorder's
        ``health.json``: per-device scores/states, the quarantine set,
        and the tick count."""
        with self._lock:
            ids = sorted(set(self._score) | set(self._state))
            return {
                "scores": {str(i): round(self._score.get(i, 1.0), 4)
                           for i in ids},
                "states": {str(i): self._state.get(i, HEALTHY)
                           for i in ids},
                "quarantined": sorted(i for i, s in self._state.items()
                                      if s == QUARANTINED),
                "ticks": self.ticks,
            }

    def assert_usable(self, device_ids: Iterable[int]) -> None:
        bad = sorted(set(device_ids) & set(self.quarantined_ids))
        if bad:
            raise DeviceDegradedError(
                f"devices {bad} are health-quarantined (scores "
                f"{[round(self.score(i), 3) for i in bad]}) — they must "
                f"not be scheduled until reinstated")

    # -- observations --------------------------------------------------------
    def _emit(self, event: str, devices: Sequence[int], **fields) -> None:
        self._events.append({"event": event,
                             "devices": [int(i) for i in devices],
                             **fields})

    def _penalize(self, ids: tuple[int, ...], amount: float, *,
                  signal: str, value: float, baseline: float | None) -> None:
        hit = []
        for i in ids:
            self._score[i] = max(0.0, self._score.get(i, 1.0) - amount)
            hit.append(i)
        self._emit("degrading", hit, signal=signal,
                   score=round(min(self._score[i] for i in hit), 4),
                   value=round(float(value), 4),
                   **({"baseline": round(baseline, 4)}
                      if baseline is not None else {}))
        for i in hit:
            if (self._state.get(i, HEALTHY) == HEALTHY
                    and self._score[i] <= self.policy.quarantine_below):
                self._state[i] = QUARANTINED
                self._probation[i] = 0
                self._quarantine_pending.add(i)
                self._emit("quarantine", [i],
                           score=round(self._score[i], 4))

    def observe(self, signal: str, device_ids: Iterable[int], value: float,
                n: int = 1) -> None:
        """One timing observation for a device slice: ``value`` is the
        per-unit wall time (e.g. per-step seconds averaged over an
        ``n``-step window). Outliers against the (signal, slice) baseline
        penalize every device of the slice; healthy values credit them
        and update the baseline."""
        ids = tuple(sorted(int(i) for i in device_ids))
        if not ids or value <= 0 or n <= 0:
            return
        p = self.policy
        with self._lock:
            base = self._baseline.setdefault((signal, ids), [0.0, 0])
            mean, count = base
            if count >= p.warmup and value > max(mean * p.outlier_factor,
                                                 mean + p.min_outlier_s):
                self._penalize(ids, p.outlier_penalty, signal=signal,
                               value=value, baseline=mean)
                return      # outliers never teach the baseline
            if count < p.warmup:
                # Warmup seeds the baseline with the MINIMUM observation:
                # the first window of a fresh slice carries one-time jit
                # compilation (seconds against a milliseconds steady
                # state), and seeding an average with it would blind the
                # outlier test to every real degradation under ~compile
                # time. The min is the honest steady-state floor.
                base[0] = value if count == 0 else min(mean, value)
            else:
                base[0] = (1 - p.ewma) * mean + p.ewma * value
            base[1] = count + 1
            if count >= p.warmup:
                for i in ids:
                    if self._state.get(i, HEALTHY) == HEALTHY:
                        self._score[i] = min(
                            1.0, self._score.get(i, 1.0)
                            + p.recovery_credit * n)

    def observe_stall(self, device_ids: Iterable[int],
                      blocked_s: float) -> None:
        """A watchdog stall escalation on this slice: hard penalty, no
        baseline (a stall-budget overrun is already an adjudicated
        anomaly — train/resilience.Watchdog)."""
        ids = tuple(sorted(int(i) for i in device_ids))
        if not ids:
            return
        with self._lock:
            self._penalize(ids, self.policy.stall_penalty, signal="stall",
                           value=blocked_s, baseline=None)

    # -- the control-loop edge -----------------------------------------------
    def tick(self) -> list[dict]:
        """Advance probation for quarantined devices and drain the event
        queue. The orchestrator calls this once per scheduling round and
        applies the transitions (``quarantine`` events -> DevicePool
        quarantine + holder migration; ``reinstate`` events -> pool
        reinstate + possible tenant grow-back)."""
        p = self.policy
        with self._lock:
            self.ticks += 1
            for i, st in sorted(self._state.items()):
                if st != QUARANTINED:
                    continue
                if i in self._quarantine_pending:
                    # This tick only delivers the quarantine event;
                    # probation starts on the next one.
                    self._quarantine_pending.discard(i)
                    continue
                self._probation[i] = self._probation.get(i, 0) + 1
                self._score[i] = min(1.0, self._score.get(i, 0.0)
                                     + p.idle_credit)
                if (self._probation[i] >= p.min_probation_ticks
                        and self._score[i] >= p.reinstate_above):
                    self._state[i] = HEALTHY
                    self._emit("reinstate", [i],
                               score=round(self._score[i], 4),
                               probation_ticks=self._probation[i])
            out, self._events = self._events, []
            return out


# ---------------------------------------------------------------------------
# Process-wide installation: trainers feed whatever monitor the
# orchestrator installed, and pay one None-check when none is.
# ---------------------------------------------------------------------------

_monitor: DeviceHealthMonitor | None = None


def install(monitor: DeviceHealthMonitor) -> DeviceHealthMonitor:
    """Install ``monitor`` as the process-wide health sink (the
    orchestrator does this for the duration of a campaign)."""
    global _monitor
    _monitor = monitor
    return monitor


def installed() -> DeviceHealthMonitor | None:
    return _monitor


def uninstall() -> None:
    global _monitor
    _monitor = None


def observe_step(device_ids: Iterable[int], per_step_s: float,
                 n: int = 1) -> None:
    """Per-step wall time for one drained step window (trainers)."""
    if _monitor is not None:
        _monitor.observe("step", device_ids, per_step_s, n)


def observe_step_warmed(trainer, device_ids: Iterable[int],
                        per_step_s: float, n: int = 1) -> None:
    """:func:`observe_step`, skipping the FIRST window of ``trainer``'s
    life (tracked via a ``_health_warmed`` attribute on it): a trainer's
    first window carries one-time jit compilation, and a re-admitted
    (migrated / grown-back) tenant must not have its fresh compile
    billed against the slice's steady-state baseline as a spurious
    degradation. One helper so all three trainers share the gate."""
    if n <= 0:
        return
    if not getattr(trainer, "_health_warmed", False):
        trainer._health_warmed = True
        return
    observe_step(device_ids, per_step_s, n)


def observe_serve(device_ids: Iterable[int], seconds: float) -> None:
    """One serving replica's engine-iteration wall time on its device
    slice (serve/fleet.py) — the signal that lets the sentinel
    quarantine a degrading replica and trigger live request migration.
    Fed per fleet round; a fleet constructed with its own monitor feeds
    that directly instead."""
    if _monitor is not None:
        _monitor.observe("serve", device_ids, seconds)


def observe_sync(device_ids: Iterable[int], seconds: float) -> None:
    """One guarded blocking drain's wall time (train/guards.py)."""
    if _monitor is not None:
        _monitor.observe("sync", device_ids, seconds)


def observe_fetch(device_ids: Iterable[int], seconds: float) -> None:
    """One consistency-sentinel fingerprint fetch (train/consistency.py)."""
    if _monitor is not None:
        _monitor.observe("fetch", device_ids, seconds)


def observe_io(device_ids: Iterable[int], seconds: float) -> None:
    """One checkpoint save's wall time (train/resilience.py note_good)."""
    if _monitor is not None:
        _monitor.observe("io", device_ids, seconds)


def observe_stall(device_ids: Iterable[int], blocked_s: float) -> None:
    """A watchdog stall escalation (train/resilience.py on_stall)."""
    if _monitor is not None:
        _monitor.observe_stall(device_ids, blocked_s)
