"""Tracing / profiling.

The reference's entire observability story is ``time.time()`` deltas averaged
per epoch (``utils.py:41,48,64-74``; SURVEY.md §5). Equivalent meters live in
``train/metrics.py`` (StepTimer). This module adds the TPU-native upgrade:
``jax.profiler`` traces viewable in TensorBoard/Perfetto, plus a lightweight
step-latency profiler for benchmarking jitted step functions.
"""

from __future__ import annotations

import contextlib
import statistics
import time
from typing import Callable

import jax


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/dmp_trace"):
    """Capture an XLA/TPU profiler trace for the enclosed region."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def time_step(fn: Callable, *args, warmup: int = 2, iters: int = 10,
              **kwargs) -> dict:
    """Steady-state latency of a jitted callable (seconds).

    Blocks on the last output each iteration, so async dispatch does not
    fake the numbers.
    """
    out = None
    for _ in range(warmup):
        out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        samples.append(time.perf_counter() - t0)
    return {
        "mean_s": statistics.fmean(samples),
        "median_s": statistics.median(samples),
        "min_s": min(samples),
        "max_s": max(samples),
        "iters": iters,
    }
