"""Tracing / profiling.

The reference's entire observability story is ``time.time()`` deltas averaged
per epoch (``utils.py:41,48,64-74``; SURVEY.md §5). Equivalent meters live in
``train/metrics.py`` (StepTimer). This module adds the TPU-native upgrade:
``jax.profiler`` traces viewable in TensorBoard/Perfetto, plus a lightweight
step-latency profiler for benchmarking jitted step functions.

**Why timing forces a host fetch:** on some device transports (notably the
remote-TPU tunnel this environment uses) ``jax.block_until_ready`` returns
before the device actually finishes, so per-call wall-clock around it
measures dispatch latency, not execution (observed: an 8192^3 matmul
"finishing" in 30µs ≈ 30,000 TFLOPS). A device→host copy of the result
cannot lie — the bytes only exist once the program ran. ``time_step``
therefore times a whole loop of calls bracketed by one host fetch, and
subtracts the separately-measured fetch round-trip cost.
"""

from __future__ import annotations

import contextlib
import re
import time
import warnings
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


# Published bf16 peak matmul throughput per chip (FLOP/s), keyed by
# device_kind prefix. Used to turn measured step time + XLA cost-analysis
# FLOPs into model-FLOPs-utilization (MFU) — an absolute efficiency number,
# unlike throughput ratios against a historical baseline.
TPU_PEAK_FLOPS: dict[str, float] = {
    "TPU v6": 918e12,        # v6e (Trillium)
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # bare "v5" = v5p
    "TPU v4 lite": 137.5e12,  # v4i
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}


# Published HBM bandwidth per chip (bytes/s), same prefix keying. Used for
# the bandwidth roofline: a step whose achieved bytes/s sits at this
# ceiling is HBM-bound — more MFU is not available without moving less
# data (fusion, layout, batching), which turns "the CNN rows are
# HBM-bound" from an assertion into a measurement (VERDICT r3 weak #1).
TPU_PEAK_HBM_BYTES: dict[str, float] = {
    "TPU v6": 1640e9,        # v6e (Trillium)
    "TPU v5p": 2765e9,
    "TPU v5 lite": 819e9,    # v5e
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v4 lite": 614e9,
    "TPU v4": 1228e9,
    "TPU v3": 900e9,
    "TPU v2": 700e9,
}


def match_device_kind(table: dict, device=None, *, kind: str | None = None):
    """Longest-prefix lookup of ``device.device_kind`` in ``table`` (so
    "TPU v5 lite..." hits a "TPU v5 lite" row, not "TPU v5"). Shared by the
    peak-FLOPs table here and the flash dispatch table
    (ops/pallas_attention.py). Returns the value or None.

    Pass ``kind`` to look up a recorded device_kind string without a live
    backend (scripts/dmp_report.py reads it from a telemetry stream)."""
    if kind is None:
        device = device if device is not None else jax.devices()[0]
        kind = getattr(device, "device_kind", "") or ""
    for prefix in sorted(table, key=len, reverse=True):
        if kind.startswith(prefix):
            return table[prefix]
    return None


def peak_flops_per_chip(device=None) -> float | None:
    """bf16 peak FLOP/s for ``device`` (default: devices()[0]); None when
    unknown (e.g. CPU), in which case MFU cannot be reported honestly."""
    return match_device_kind(TPU_PEAK_FLOPS, device)


def compiled_cost_analysis(jitted: Callable, *args) -> dict:
    """XLA cost analysis of the compiled program for ``jitted(*args)``
    (client-side on the HLO — no execution, no donation). One AOT compile
    serves every metric read from it; empty dict on failure.

    Two blind spots make the numbers unusable for programs that contain
    loops or pallas kernels (both verified on v5e, see the round-3 notes
    in bench.py):

    * ``lax.scan`` / ``while`` bodies are counted ONCE, not trip-count
      times — a stacked-blocks decoder reports 1/L of its dense math, a
      scanned multi-step program reports 1 step.
    * Custom calls (pallas kernels) have no registered cost and
      contribute zero — flash attention's score/value matmuls vanish.

    Use it only on loop-free, kernel-free programs (e.g. the CNN single
    train step), or as a lower-bound cross-check next to an analytic
    count such as :func:`lm_model_flops`."""
    try:
        return cost_analysis_of(jitted.lower(*args).compile())
    except Exception:
        return {}


def cost_analysis_of(compiled) -> dict:
    """Cost analysis of an already-compiled program (see
    :func:`compiled_cost_analysis` for the blind spots); empty on failure."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):    # older JAX: one dict per comp
            ca = ca[0] if ca else {}
        return dict(ca) if ca else {}
    except Exception:
        return {}


def compiled_flops(jitted: Callable, *args) -> float | None:
    """Total FLOPs per :func:`compiled_cost_analysis` (see its caveats)."""
    flops = compiled_cost_analysis(jitted, *args).get("flops")
    return float(flops) if flops else None


def peak_hbm_bytes_per_chip(device=None) -> float | None:
    """HBM bandwidth (bytes/s) for ``device``; None when unknown."""
    return match_device_kind(TPU_PEAK_HBM_BYTES, device)


# ---------------------------------------------------------------------------
# Buffer-donation audit: trace-time proof that donation held.
# ---------------------------------------------------------------------------

class DonationError(AssertionError):
    """An expected buffer donation was dropped (or never set up) by XLA.

    Dropped donation is a *silent* perf/memory regression: the step still
    computes the same numbers, it just holds two copies of the state —
    which is exactly how an OOM or a 2x live-memory surprise ships.
    """


# One alias entry of the HLO module header's input_output_alias field,
# e.g. ``{0}: (0, {}, may-alias)`` — (output index): (param number,
# param index, kind).
_ALIAS_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\(\s*(\d+)\s*,\s*\{[\d,\s]*\}\s*,\s*"
    r"(may-alias|must-alias)\s*\)")


def aot_compile(jitted: Callable, *args, **kwargs):
    """``jitted.lower(*args).compile()`` with lowering warnings captured:
    returns ``(compiled, warnings_list)``. One AOT compile serves cost
    analysis AND the donation report (bench.py does both from it).
    ``args`` may be concrete arrays or ``jax.ShapeDtypeStruct``s."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        compiled = jitted.lower(*args, **kwargs).compile()
    return compiled, list(caught)


def donation_report(compiled, caught=()) -> dict:
    """What happened to a compiled program's donated buffers:
    ``{"n_aliased", "aliased_params", "dropped"}``.

    * ``n_aliased`` — input→output alias pairs XLA committed to (the
      ``input_output_alias`` field of the compiled module header): these
      buffers are genuinely reused in place.
    * ``dropped`` — donations XLA could NOT use (jax's "Some donated
      buffers were not usable" lowering warning from ``caught``, captured
      instead of printed), as the warned shape strings, e.g.
      ``["uint8[512,32,32,3]"]``. Caveat: the warning fires at *lowering*
      — a jit whose lowering was already cached (the function was called
      before) re-raises nothing, so dropped-detection needs a fresh
      jitted fn (or the trainers' build-time audit).
    """
    dropped: list[str] = []
    for w in caught:
        msg = str(w.message)
        if "donated buffers were not usable" in msg:
            dropped += re.findall(r"ShapedArray\(([^)]+)\)", msg) or [msg]
    # The alias field's nested braces defeat a simple field-isolating
    # regex; the entry pattern's literal "may-alias)" is unambiguous in
    # the whole module header, so match entries directly. The header is
    # everything before the first computation body.
    header = compiled.as_text().split("ENTRY", 1)[0]
    entries = _ALIAS_ENTRY_RE.findall(header)
    return {
        "n_aliased": len(entries),
        "aliased_params": sorted({int(p) for p, _ in entries}),
        "dropped": dropped,
    }


def donation_audit(jitted: Callable, *args, **kwargs) -> dict:
    """AOT-compile ``jitted(*args)`` and return its :func:`donation_report`.
    A real (cache-miss) XLA compile of the program — use at trace/startup
    time, not per step."""
    return donation_report(*aot_compile(jitted, *args, **kwargs))


def assert_donation(jitted: Callable, *args, min_aliased: int = 1,
                    allow_dropped: tuple[str, ...] = (), **kwargs) -> dict:
    """Fail loudly when an expected donation was dropped by XLA.

    Asserts the compiled program carries at least ``min_aliased``
    input→output buffer aliases AND that every dropped donation matches an
    ``allow_dropped`` prefix (e.g. ``("uint8", "int32")`` for the batch
    buffers, which have no same-shaped output to alias with but are still
    donated so the runtime frees them at dispatch). Returns the
    :func:`donation_audit` report on success; raises :class:`DonationError`
    otherwise. The CI smoke (tests/test_perf_pipeline.py) pins both
    failure modes on toy functions.
    """
    report = donation_audit(jitted, *args, **kwargs)
    unexpected = [d for d in report["dropped"]
                  if not any(d.startswith(p) for p in allow_dropped)]
    if unexpected:
        raise DonationError(
            f"XLA dropped donation for {unexpected} (aliased "
            f"{report['n_aliased']} buffers) — an expected in-place "
            f"update silently became a copy; see donation_audit()")
    if report["n_aliased"] < min_aliased:
        raise DonationError(
            f"expected >= {min_aliased} donated input→output aliases, "
            f"compiled program has {report['n_aliased']} — donation is "
            f"not set up (missing donate_argnums?)")
    return report


def demand_frac_of_peak(bytes_per_s: float | None,
                        peak_bytes_per_s: float | None
                        ) -> tuple[float | None, str | None]:
    """Demand-side bytes rate as a fraction of the physical HBM peak —
    or ``(None, reason)`` when the fraction exceeds 1.0: a demand
    estimate above the DMA ceiling is an op-level byte-accounting
    overcount (VMEM-reused values billed once per use — see
    :func:`bytes_accessed_of`), not a measurement, and publishing it as
    fact is how BENCH_r04's bogus ``hbm_frac_of_peak: 1.457`` happened.
    The single policy point for bench.py AND scripts/dmp_report.py, so
    the threshold and explanation cannot drift apart. The GB/s demand
    number stays honest as *demand*; only the roofline *position* is
    refused."""
    if not bytes_per_s or not peak_bytes_per_s:
        return None, None
    frac = bytes_per_s / peak_bytes_per_s
    if frac > 1.0:
        return None, (f"demand {bytes_per_s / 1e9:.0f} GB/s exceeds the "
                      f"{peak_bytes_per_s / 1e9:.0f} GB/s physical peak "
                      f"({frac:.2f}x): op-level byte accounting overcount, "
                      f"not a DMA rate — see benchmarks/run_step_profile.py "
                      f"for the measured-timeline roofline")
    return round(frac, 3), None


def bytes_accessed_of(ca: dict) -> float | None:
    """"bytes accessed" from a :func:`compiled_cost_analysis` dict.

    Same caveats as the flops count (scan bodies counted once, custom
    calls zero), plus one of its own: "bytes accessed" is the op-level
    sum over the optimized HLO — post-fusion, so fused producers don't
    round-trip HBM in the count, but values XLA keeps in registers/VMEM
    across ops still count once per use. Treat it as the demand-side
    estimate a bandwidth roofline needs, not a hardware counter — on the
    32px CNN step it EXCEEDS the HBM peak (bench_tpu.json), which is
    itself the proof the step is bandwidth-saturated."""
    val = ca.get("bytes accessed")
    return float(val) if val else None


def lm_model_flops(cfg, batch: int, seq: int, causal: bool = True) -> float:
    """Analytic model FLOPs (forward + backward) of one Transformer LM
    train step at ``batch`` sequences of ``seq`` tokens.

    XLA's cost analysis cannot produce this number for the real program
    (scan bodies counted once, pallas custom calls counted zero — see
    :func:`compiled_flops`), so MFU uses the standard analytic count:

    * dense matmuls: ``6 * N_mm * tokens`` where ``N_mm`` is the matmul
      parameter count touched per token (q/kv/o projections, MLP or the
      top-k routed expert slice plus router, LM head; embedding lookups
      and elementwise work excluded) — fwd ``2N`` + bwd ``4N``.
    * attention scores/values: fwd ``4*B*H*pairs*hd`` + bwd twice that,
      where ``pairs`` is the number of attended (q, k) positions —
      ``T*(T+1)/2`` causal, banded under a sliding window.
    * backward recompute (remat or the FA2 in-kernel score rebuild) is
      EXCLUDED: that work is implementation overhead, not model FLOPs
      (this is MFU, not HFU).
    """
    d, hd = cfg.d_model, cfg.head_dim
    H, kv = cfg.n_heads, cfg.kv_heads
    L, f, V = cfg.n_layers, cfg.d_ff, cfg.vocab_size
    attn_proj = d * H * hd + d * kv * 2 * hd + H * hd * d
    if cfg.moe_experts:
        mlp = cfg.moe_top_k * 2 * d * f + d * cfg.moe_experts
    else:
        mlp = 2 * d * f
    n_mm = L * (attn_proj + mlp) + d * V
    tokens = batch * seq
    dense = 6 * n_mm * tokens
    if cfg.attn_window is not None:
        w = min(cfg.attn_window, seq)
        # query i attends keys (i-w, i]: min(i+1, w) positions
        pairs = seq * w - w * (w - 1) // 2
    elif causal:
        pairs = seq * (seq + 1) // 2
    else:
        pairs = seq * seq
    attn = 12 * batch * H * pairs * hd * L
    return float(dense + attn)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/dmp_trace"):
    """Capture an XLA/TPU profiler trace for the enclosed region."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def fetch(out) -> None:
    """Force device→host transfer of one leaf of ``out`` (true sync point).

    Devices execute enqueued programs in order, so fetching the last
    program's output waits for everything before it too.
    """
    leaves = jax.tree.leaves(out)
    if leaves:
        np.asarray(leaves[-1])


def fetch_overhead() -> float:
    """Seconds for one device→host round trip of an already-computed value
    (pure transport latency; ~0 locally, tens of ms over a tunnel)."""
    a = jax.jit(lambda v: v + 1)(jax.numpy.zeros(()))
    b = jax.jit(lambda v: v + 2)(jax.numpy.zeros(()))
    fetch(a)   # waits for both trivial programs; warms the transport path
    t0 = time.perf_counter()
    fetch(b)   # executed but not host-cached: a pure round trip
    return time.perf_counter() - t0


def _warn_if_swamped(total: float, t_fetch: float, who: str) -> bool:
    """A timed loop shorter than the (single-sample) fetch round-trip means
    the measurement is noise — say so rather than report inflated numbers."""
    if total <= t_fetch:
        import sys
        print(f"[{who}] WARNING: timed loop ({total * 1e3:.1f} ms) <= fetch "
              f"round-trip ({t_fetch * 1e3:.1f} ms); measurement invalid — "
              f"raise iters or use a bigger workload", file=sys.stderr)
        return False
    return True


def time_fn_in_scan(fn: Callable, *args, iters: int = 20) -> float:
    """True device seconds per call of a pure array function.

    Runs ``iters`` calls inside ONE jitted ``lax.scan`` — no per-call
    dispatch at all — bracketed by a single host fetch. Use for kernel
    comparisons (e.g. attention implementations), where per-program
    dispatch overhead is not part of what's being measured; ``time_step``
    measures dispatched-call latency instead. The first argument must be a
    float array; a data dependency through the scan carry defeats CSE.
    Iteration count auto-scales (up to 16x) until the timed loop clearly
    exceeds the fetch round-trip, so fast kernels still measure validly
    over a high-latency transport.
    """
    first = args[0]

    def measure(n: int) -> tuple[float, float]:
        @jax.jit
        def run(first):
            def body(acc, _):
                out = fn(first + acc.astype(first.dtype) * 0, *args[1:])
                # Every output leaf must reach the carry — depending on just
                # one would let XLA dead-code-eliminate the computation of
                # the others (e.g. the dk/dv kernel of a multi-output
                # backward), timing only part of the work.
                dep = sum((jnp.sum(leaf) * 1e-20).astype(jnp.float32)
                          for leaf in jax.tree.leaves(out))
                return acc + dep, ()

            acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None,
                                  length=n)
            return acc

        fetch(run(first))          # compile + warm
        t_fetch = fetch_overhead()
        t0 = time.perf_counter()
        fetch(run(first))
        return time.perf_counter() - t0, t_fetch

    n = iters
    for attempt in range(3):
        total, t_fetch = measure(n)
        if total > 2 * t_fetch or attempt == 2:
            break
        n *= 4                     # too fast to resolve — lengthen the loop
    _warn_if_swamped(total, t_fetch, "time_fn_in_scan")
    return max(1e-9, total - t_fetch) / n


def time_step(fn: Callable, *args, warmup: int = 2, iters: int = 10,
              **kwargs) -> dict:
    """Steady-state per-call latency of a jitted callable (seconds).

    Times ``iters`` back-to-back calls bracketed by a single host fetch of
    the final output (see module docstring for why), then subtracts the
    measured fetch round-trip. Only aggregate keys are returned — per-call
    percentiles are unknowable under single-fetch timing, so none are
    fabricated.
    """
    out = None
    for _ in range(warmup):
        out = fn(*args, **kwargs)
    fetch(out)
    t_fetch = fetch_overhead()

    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kwargs)
    fetch(out)
    total = time.perf_counter() - t0
    # Floor: a noisy fetch-overhead sample larger than a fast timed loop
    # must not produce 0 (callers divide by this).
    valid = _warn_if_swamped(total, t_fetch, "time_step")
    per_call = max(1e-9, total - t_fetch) / iters
    return {
        "mean_s": per_call,
        "total_s": total,
        "fetch_overhead_s": t_fetch,
        "iters": iters,
        "valid": valid,
    }
